"""Observability plane (ISSUE 6): request-scoped tracing, the wedge
flight recorder, and structured JSON logging.

Dependency-free by contract (stdlib only — no jax, no numpy): the
queue/scheduler plane, the daemon, and the fabric transport all import
this package, and it must cost nothing but a dict append when nobody
is scraping. See docs/observability.md for the span taxonomy and the
flight-recorder format.
"""

from .flight import FlightRecorder, default_flight_dir
from .trace import Span, Tracer, get_tracer, scoped, set_tracer
from .xproc import ClockSync, SpanShip

__all__ = [
    "ClockSync",
    "FlightRecorder",
    "Span",
    "SpanShip",
    "Tracer",
    "default_flight_dir",
    "get_tracer",
    "scoped",
    "set_tracer",
]
