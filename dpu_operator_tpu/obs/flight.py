"""The wedge flight recorder: post-mortem without reproduction.

When the supervisor declares a replica wedged, a replica crashes, or
the breaker parks one, the interesting evidence is ALREADY in the
tracer's ring buffer — the fault firing, the last decode steps, the
watchdog detection, the seize/requeue chain. This module snapshots
that ring (plus the recent scheduler decisions and the drop counter)
to a JSON file at the moment of failure, so a chaos-run post-mortem
reads a timeline instead of re-rolling the dice. ``GET /debug/flight``
serves the same snapshot on demand without writing a file.

Snapshots are bounded like everything else on this plane: at most
``keep`` files survive per directory (oldest pruned), and a write
failure degrades to an in-memory snapshot with ``write_error`` set —
the recorder must never make a failing replica's day worse.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import time
from collections import deque
from datetime import datetime, timezone
from typing import Optional

from .trace import Tracer, get_tracer

log = logging.getLogger(__name__)

_seq = itertools.count(1)


def default_flight_dir() -> str:
    return os.environ.get(
        "DPU_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "dpu_flight"))


class FlightRecorder:
    def __init__(self, tracer: Optional[Tracer] = None,
                 flight_dir: Optional[str] = None, keep: int = 24,
                 registry=None, prefix: str = "flight",
                 max_spans: int = 2048, shard_tail: int = 128):
        self._tracer = tracer
        self.flight_dir = (flight_dir if flight_dir is not None
                           else default_flight_dir())
        self.keep = int(keep)
        self.registry = registry
        self.prefix = prefix
        # Snapshot the ring's TAIL, not the whole thing: the supervisor
        # writes synchronously at failure time, and a post-mortem wants
        # the recent history around the failure — dumping a full 16k
        # ring would make every replica death pay a multi-hundred-ms
        # serialization bill.
        self.max_spans = int(max_spans)
        # Per-rank span tail for the `shards` section (ISSUE 11): a
        # chaos post-mortem needs the victim rank's last moments even
        # when a busy coordinator flooded the main tail.
        self.shard_tail = int(shard_tail)

    @property
    def tracer(self) -> Tracer:
        # Resolved per snapshot, not per ctor: a test that installs a
        # scoped tracer AFTER building the server still records into
        # the active one.
        return self._tracer if self._tracer is not None else get_tracer()

    def snapshot(self, reason: str, extra: Optional[dict] = None,
                 write: bool = True) -> dict:
        tracer = self.tracer
        spans = tracer.spans_snapshot()
        # The `shards` section (ISSUE 11): every rank-attributed span
        # (shard.compute/reduce_blocked/encode, fabric.*, a rank-
        # stamped fault.fired) grouped per rank, tail-bounded PER RANK
        # and taken from the FULL snapshot before the main tail
        # truncates — a kill-one-shard post-mortem must show the
        # victim's fault firing and its peers' reduce stalls even when
        # the coordinator's own spans flooded the recent end. Foreign
        # spans arrive clock-aligned (Tracer.ingest shifted them) with
        # their offset+uncertainty stamped, so ordering claims across
        # the section carry their own error bars.
        shards: dict = {}
        for sp in spans:
            rank = sp.attrs.get("rank") if sp.attrs else None
            if rank is None:
                continue
            tail = shards.get(str(rank))
            if tail is None:
                # deque(maxlen): O(1) eviction, and to_dict() runs
                # only over the KEPT tail below — this is the
                # supervisor's synchronous failure path, where a
                # rank-heavy 16k ring must not pay dict
                # materialization for spans it immediately discards.
                tail = shards[str(rank)] = deque(
                    maxlen=self.shard_tail)
            tail.append(sp)
        shards = {rank: [sp.to_dict() for sp in tail]
                  for rank, tail in shards.items()}
        truncated = len(spans) - self.max_spans
        if truncated > 0:
            spans = spans[-self.max_spans:]
        data = {
            "reason": reason,
            "pid": os.getpid(),
            "wall_time": datetime.now(timezone.utc).isoformat(),
            # The monotonic anchor every span t0/t1 (and the fault
            # plan's fired_at) is relative to — the one shared axis.
            "monotonic": round(time.monotonic(), 6),
            "trace_dropped_total": tracer.dropped_total(),
            "spans_truncated": max(0, truncated),
            "spans": [sp.to_dict() for sp in spans],
            "decisions": tracer.decisions_snapshot(),
        }
        if shards:
            data["shards"] = shards
        if extra:
            data["extra"] = extra
        if self.registry is not None:
            self.registry.counter_inc(
                "serving_flight_snapshots_total", {"reason": reason},
                help="flight-recorder snapshots by trigger")
        if write:
            try:
                os.makedirs(self.flight_dir, exist_ok=True)
                name = (f"{self.prefix}-{reason}-{os.getpid()}"
                        f"-{next(_seq):05d}.json")
                path = os.path.join(self.flight_dir, name)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(data, f, indent=1, default=str)
                os.replace(tmp, path)
                data["path"] = path
                self._prune()
            except OSError as e:
                # Disk trouble must not escalate a replica failure into
                # a supervisor failure; the in-memory snapshot is still
                # returned to /debug/flight callers.
                log.warning("flight recorder: snapshot write failed: "
                            "%s", e)
                data["write_error"] = str(e)
        return data

    def _prune(self) -> None:
        try:
            entries = sorted(
                f for f in os.listdir(self.flight_dir)
                if f.startswith(self.prefix + "-")
                and f.endswith(".json"))
            for stale in entries[:-self.keep] if self.keep else entries:
                os.unlink(os.path.join(self.flight_dir, stale))
        except OSError:
            pass
