"""Request-scoped tracing for the serving plane and fabric (ISSUE 6).

The serving plane's aggregate histograms answer "how slow is p99"; they
cannot answer "where did THIS request's time go" — queue wait vs
pipelined hand-off vs device step vs seize/requeue/restart. This module
is the Dapper-shaped answer sized to this repo: a dependency-free
``Span``/``Tracer`` with monotonic-clock spans, explicit parent ids and
a bounded per-process ring buffer, threaded through the whole request
path (server → queue → batcher → executor seam → fabric transport →
supervisor) and scraped through ``GET /debug/traces?request_id=`` and
the flight recorder (obs/flight.py).

Always-on cheap is the design constraint, not a hope:

  * recording is LOCK-LIGHT — each thread appends completed spans to
    its own buffer (plain ``deque.append``, no lock on the hot path);
    the scraper drains every thread buffer into the central ring under
    the tracer lock. The only lock a recording thread ever takes is a
    one-time registration when it records its first span.
  * both the per-thread buffers and the central ring are BOUNDED, and
    every span that falls off either bound is COUNTED — the serving
    plane exports the total as ``serving_trace_dropped_total`` at
    scrape time, so the bound is proven, never hidden.
  * ``Tracer.enabled = False`` turns every record into a near-free
    no-op (one attribute read) — the knob bench_serving section 7 uses
    to price the traced-vs-untraced step rate (gated at <2%).

Span model: one ``Span`` per operation, ``parent_id`` for same-request
nesting (the HTTP handler's root span parents the queue/admit/retire
spans via ``GenerateRequest.trace_parent``), and a ``request_ids``
attr for spans that serve MANY requests at once (a decode step runs
every occupied slot) — the query surface attaches those to each
occupant's tree as linked children, Dapper's follows-from. Events are
zero-duration spans (``kind == "event"``).

Clock discipline: every timestamp is ``time.monotonic()`` — the same
clock the scheduler's deadlines and the fault plan's ``fired_at`` use,
so a flight-recorder timeline orders fault firing, watchdog detection
and recovery on one axis.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

# itertools.count.__next__ is atomic under the GIL: unique int ids with
# no lock on the record path (and no string formatting — ids stay ints
# all the way into the JSON).
_ids = itertools.count(1)


class Span:
    """One traced operation. ``t0``/``t1`` are time.monotonic seconds;
    ``kind`` is "span" (has duration) or "event" (t1 == t0). Span ids
    are process-unique ints.

    The HOT recording paths (record_span/event) never build these —
    they append a plain tuple to the thread buffer and drain()
    materializes Spans at scrape time, so the per-step cost in the
    decode loop is one tuple + one deque append."""

    __slots__ = ("name", "span_id", "parent_id", "request_id",
                 "kind", "t0", "t1", "attrs")

    def __init__(self, name: str, span_id: int,
                 parent_id: Optional[int], request_id: Optional[str],
                 t0: float, kind: str = "span",
                 attrs: Optional[dict] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.request_id = request_id
        self.kind = kind
        self.t0 = t0
        self.t1 = t0
        self.attrs = attrs if attrs is not None else {}

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "request_id": self.request_id,
            "kind": self.kind,
            "t0": round(self.t0, 6),
            "t1": round(self.t1, 6),
            "dur_ms": round((self.t1 - self.t0) * 1000.0, 3),
            "attrs": self.attrs,
        }

    def __repr__(self):
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, rid={self.request_id}, "
                f"{(self.t1 - self.t0) * 1000.0:.3f}ms)")


# Returned by start() when tracing is disabled: callers may set attrs /
# finish it without a branch of their own; nothing is ever recorded.
_NOOP = Span("noop", 0, None, None, 0.0)


def is_noop(span: Optional[Span]) -> bool:
    """True for the disabled-tracer placeholder — callers that stash a
    span id for cross-thread parenting must not stash this one."""
    return span is None or span is _NOOP


class _ThreadBuf:
    """One thread's outbound span buffer. The owner appends (right);
    the drainer pops (left) — both deque ends are thread-safe, so the
    hot path never takes a lock."""

    __slots__ = ("spans", "dropped", "thread")

    def __init__(self):
        self.spans: deque = deque()
        self.dropped = 0
        self.thread = threading.current_thread()


class Tracer:
    def __init__(self, capacity: int = 16384,
                 per_thread_cap: int = 4096,
                 decision_cap: int = 512):
        self.enabled = True
        self.capacity = int(capacity)
        self.per_thread_cap = int(per_thread_cap)
        self._local = threading.local()
        self._lock = threading.Lock()      # registry + ring, never hot
        self._bufs: List[_ThreadBuf] = []
        self._ring: deque = deque()
        self._ring_dropped = 0
        self._buf_dropped_collected = 0
        # Recent scheduler decisions (admit/shed/requeue/seize/restart/
        # breaker) — the flight recorder snapshots these next to the
        # span ring. deque(maxlen) appends are thread-safe.
        self._decisions: deque = deque(maxlen=int(decision_cap))

    # -- recording (hot path) -------------------------------------------------
    #
    # The thread buffer holds EITHER Span objects (the start/finish
    # context path — cold: request roots) or plain 8-tuples in Span
    # field order (record_span/event — the decode loop's per-step
    # path). drain() materializes tuples into Spans at scrape time, so
    # the hot path pays one id bump, one tuple and one deque append.

    def _buf(self) -> _ThreadBuf:
        try:
            return self._local.buf
        except AttributeError:
            buf = _ThreadBuf()
            self._local.buf = buf
            with self._lock:
                self._bufs.append(buf)
            return buf

    def _record(self, item) -> None:
        buf = self._buf()
        if len(buf.spans) >= self.per_thread_cap:
            buf.dropped += 1
            return
        buf.spans.append(item)

    def start(self, name: str, request_id: Optional[str] = None,
              parent_id: Optional[int] = None,
              attrs: Optional[dict] = None) -> Span:
        """Open a span (recorded only at finish()). With no explicit
        parent_id the innermost open ``span()`` context on THIS thread
        becomes the parent; cross-thread parenting is always explicit
        (that's what GenerateRequest.trace_parent carries)."""
        if not self.enabled:
            return _NOOP
        if parent_id is None:
            stack = getattr(self._local, "stack", None)
            if stack:
                parent_id = stack[-1]
        return Span(name, next(_ids), parent_id, request_id,
                    time.monotonic(), attrs=attrs)

    def finish(self, span: Span,
               attrs: Optional[dict] = None) -> None:
        if span is _NOOP:
            return
        span.t1 = time.monotonic()
        if attrs:
            span.attrs.update(attrs)
        self._record(span)

    @contextmanager
    def span(self, name: str, request_id: Optional[str] = None,
             parent_id: Optional[int] = None,
             attrs: Optional[dict] = None) -> Iterator[Span]:
        sp = self.start(name, request_id=request_id,
                        parent_id=parent_id, attrs=attrs)
        if sp is not _NOOP:
            stack = getattr(self._local, "stack", None)
            if stack is None:
                stack = self._local.stack = []
            stack.append(sp.span_id)
        try:
            yield sp
        finally:
            if sp is not _NOOP:
                self._local.stack.pop()
            self.finish(sp)

    def event(self, name: str, request_id: Optional[str] = None,
              parent_id: Optional[int] = None,
              attrs: Optional[dict] = None) -> Optional[int]:
        """Record a zero-duration span immediately; returns its id."""
        if not self.enabled:
            return None
        sid = next(_ids)
        t = time.monotonic()
        self._record((name, sid, parent_id, request_id, "event",
                      t, t, attrs))
        return sid

    def record_span(self, name: str, t0: float, t1: float,
                    request_id: Optional[str] = None,
                    parent_id: Optional[int] = None,
                    attrs: Optional[dict] = None,
                    span_id: Optional[int] = None) -> Optional[int]:
        """Record a completed span from timestamps the caller already
        measured (time.monotonic) — the scheduler's step segments come
        in this way, so tracing adds no clock calls of its own there.
        Returns the span id (for explicit child parenting).

        ``span_id`` takes a previously ``reserve_id()``-ed id: the
        cross-process pattern where an id must be SHIPPED to workers at
        submit time (they parent on it) while the span itself is only
        recordable at collect, when its duration exists."""
        if not self.enabled:
            return None
        sid = span_id if span_id is not None else next(_ids)
        self._record((name, sid, parent_id, request_id, "span",
                      t0, t1, attrs))
        return sid

    def reserve_id(self) -> int:
        """Allocate a span id with nothing recorded yet — the
        cross-process parent hand-off (see record_span's span_id)."""
        return next(_ids)

    def ingest(self, wire_spans, offset: float = 0.0,
               attrs: Optional[dict] = None) -> int:
        """Record another process's finished spans (obs.xproc wire
        lists: [name, sid, parent, rid, kind, t0, t1, attrs]).

        Foreign span ids live in the WORKER's counter and collide with
        local ids, so every shipped id is remapped to a fresh local
        one; parent links INSIDE the shipment follow the map, a parent
        id a shipment doesn't carry is dropped (its span was lost to
        the worker's bounded buffer — a dangling link must not alias a
        local span), and a parent in the COORDINATOR's id space rides
        ``attrs["xparent"]`` and passes through verbatim. Timestamps
        shift by ``-offset`` (offset = remote_clock - local_clock, the
        ClockSync estimate) onto the local monotonic axis; ``attrs``
        merge into every span (the offset/uncertainty stamp). Stays on
        the lock-light tuple path — ingest is a collect-leg cost.
        Returns the number of spans recorded."""
        if not self.enabled or not wire_spans:
            return 0
        idmap = {w[1]: next(_ids) for w in wire_spans}
        n = 0
        for name, sid, parent, rid, kind, t0, t1, sattrs in wire_spans:
            # The shipment's attr dicts are OWNED here (parsed off the
            # wire, shared with nobody) — mutated in place rather than
            # copied: ingest runs per rank per step on the collect leg.
            a = sattrs if sattrs is not None else {}
            xparent = a.pop("xparent", None)
            if parent is not None:
                parent = idmap.get(parent)
            if parent is None and xparent is not None:
                parent = xparent
            if attrs:
                a.update(attrs)
            self._record((name, idmap[sid], parent, rid, kind,
                          t0 - offset, t1 - offset, a))
            n += 1
        return n

    def decision(self, kind: str, **attrs) -> None:
        """Append one scheduler decision to the bounded decision log
        (flight-recorder context, not part of the span ring)."""
        if not self.enabled:
            return
        attrs["t"] = round(time.monotonic(), 6)
        attrs["kind"] = kind
        self._decisions.append(attrs)

    # -- scraping -------------------------------------------------------------

    def drain(self) -> None:
        """Move every thread buffer's spans into the central ring
        (oldest spans fall off the ring bound, counted), materializing
        the hot path's tuples into Span objects here — at scrape time,
        off every decode loop. Buffers of dead threads are pruned once
        empty."""
        with self._lock:
            live: List[_ThreadBuf] = []
            for buf in self._bufs:
                while True:
                    try:
                        item = buf.spans.popleft()
                    except IndexError:
                        break
                    if type(item) is tuple:
                        name, sid, parent, rid, kind, t0, t1, attrs = \
                            item
                        span = Span(name, sid, parent, rid, t0,
                                    kind=kind, attrs=attrs)
                        span.t1 = t1
                        item = span
                    self._ring.append(item)
                if buf.spans or buf.thread.is_alive():
                    live.append(buf)
                else:
                    # Dead and drained: fold its drop count into the
                    # collected total before letting it go.
                    self._buf_dropped_collected += buf.dropped
            self._bufs = live
            while len(self._ring) > self.capacity:
                self._ring.popleft()
                self._ring_dropped += 1

    def spans_snapshot(self) -> List[Span]:
        """Drained ring contents in start-time order (buffers from
        different threads interleave at drain; the flight recorder's
        tail must be the chronologically recent end)."""
        self.drain()
        with self._lock:
            return sorted(self._ring,
                          key=lambda s: (s.t0, s.span_id))

    def drain_take(self) -> List[Span]:
        """Drain AND consume as Span objects — the materializing
        convenience over drain_take_wire() (one consume
        implementation; this wrapper only shapes the result). Taken
        spans are not 'dropped' (they were delivered); the loss
        counters keep their meaning."""
        out = []
        for name, sid, parent, rid, kind, t0, t1, attrs in                 self.drain_take_wire():
            sp = Span(name, sid, parent, rid, t0, kind=kind,
                      attrs=attrs)
            sp.t1 = t1
            out.append(sp)
        return out

    def drain_take_wire(self) -> List[tuple]:
        """drain_take for the PER-STEP ship path: consume everything
        as wire-order tuples — (name, span_id, parent_id, request_id,
        kind, t0, t1, attrs), exactly the hot-path record format and
        exactly obs.xproc's wire layout — WITHOUT materializing Span
        objects that the next json.dumps would only take apart again.
        This runs once per worker step, so its cost is decode-loop
        overhead (priced by bench_serving section 10)."""
        with self._lock:
            out: List[tuple] = []
            live: List[_ThreadBuf] = []
            for buf in self._bufs:
                while True:
                    try:
                        item = buf.spans.popleft()
                    except IndexError:
                        break
                    if type(item) is tuple:
                        out.append(item)
                    else:
                        out.append((item.name, item.span_id,
                                    item.parent_id, item.request_id,
                                    item.kind, item.t0, item.t1,
                                    item.attrs))
                if buf.spans or buf.thread.is_alive():
                    live.append(buf)
                else:
                    self._buf_dropped_collected += buf.dropped
            self._bufs = live
            while True:
                try:
                    sp = self._ring.popleft()
                except IndexError:
                    break
                out.append((sp.name, sp.span_id, sp.parent_id,
                            sp.request_id, sp.kind, sp.t0, sp.t1,
                            sp.attrs))
        out.sort(key=lambda w: (w[5], w[1]))
        return out

    def dropped_total(self) -> int:
        """Monotonic count of spans lost to either bound (thread buffer
        overflow before a drain, or ring-capacity eviction). Drains
        first: every scrape-time reader then also moves spans off
        thread buffers and prunes dead threads' — without this, a
        server scraped only via /metrics (never /debug/*) would keep
        one _ThreadBuf per finished connection thread forever."""
        self.drain()
        with self._lock:
            return (self._ring_dropped + self._buf_dropped_collected
                    + sum(b.dropped for b in self._bufs))

    def decisions_snapshot(self) -> List[dict]:
        return list(self._decisions)

    def clear(self) -> None:
        """Drop all buffered spans and decisions (drop counters keep
        their totals — they are monotonic by contract)."""
        self.drain()
        with self._lock:
            self._ring.clear()
        self._decisions.clear()

    # -- query surface --------------------------------------------------------

    def request_spans(self, request_id: str) -> List[Span]:
        """Every span owned by the request (span.request_id) or linked
        to it (request_ids attr — shared spans like decode steps),
        PLUS the descendant closure of the linked set: a shard
        worker's ``shard.compute``/``shard.reduce_blocked`` spans
        carry no request id of their own — they parent on the
        coordinator's ``shard.step`` span, which carries the occupant
        list — so the tree walks down through parent links to pull
        them in (one snapshot; closure is bounded by tree depth)."""
        snapshot = self.spans_snapshot()
        out: List[Span] = []
        have: set = set()
        rest: List[Span] = []
        for sp in snapshot:
            linked = sp.attrs.get("request_ids") if sp.attrs else None
            if sp.request_id == request_id or (
                    linked and request_id in linked):
                out.append(sp)
                have.add(sp.span_id)
            else:
                rest.append(sp)
        changed = bool(have)
        while changed and rest:
            changed = False
            keep = []
            for sp in rest:
                if sp.parent_id in have:
                    out.append(sp)
                    have.add(sp.span_id)
                    changed = True
                else:
                    keep.append(sp)
            rest = keep
        return out

    def recent_requests(self, limit: int = 20) -> List[dict]:
        """The /debug/traces discoverability listing: the most
        recently active request ids still in the ring, newest first,
        each with its span count and activity window — the handles an
        operator who doesn't have an X-Request-Id in hand can start
        from."""
        info: Dict[str, dict] = {}
        for sp in self.spans_snapshot():
            rid = sp.request_id
            if rid is None:
                continue
            d = info.get(rid)
            if d is None:
                d = info[rid] = {"request_id": rid, "spans": 0,
                                 "t0": sp.t0, "t_last": sp.t1}
            d["spans"] += 1
            d["t0"] = min(d["t0"], sp.t0)
            d["t_last"] = max(d["t_last"], sp.t1)
        out = sorted(info.values(), key=lambda d: d["t_last"],
                     reverse=True)[:max(1, int(limit))]
        for d in out:
            d["t0"] = round(d["t0"], 6)
            d["t_last"] = round(d["t_last"], 6)
        return out

    def span_tree(self, request_id: str) -> dict:
        """JSON-ready span tree for one request: parent_id nesting
        where it exists; spans with no in-set parent (shared step
        spans, supervisor spans) attach under the request root as
        linked children, ordered by start time."""
        spans = sorted(self.request_spans(request_id),
                       key=lambda s: (s.t0, s.span_id))
        nodes: Dict[str, dict] = {}
        for sp in spans:
            node = sp.to_dict()
            node["children"] = []
            nodes[sp.span_id] = node
        roots: List[dict] = []
        for sp in spans:
            node = nodes[sp.span_id]
            parent = nodes.get(sp.parent_id) if sp.parent_id else None
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)
        # The handler's root request span adopts every parentless
        # linked span (decode steps, supervisor recovery) so the tree
        # reads as one timeline.
        req_root = next((n for n in roots
                         if n["name"] == "request"
                         and n["request_id"] == request_id), None)
        if req_root is not None:
            for n in roots:
                if n is not req_root:
                    n["linked"] = True
                    req_root["children"].append(n)
            req_root["children"].sort(key=lambda n: n["t0"])
            roots = [req_root]
        return {
            "request_id": request_id,
            "span_count": len(spans),
            "tree": roots,
        }


# -- process-global tracer -----------------------------------------------------
#
# Always installed (tracing is always-on by contract); faults.py and the
# fabric transport record here, and ServingServer defaults to it so a
# fault fired on a device-worker thread lands in the same timeline the
# flight recorder snapshots. Tests wanting isolation use scoped().

_tracer = Tracer()
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _tracer


def set_tracer(tracer: Tracer) -> Tracer:
    global _tracer
    with _tracer_lock:
        _tracer = tracer
        return _tracer


@contextmanager
def scoped(tracer: Optional[Tracer] = None) -> Iterator[Tracer]:
    """``with obs.trace.scoped() as tr:`` — install a fresh global
    tracer for a scope, always restore (a leaked tracer would bleed
    spans across tests)."""
    prev = get_tracer()
    t = set_tracer(tracer if tracer is not None else Tracer())
    try:
        yield t
    finally:
        set_tracer(prev)


def event(name: str, request_id: Optional[str] = None,
          parent_id: Optional[str] = None,
          attrs: Optional[dict] = None) -> Optional[Any]:
    """Module-level convenience over the global tracer (the faults
    seam's one-liner)."""
    return _tracer.event(name, request_id=request_id,
                         parent_id=parent_id, attrs=attrs)
