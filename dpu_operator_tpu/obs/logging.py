"""Structured logging unification: JSON lines with request context.

Library code across serving/ and daemon/ logs through stdlib
``logging`` — this module is the one place that decides what a log
LINE is: a single JSON object carrying ``ts``/``level``/``logger``/
``msg`` plus the request-scoped context (``request_id``, ``replica``,
``component``) that turns grep-by-request into a one-liner and gives
graftlint GL008 a mechanical target (request-path log calls must bind
request context — see docs/static-analysis.md).

Two ways context reaches a record, in precedence order:

  * ``extra={"request_id": ..., "replica": ...}`` on the call — the
    explicit form request-path code uses;
  * ``with obs.logging.context(replica="replica0"):`` — a thread-local
    binding the ``ContextFilter`` stamps onto every record the thread
    emits inside the scope (the batcher thread binds its replica once
    instead of repeating it at every call site).

``setup()`` installs the formatter+filter on the root logger — the
app-level entry points (daemon/main.py, serving __main__s) call it;
library modules just log.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
from contextlib import contextmanager
from datetime import datetime, timezone
from typing import Iterator, Optional

CONTEXT_FIELDS = ("request_id", "replica", "component", "rank")

_ctx = threading.local()


def bound_context() -> dict:
    return dict(getattr(_ctx, "fields", ()) or {})


@contextmanager
def context(**fields) -> Iterator[None]:
    """Bind context fields for every record this thread emits inside
    the scope; nests (inner bindings shadow, outer restored)."""
    prev = getattr(_ctx, "fields", None)
    merged = dict(prev or {})
    merged.update(fields)
    _ctx.fields = merged
    try:
        yield
    finally:
        _ctx.fields = prev


class ContextFilter(logging.Filter):
    """Stamp thread-local context onto records that don't already carry
    the field via ``extra=`` (explicit wins)."""

    def filter(self, record: logging.LogRecord) -> bool:
        bound = getattr(_ctx, "fields", None)
        if bound:
            for k, v in bound.items():
                if getattr(record, k, None) is None:
                    setattr(record, k, v)
        return True


class JsonLinesFormatter(logging.Formatter):
    """One JSON object per line; context fields included only when
    present (absent != empty — a replica-lifecycle line has no
    request_id and shouldn't pretend otherwise)."""

    def __init__(self, component: Optional[str] = None):
        super().__init__()
        self.component = component

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": datetime.fromtimestamp(
                record.created, timezone.utc).isoformat(),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if self.component is not None:
            out["component"] = self.component
        for k in CONTEXT_FIELDS:
            v = getattr(record, k, None)
            if v is not None:
                out[k] = v
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup(component: str, level: int = logging.INFO,
          stream=None) -> logging.Handler:
    """Install JSON-lines logging on the root logger (replacing any
    handler a previous setup() installed — idempotent for the daemon's
    restart-in-process tests). Returns the installed handler."""
    root = logging.getLogger()
    for h in list(root.handlers):
        if getattr(h, "_dpu_obs_handler", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonLinesFormatter(component=component))
    handler.addFilter(ContextFilter())
    handler._dpu_obs_handler = True
    root.addHandler(handler)
    root.setLevel(level)
    return handler
