"""Cross-process tracing: span shipping + clock alignment (ISSUE 11).

PR 6 built a process-local tracing plane; PR 8 moved the decode hot
path into shard subprocesses. This module is what lets a span tree
cross that boundary, Dapper-style, with ZERO extra protocol round
trips:

  * **context propagation** — the coordinator ships a ``trace_parent``
    span id inside the control frames it already sends (the framed-JSON
    step message, the fabric ``_HELLO``); workers parent their local
    spans on it. Old workers ignore the extra field.
  * **span shipping** — a worker buffers its finished spans in a
    bounded :class:`SpanShip` (losses counted, same tuple discipline as
    trace.py) and piggybacks the buffer onto the reply frames it
    already sends. The coordinator ingests them into its own tracer
    (``Tracer.ingest``) with remapped span ids.
  * **clock alignment** — every process stamps ``time.monotonic()``,
    and monotonic clocks do not share a zero across processes (they do
    on Linux, but the design must hold for pods on different hosts).
    :class:`ClockSync` estimates the per-worker offset from the
    request/reply timestamps the protocol already carries — the
    NTP/Cristian four-timestamp midpoint method — and every foreign
    span is shifted onto the coordinator's axis and STAMPED with the
    offset and its uncertainty, so "A happened before B" claims across
    processes are made only to the precision the estimate supports.

Like the rest of obs/, stdlib-only by contract (the shard worker and
the coordinator both import this; neither should pay a numpy import
for tracing).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Tuple

from .trace import Span

# What a worker ships by default: the shard-plane taxonomy, the ring
# rendezvous, and fault firings. Per-chunk fabric.send/recv spans stay
# worker-local by design — at wire speed they arrive thousands per
# second and would evict everything else out of the bounded ship
# buffer (an operator who wants them reads the worker's own log).
SHIP_PREFIXES = ("shard.",)
SHIP_NAMES = ("fabric.connect", "fault.fired")


def ship_default(name: str) -> bool:
    return name.startswith(SHIP_PREFIXES) or name in SHIP_NAMES


def wire_span(span: Span) -> list:
    """One finished span as a JSON-able list, field order matching the
    tracer's hot-path tuple: [name, span_id, parent_id, request_id,
    kind, t0, t1, attrs]. ``parent_id`` here is a LOCAL id (this
    process's counter); a parent living in the COORDINATOR's id space
    rides ``attrs["xparent"]`` instead — the two spaces collide
    numerically, so the wire format keeps them apart structurally."""
    return [span.name, span.span_id, span.parent_id, span.request_id,
            span.kind, round(span.t0, 6), round(span.t1, 6),
            span.attrs]


class SpanShip:
    """A worker's bounded outbound span buffer. ``harvest()`` empties
    the process tracer into it (filtered); ``flush()`` hands the
    accumulated wire spans to the caller assembling a reply frame.
    Spans that arrive while the buffer is at capacity are dropped and
    COUNTED — the coordinator re-exports the total, so piggyback loss
    under pressure is a visible number, never silence."""

    def __init__(self, cap: int = 512, ship=ship_default):
        self.cap = int(cap)
        self.ship = ship
        self._lock = threading.Lock()
        self._buf: deque = deque()
        self.dropped_total = 0

    def harvest(self, tracer) -> int:
        """Drain every finished span out of ``tracer`` (consuming its
        ring) and buffer the shippable ones. Returns how many were
        buffered. Rides the tracer's wire-tuple fast path — the
        hot-path record format IS the wire layout, so nothing is
        materialized per span on the way to the reply frame."""
        n = 0
        wires = tracer.drain_take_wire()
        with self._lock:
            for w in wires:
                if not self.ship(w[0]):
                    continue
                if len(self._buf) >= self.cap:
                    self.dropped_total += 1
                    continue
                self._buf.append(w)
                n += 1
        return n

    def flush(self) -> List[list]:
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out

    def __len__(self) -> int:
        return len(self._buf)


class ClockSync:
    """Per-peer monotonic clock offset from protocol round trips.

    Four timestamps per exchange, all ``time.monotonic()``: the
    coordinator sends at ``t_tx_local``, the worker receives the frame
    at ``t_rx_remote`` and replies at ``t_tx_remote``, the coordinator
    receives the reply at ``t_rx_local``. The midpoint estimate
    (NTP's) of ``offset = remote_clock - local_clock``:

        offset      = ((t_rx_remote - t_tx_local)
                       + (t_tx_remote - t_rx_local)) / 2
        uncertainty = ((t_rx_local - t_tx_local)
                       - (t_tx_remote - t_rx_remote)) / 2

    The uncertainty is HALF the un-accounted wire time: the true
    offset provably lies within ±uncertainty of the estimate under any
    split of that time between the two directions (asymmetric delay
    biases the midpoint but never past the bound). The step exchange's
    processing time sits between the remote stamps, so it never
    inflates the bound — only genuine queuing/wire time does.

    Samples are windowed (``window`` most recent, the "re-estimated
    per N steps" contract): the published estimate is the
    minimum-uncertainty sample still in the window, so a transient
    scheduling stall poisons at most ``window`` steps and a drifting
    clock cannot pin an ancient tight sample forever."""

    def __init__(self, window: int = 64):
        self.window = int(window)
        self._samples: deque = deque(maxlen=self.window)
        # Cached window minimum, maintained incrementally: estimate()
        # runs once per rank per step on the collect leg, and a
        # min-scan over the window there would be pure per-step
        # overhead (section 10 prices this path).
        self._best = None

    def observe(self, t_tx_local: float, t_rx_remote: float,
                t_tx_remote: float, t_rx_local: float) -> None:
        rtt_net = ((t_rx_local - t_tx_local)
                   - (t_tx_remote - t_rx_remote))
        if rtt_net < 0:
            # A reply cannot arrive before its request net of remote
            # processing: one of the stamps is garbage — skip.
            return
        offset = ((t_rx_remote - t_tx_local)
                  + (t_tx_remote - t_rx_local)) / 2.0
        sample = (rtt_net / 2.0, offset)
        evicted = (self._samples[0]
                   if len(self._samples) == self._samples.maxlen
                   else None)
        # deque(maxlen) append is the windowing AND the thread
        # discipline: an atomic container op, no RMW state.
        self._samples.append(sample)
        best = self._best
        if best is None or sample < best:
            self._best = sample
        elif evicted is not None and evicted == best:
            # The cached minimum just aged out: one rescan, amortized
            # over the window length.
            self._best = min(self._samples)

    @property
    def ready(self) -> bool:
        return bool(self._samples)

    @property
    def estimate(self) -> Tuple[float, float]:
        """(offset, uncertainty); (0.0, inf) before any sample — a
        caller aligning spans with no estimate must say so loudly."""
        if self._best is None:
            return 0.0, float("inf")
        unc, off = self._best
        return off, unc

    def to_local(self, t_remote: float) -> float:
        off, _unc = self.estimate
        return t_remote - off


def federate_labels(rank, codec: str, replica: str) -> Dict[str, str]:
    """The label set every re-exported worker series carries: a
    quantized replica's series must never aggregate with an fp32
    one's, and per-rank resolution is the whole point."""
    return {"rank": str(rank), "codec": codec, "replica": replica}
