"""Continuous batching: admit/retire at STEP boundaries, not batch ones.

The classic serving mistake is static batching — collect B requests,
run all their tokens, return, repeat — which makes every request wait
for the slowest member of its batch and leaves slots idle as members
finish early. Continuous batching (Orca, OSDI '22) re-forms the batch
every model step: a request occupies one SLOT, each step decodes one
token for every occupied slot, finished requests free their slot at
the step boundary and queued requests are admitted into free slots
before the next step. Occupancy tracks offered load step by step;
nobody waits for a stranger's tail.

The executor's batch shape is FIXED at [slots, d] (idle slots carry
zeros) so the jitted forward compiles once — occupancy varies, shapes
don't. One batcher per replica, one thread per batcher; the shared
AdmissionQueue is the only cross-replica coupling.

Two loop shapes (picked off `executor.pipelined`):

  * sync — the PR 2 loop: step(x) blocks, then retire/admit run while
    the device idles. Kept as the fallback for step()-only executors
    and as the measured baseline.
  * pipelined — the ISSUE 3 loop: submit step k (async dispatch), THEN
    retire step k-1's tokens and admit for step k+1 while the device
    runs k. Host bookkeeping hides behind device time; the device
    never waits for python. The semantic delta, by construction: a
    slot freed by step k-1's retire is admitted at step k+1, one step
    later than the sync loop would (submit(k) precedes retire(k-1)),
    and each slot hand-off decodes one stale step nobody reads. Token
    STREAMS are identical to the sync loop — rows decode
    independently, so a later admission shifts when tokens are
    computed, never what they are.

Step-time decomposition (per replica, both loops):
`serving_step_device_seconds` is time blocked on the device (sync:
step() wall; pipelined: collect() wall — the device time host work
did NOT hide); `serving_host_gap_seconds` is host bookkeeping between
observing one step's completion and dispatching the next — the window
the device sits idle in the sync loop, and the budget that must stay
under device step time for full overlap in the pipelined loop.
`serving_step_seconds` keeps its PR 2 series as the blocked-time
back-compat alias.

Failure policy is two-mode. Standalone batchers keep the legacy shape
(an executor failure 500s the current occupants and the loop keeps
running). Under a supervising ReplicaPool the batcher is CRASH-ONLY:
the failure exits the loop with the occupants left in their slots and
the supervisor seizes them (under this batcher's settle lock, so
nothing is ever settled twice), re-admits them to the shared queue and
restarts the replica. `blocked_since` is the watchdog hook: published
while the thread is blocked on the device, it lets the supervisor
detect a wedged step no in-thread timeout could ever fire on.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from ..obs import logging as obs_logging
from ..obs import trace as obs_trace
from .api import KV_OOM_ERROR, GenerateRequest
from .kvcache.allocator import KVCacheOOM
from .spec import token_run

log = logging.getLogger(__name__)

# Decode loops run 10^2..10^4 steps/s; the default request-latency
# buckets start two decades too high to resolve them.
_STEP_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                 0.05, 0.1, 0.25, 1.0)
_OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


class ContinuousBatcher:
    def __init__(self, executor, queue, registry=None,
                 replica: str = "replica0", idle_wait_s: float = 0.05,
                 pipelined: Optional[bool] = None,
                 crash_only: bool = False, tracer=None,
                 handoff=None):
        self.executor = executor
        self.queue = queue
        self.registry = registry
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        self.replica = replica
        self.idle_wait_s = idle_wait_s
        self.pipelined = (bool(executor.pipelined) if pipelined is None
                          else bool(pipelined))
        # Paged-KV executors (serving/kvcache) speak tokens, not
        # [slots, d] rows: admission binds a block-table lease and the
        # loop is _run_kv (chunked prefill + NO_TOKEN-aware retire).
        self.kv_mode = bool(getattr(executor, "kv", False))
        if (getattr(executor, "speculative", False) and self.pipelined
                and not bool(executor.pipelined)):
            # Speculation rides BOTH loop shapes since ISSUE 18, but
            # the plan-ahead discipline (draft from proposed tokens,
            # epoch-gated rollback) lives in the EXECUTOR — it must
            # have been built pipelined. Overriding a sync-built
            # speculative executor into the pipelined loop would plan
            # verify windows from stale last_token cursors (collect
            # has not run yet) and silently fork the stream.
            raise ValueError(
                "speculative executor was built for the sync loop "
                "shape; pipelined=True override is invalid (build it "
                "with pipelined speculation instead)")
        # Role hand-off (serving/disagg): when set, this batcher is a
        # PREFILL replica — a request that emits a token and is not
        # finished leaves its slot through kv_detach_slot and
        # handoff(req, detach) instead of decoding here. Called UNDER
        # the settle lock, so it must only enqueue (the transfer
        # plane's worker does the export/stream off-thread). KV-only:
        # the row plane has no transferable state.
        if handoff is not None and not self.kv_mode:
            raise ValueError("handoff requires a paged-KV executor")
        self.handoff = handoff
        # crash_only (Candea & Fox): an executor failure EXITS the loop
        # with the occupants left in their slots and the error on
        # self.failure — the supervisor (ReplicaPool) seizes, requeues
        # and restarts. Standalone batchers keep the legacy policy
        # (fail the current occupants, keep looping).
        self.crash_only = crash_only
        self.failure: Optional[BaseException] = None
        # monotonic timestamp published while the thread is blocked on
        # the device (step()/collect()) — the supervisor's watchdog
        # reads it to catch a wedged device step the loop itself can
        # never time out of.
        self.blocked_since: Optional[float] = None
        # Serializes settle/pop bookkeeping against a supervisor
        # seize(): once _abandoned flips under this lock, the loop will
        # never settle a request or pop the queue again — the no-
        # double-settle guarantee re-admission depends on.
        self._settle_lock = threading.Lock()
        self._abandoned = False
        self._slots: List[Optional[GenerateRequest]] = (
            [None] * executor.slots)
        self._x = np.zeros((executor.slots, executor.d), np.float32)
        self._zero_row = np.zeros(executor.d, np.float32)
        self._dirty: set = set()  # freed slots with stale device rows
        self._prezeroed: set = set()  # zeroed ahead of their retire
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.steps = 0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"batcher-{self.replica}")
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        # Under the settle lock with _abandoned flipped: a thread that
        # outlived the join timeout (wedged in the executor) must not
        # settle anything after we fail its occupants here.
        with self._settle_lock:
            self._abandoned = True
            for i, req in enumerate(self._slots):
                if req is not None:
                    req.fail("server stopped")
                    self._slots[i] = None

    @property
    def thread_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def stopping(self) -> bool:
        return self._stop.is_set()

    def seize(self) -> List[GenerateRequest]:
        """Supervisor-side takeover of a dead or wedged replica's
        in-flight requests. Taking the settle lock first means an
        in-progress retire completes before ownership moves; after
        _abandoned flips, the batcher thread (should it ever wake from
        a wedge) exits without settling or popping anything — each
        seized request has exactly one owner: the caller."""
        self._stop.set()
        got = self._settle_lock.acquire(timeout=5.0)
        try:
            self._abandoned = True
            occ = [r for r in self._slots if r is not None]
            self._slots = [None] * len(self._slots)
            return occ
        finally:
            if got:
                self._settle_lock.release()

    @property
    def active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    # -- metrics helpers ------------------------------------------------------

    def _observe(self, name: str, value: float, help: str = "",
                 buckets=None) -> None:
        if self.registry is not None:
            self.registry.observe(name, value, {"replica": self.replica},
                                  help=help, buckets=buckets)

    def _count(self, name: str, labels: dict, help: str = "",
               by: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, labels, by=by, help=help)

    def _observe_step(self, blocked_s: float, n_active: int) -> None:
        self._observe("serving_step_device_seconds", blocked_s,
                      help="wall time blocked on the device per step "
                           "(device time not hidden by host work)",
                      buckets=_STEP_BUCKETS)
        self._observe("serving_step_seconds", blocked_s,
                      help="model step wall time")
        self._observe("serving_batch_occupancy",
                      n_active / self.executor.slots,
                      help="occupied fraction of batch slots",
                      buckets=_OCCUPANCY_BUCKETS)

    def _observe_gap(self, gap_s: float) -> None:
        self._observe("serving_host_gap_seconds", gap_s,
                      help="host bookkeeping between observing a step's "
                           "completion and dispatching the next",
                      buckets=_STEP_BUCKETS)

    # -- admission ------------------------------------------------------------

    def _pop_admissions(self, block: bool
                        ) -> List[Tuple[int, GenerateRequest,
                                        np.ndarray]]:
        """Pop up to len(free slots) requests and place each in a slot;
        returns [(slot, request, prompt_row)] for successful
        placements. The slot index binds BEFORE the guarded region: a
        failure inside it must report the real error against a known
        slot (the old `i = free.pop(0)` inside the try raised
        NameError('i') in its own handler, masking the actual failure
        and leaking the queue's inflight count)."""
        free = [i for i, r in enumerate(self._slots) if r is None]
        if not free:
            return []
        # Block only when fully idle: a running batch polls (timeout 0)
        # so decode steps are never held hostage to admission.
        timeout = self.idle_wait_s if block else 0.0
        placed: List[Tuple[int, GenerateRequest, np.ndarray]] = []
        for req in self.queue.get_many(len(free), timeout=timeout):
            i = free.pop(0)
            try:
                kv_cached = None
                if self.kv_mode:
                    # Bind (or re-attach) the request's KV lease: the
                    # executor reserves its worst-case pages here, so
                    # OOM is an admission decision, never a mid-decode
                    # failure.
                    vec = None
                    kv_cached = self.executor.kv_attach(i, req)
                else:
                    vec = np.asarray(req.prompt_vec, np.float32)
                    if vec.shape != (self.executor.d,):
                        raise ValueError(
                            f"prompt_vec shape {vec.shape} != "
                            f"({self.executor.d},)")
                req.admitted_at = time.monotonic()
                self._slots[i] = req
                placed.append((i, req, vec))
                if self.tracer.enabled:
                    # `lands_at_step` is the step whose scatter applies
                    # the row — in the pipelined loop that is by
                    # construction one step after the retire that freed
                    # the slot (the ISSUE 3 hand-off, visible in the
                    # trace instead of only in a docstring).
                    attrs = {"replica": self.replica, "slot": i,
                             "lands_at_step": self.steps + 1,
                             "pipelined": self.pipelined}
                    if kv_cached is not None:
                        attrs["kv_cached_tokens"] = kv_cached
                    self.tracer.event(
                        "batcher.admit", request_id=req.request_id,
                        parent_id=req.trace_parent, attrs=attrs)
                    self.tracer.decision(
                        "admit", request_id=req.request_id,
                        replica=self.replica, slot=i)
            except KVCacheOOM as e:
                # Capacity shed, not a replica failure: pages free as
                # in-flight work finishes, so the HTTP layer answers
                # 503 + Retry-After (KV_OOM_ERROR matched exactly).
                log.warning("batcher %s: kv admission shed "
                            "(request %s): %s", self.replica,
                            req.request_id, e)
                req.fail(KV_OOM_ERROR)
                self._count("serving_kv_admission_shed_total",
                            {"replica": self.replica},
                            help="requests shed at admission because "
                                 "the KV allocator had no pages")
                self.tracer.decision("shed_kv_oom",
                                     request_id=req.request_id,
                                     replica=self.replica)
            except Exception as e:
                # A request popped from the queue has exactly one owner
                # now — losing it here would park its handler thread
                # for the full deadline.
                log.exception("batcher %s: admit failed (request %s)",
                              self.replica, req.request_id)
                if self._slots[i] is req:
                    self._slots[i] = None
                if self.kv_mode:
                    # kv_attach may have bound the slot before a later
                    # admit statement raised; leaving it bound poisons
                    # the slot ("already bound" for every future admit)
                    # and keeps planning decode for a ghost state.
                    # No-op when nothing is bound; lease release is
                    # idempotent against fail()'s finish hook.
                    self.executor.kv_release_slot(i, cache=False)
                req.fail(f"admission failed: {e}")
            finally:
                # In a slot (or failed) — no longer "in flight between
                # queue and slot" for the drain quiesce accounting.
                self.queue.mark_placed(1)
        return placed

    def _maybe_preempt_kv(self) -> None:
        """QoS preemption (ISSUE 20), called under the settle lock
        right before admissions: when every slot is occupied and an
        INTERACTIVE request is waiting, park the coldest batch-class
        occupant (fewest settled tokens — the least work at stake)
        through ``kv_preempt_slot`` and requeue it at the front of its
        own class. Preemption is policy, not failure: the victim's
        ``attempts`` budget is untouched, its ``preemptions`` counter
        ticks, and its KV rides the requeue as a ParkedKV (or a
        reattached lease when nothing was parkable), so resume replays
        strictly less than a re-decode. One victim per loop iteration —
        the freed slot admits in the SAME _pop_admissions call, and the
        next iteration re-evaluates with fresh queue state."""
        if not self.kv_mode:
            return
        waiting = getattr(self.queue, "waiting", None)
        if waiting is None or waiting("interactive") <= 0:
            return
        if any(r is None for r in self._slots):
            return
        victims = [(len(r.tokens), i, r)
                   for i, r in enumerate(self._slots)
                   if r is not None and not r.done
                   and getattr(r, "priority", "interactive") == "batch"]
        if not victims:
            return
        _, i, victim = min(victims, key=lambda v: (v[0], v[1]))
        try:
            res = self.executor.kv_preempt_slot(i, victim)
        except Exception:
            if self.crash_only:
                raise
            # Park failed (tier fault): the victim is still BOUND and
            # still decoding — skip preemption this round rather than
            # turning a QoS decision into a request failure.
            log.exception("batcher %s: preempt park failed "
                          "(request %s)", self.replica,
                          victim.request_id)
            return
        self._slots[i] = None
        if res is None:
            # Settled concurrently: the slot freed through the choke
            # point, nothing to requeue.
            return
        victim.preemptions += 1
        self._count("serving_preempted_total",
                    {"replica": self.replica},
                    help="batch-class occupants preempted for an "
                         "interactive arrival (KV parked, requeued)")
        self.tracer.event(
            "batcher.preempt", request_id=victim.request_id,
            parent_id=victim.trace_parent,
            attrs={"replica": self.replica, "slot": i,
                   "tokens": len(victim.tokens),
                   "parked_blocks": res.get("parked_blocks", 0),
                   "preemptions": victim.preemptions})
        self.tracer.decision("preempt", request_id=victim.request_id,
                             replica=self.replica, slot=i)
        self.queue.requeue(victim, preempted=True)

    # -- sync loop (fallback + measured baseline) -----------------------------

    def _settle(self, req: GenerateRequest, token: int,
                now: float) -> bool:
        """Append one decoded token and finish the request if its
        budget or deadline says so; True when it leaves its slot. THE
        retire bookkeeping, shared by both loops — sync and pipelined
        request outcomes must never diverge (the token-stream
        equivalence contract)."""
        req.tokens.append(int(token))
        if req.first_token_at is None:
            req.first_token_at = now
        finished = len(req.tokens) >= req.max_tokens
        if not finished and now >= req.deadline:
            # Deadline mid-decode: return what exists, marked, at the
            # boundary — p99 for admitted work stays bounded by
            # deadline + one step, never by another request's tail.
            req.truncated = True
            finished = True
        if finished:
            self._count("serving_tokens_total",
                        {"replica": self.replica},
                        by=float(len(req.tokens)),
                        help="decoded tokens")
            req.finish()
            self.tracer.event(
                "batcher.retire", request_id=req.request_id,
                parent_id=req.trace_parent,
                attrs={"replica": self.replica,
                       "tokens": len(req.tokens),
                       "truncated": req.truncated})
        return finished

    def _admit(self) -> None:
        for i, _req, vec in self._pop_admissions(block=self.active == 0):
            self._x[i] = vec

    def _retire(self, y: np.ndarray, tokens: np.ndarray) -> None:
        """Step-boundary bookkeeping. `tokens` is ONE batched argmax
        over all slots (the per-row np.argmax python loop costs real
        time at decode step rates)."""
        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.done:
                # Abandoned by the handler (wait timeout → 500): evict
                # rather than decode to max_tokens for nobody — zombie
                # slots are capacity loss exactly when capacity is short.
                self._slots[i] = None
                self._x[i] = 0.0
                continue
            if self._settle(req, tokens[i], now):
                self._slots[i] = None
                self._x[i] = 0.0
            else:
                self._x[i] = y[i]  # decode recurrence: output is next state

    def _run_sync(self) -> None:
        if self.crash_only:
            # A restarted replica must not inherit poisoned state from
            # the incarnation the supervisor just tore down. Under the
            # watchdog clock: a reset that serializes behind a still-
            # hung device step would otherwise block HERE invisibly,
            # recreating the exact wedge the supervisor just detected
            # while reporting the replica live.
            self.blocked_since = time.monotonic()
            self.executor.reset()
            self.blocked_since = None
        t_gap_start = None
        while not self._stop.is_set():
            # crash_only: any failure exits the loop with the slots
            # intact — the supervisor requeues and restarts. Legacy
            # (standalone) policy: the failure costs at most the
            # CURRENT occupants, never the thread.
            try:
                with self._settle_lock:
                    if self._abandoned:
                        return
                    if self.active == 0:
                        # Drained before the (possibly blocking) admit:
                        # queue-idle wait must not masquerade as host
                        # gap.
                        t_gap_start = None
                    self._admit()
                    n_active = self.active
                if n_active == 0:
                    t_gap_start = None
                    continue
                # One clock (time.monotonic) for metrics AND spans so
                # the step segments share the axis every other span —
                # and the fault plan's fired_at — lives on.
                traced = self.tracer.enabled
                rids = ([r.request_id for r in self._slots
                         if r is not None] if traced else None)
                t0 = time.monotonic()
                if t_gap_start is not None:
                    self._observe_gap(t0 - t_gap_start)
                    if traced:
                        self.tracer.record_span(
                            "step.host", t_gap_start, t0,
                            attrs={"replica": self.replica,
                                   "step": self.steps + 1,
                                   "mode": "sync",
                                   "request_ids": rids})
                self.blocked_since = t0
                y = np.asarray(self.executor.step(self._x), np.float32)
                self.blocked_since = None
                t1 = time.monotonic()
                t_gap_start = t1
                self.steps += 1
                self._observe_step(t1 - t0, n_active)
                if traced:
                    self.tracer.record_span(
                        "step.device", t0, t1,
                        attrs={"replica": self.replica,
                               "step": self.steps, "mode": "sync",
                               "n_active": n_active,
                               "request_ids": rids})
                with self._settle_lock:
                    if self._abandoned:
                        return
                    self._retire(y, y.argmax(axis=1))
            except Exception as e:
                self.blocked_since = None
                if self.crash_only:
                    raise
                log.exception("batcher %s: step failed", self.replica)
                self._fail_occupants(e)
                t_gap_start = None

    # -- pipelined loop (device-resident executors) ---------------------------

    def _retire_tokens(self, tokens: np.ndarray,
                       snapshot: List[Optional[GenerateRequest]]) -> None:
        """Retire against the slot SNAPSHOT taken at that step's
        submit: by retire time self._slots may already hold newer
        occupants (admissions run before collect). Freed slots join
        _dirty — their device rows are stale until the next submit
        zeroes them (or an admission overwrites them)."""
        now = time.monotonic()
        for i, req in enumerate(snapshot):
            if req is None:
                continue
            if req.done:
                # Finished or abandoned at an earlier boundary; this
                # step ran its slot for nobody (the one-step pipeline
                # cost). Free the slot only if still ours.
                if self._slots[i] is req:
                    self._free_slot(i)
                continue
            if self._settle(req, tokens[i], now) and self._slots[i] is req:
                self._free_slot(i)

    def _free_slot(self, i: int) -> None:
        """Release slot i at retire. Rows zeroed AHEAD of their retire
        (in the submit that overlapped it) are already clean on device;
        everything else carries stale state until the next scatter."""
        self._slots[i] = None
        if i in self._prezeroed:
            self._prezeroed.discard(i)
        else:
            self._dirty.add(i)

    def _zero_ahead(self, updates: list, snap_prev) -> None:
        """Zero rows whose occupant is certain to leave at the PENDING
        retire, in the scatter of the step being submitted. Without
        this, the hand-off step would run the finished request's stale
        nonzero row: content-derived row masking (infer.py's
        `any(x != 0)`) would count it active, and on an ep-sharded mesh
        under capacity pressure a ghost competitor can evict a real
        row's MoE dispatch — a divergence the sync loop never exhibits.
        Completion is predictable exactly for the max_tokens path
        (len + the pending token >= budget) and for already-abandoned
        requests; deadline truncation is timing-dependent and keeps its
        one stale step."""
        for i, req in enumerate(self._slots):
            if (req is not None and snap_prev[i] is req
                    and (req.done
                         or len(req.tokens) + 1 >= req.max_tokens)):
                updates.append((i, self._zero_row))
                self._prezeroed.add(i)

    def _run_pipelined(self) -> None:
        ex = self.executor
        # Under the watchdog clock (see _run_sync): on a restart after
        # a WEDGE, this reset can serialize behind the still-hung step
        # on the device/worker — blocked_since keeps the supervisor's
        # deadline on it, so a reset that never returns parks the
        # replica through the breaker instead of wedging it invisibly
        # in a state the pool reports as live.
        self.blocked_since = time.monotonic()
        ex.reset()
        self.blocked_since = None
        self._dirty.clear()
        self._prezeroed.clear()
        # (handle, slot snapshot, step no, occupant rids) in flight.
        # The rids list is computed ONCE per submitted step and shared
        # by every span that names the step's occupants — the tracing
        # budget is a handful of µs/step and list comprehensions over
        # the slots are the first thing to amortize.
        prev = None
        t_gap_start = None
        while not self._stop.is_set():
            try:
                submitted = None
                snapshot = None
                admit_rids: List[str] = []
                # Admission bookkeeping runs under the settle lock: a
                # supervisor seize() serializes against it, so an
                # abandoned batcher can never pop the queue again.
                with self._settle_lock:
                    if self._abandoned:
                        return
                    # Admit for step k+1 (block only when nothing is
                    # active AND nothing is in flight — a pending
                    # collect must not wait out the idle timeout behind
                    # an empty queue).
                    block = self.active == 0 and prev is None
                    updates = []
                    for i, req, vec in self._pop_admissions(block=block):
                        # Admission overwrites the row, whatever its
                        # state.
                        self._dirty.discard(i)
                        self._prezeroed.discard(i)
                        updates.append((i, vec))
                        admit_rids.append(req.request_id)
                    if self.active > 0:
                        # Freed-but-unadmitted slots get explicit zero
                        # rows: idle slots must be EXACTLY zero (the MoE
                        # row-mask contract) and must not keep decoding
                        # garbage.
                        for i in sorted(self._dirty):
                            updates.append((i, self._zero_row))
                        self._dirty.clear()
                        if prev is not None:
                            self._zero_ahead(updates, prev[1])
                        snapshot = list(self._slots)
                if snapshot is not None:
                    # Dispatch OUTSIDE the settle lock, under the
                    # watchdog clock: a submit that blocks (a wedged
                    # device can stall dispatch, not just completion)
                    # must be seizable — held across the lock it would
                    # deadlock stop()/seize() AND hide from the
                    # watchdog. A seize landing between the lock and
                    # this dispatch only wastes one step: the retire
                    # path re-checks _abandoned before settling.
                    traced = self.tracer.enabled
                    cur_rids = ([r.request_id for r in snapshot
                                 if r is not None] if traced else None)
                    ts0 = time.monotonic()
                    if t_gap_start is not None:
                        self._observe_gap(ts0 - t_gap_start)
                        if traced:
                            self.tracer.record_span(
                                "step.host", t_gap_start, ts0,
                                attrs={"replica": self.replica,
                                       "step": self.steps + 1,
                                       "mode": "pipelined",
                                       "request_ids": cur_rids})
                    self.blocked_since = ts0
                    # step/request_ids are diagnostic context: an
                    # update-overflow ValueError out of the device
                    # step must name the step and the admitting
                    # requests (the seize path can race admissions
                    # close to the slot limit).
                    # occupants is trace-only context: a sharded
                    # executor stamps it on its shard.step span so
                    # the worker-side subtree links into every
                    # occupant's /debug/traces tree (ISSUE 11).
                    handle = ex.submit(updates, step=self.steps + 1,
                                       request_ids=admit_rids or None,
                                       occupants=cur_rids)
                    self.blocked_since = None
                    self.steps += 1
                    if traced:
                        # `admits_landing` marks the ISSUE 3 hand-off:
                        # these rows were freed at step k-1's retire
                        # and land in step k+1's scatter — one step
                        # later than the sync loop, by construction.
                        self.tracer.record_span(
                            "executor.submit", ts0, time.monotonic(),
                            attrs={"replica": self.replica,
                                   "step": self.steps,
                                   "n_updates": len(updates),
                                   "admits_landing": admit_rids or None,
                                   "request_ids": cur_rids})
                    submitted = (handle, snapshot, self.steps, cur_rids)
                # Step k runs on the device while the host settles step
                # k-1: collect its token ids and do retire bookkeeping.
                # collect() is the one place a wedged device parks this
                # thread forever, so it runs OUTSIDE the settle lock
                # with blocked_since published — the supervisor's
                # watchdog can both see the wedge and seize around it.
                if prev is not None:
                    h_prev, snap_prev, step_prev, prev_rids = prev
                    tc = time.monotonic()
                    self.blocked_since = tc
                    tokens = ex.collect(h_prev)
                    self.blocked_since = None
                    t_done = time.monotonic()
                    n_prev = sum(1 for r in snap_prev if r is not None)
                    self._observe_step(t_done - tc, n_prev)
                    if self.tracer.enabled and prev_rids is not None:
                        dev = self.tracer.record_span(
                            "step.device", tc, t_done,
                            attrs={"replica": self.replica,
                                   "step": step_prev,
                                   "mode": "pipelined",
                                   "n_active": n_prev,
                                   "request_ids": prev_rids})
                        self.tracer.record_span(
                            "executor.collect", tc, t_done,
                            parent_id=dev,
                            attrs={"replica": self.replica,
                                   "step": step_prev,
                                   "request_ids": prev_rids})
                    with self._settle_lock:
                        if self._abandoned:
                            return
                        self._retire_tokens(tokens, snap_prev)
                    # Gap clock starts at device completion so retire
                    # bookkeeping counts toward the host gap it is.
                    t_gap_start = t_done
                if submitted is None:
                    t_gap_start = None  # pipeline drained: idle queue
                    # waits must not masquerade as host gap
                prev = submitted
            except Exception as e:
                self.blocked_since = None
                if self.crash_only:
                    raise
                log.exception("batcher %s: step failed", self.replica)
                self._fail_occupants(e)
                prev = None
                self._dirty.clear()
                self._prezeroed.clear()
                t_gap_start = None
                try:
                    ex.reset()  # drop poisoned device state
                except Exception:
                    log.exception("batcher %s: executor reset failed",
                                  self.replica)

    # -- paged-KV loop (ISSUE 7: token-level executors) ------------------------

    def _retire_kv(self, tokens, snapshot) -> None:
        """KV-aware retire against the submit-time snapshot. NO_TOKEN
        (-1) marks a slot whose step emitted nothing — a mid-prefill
        chunk (the request stays, its prompt still filling under the
        chunk budget) or a stale post-seize handle. A speculative
        executor's collect returns [slots, chunk] ACCEPTED RUNS
        instead of [slots] single tokens (ISSUE 15); both shapes
        normalize through spec.token_run and the per-request checks
        move to PER-ACCEPTED-TOKEN — a slot may finish mid-run
        (max_tokens reached, or the deadline lapsed after an earlier
        token of the same run), and tokens past that point are
        dropped exactly as an unspeculated run would never have
        decoded them. Emitted tokens settle like the row plane,
        except the lease is released-AND-cached before finish() so
        the settle hook no-ops and the prompt's full blocks enter the
        prefix tree while the owner refs still hold them."""
        ex = self.executor
        now = time.monotonic()
        for i, req in enumerate(snapshot):
            if req is None or self._slots[i] is not req:
                continue
            if req.done:
                # Abandoned by the handler (wait timeout → 500): the
                # finish hook already released the lease, so no cache
                # insert — just evict the zombie slot.
                ex.kv_release_slot(i, cache=False)
                self._slots[i] = None
                continue
            # ONE extraction for both collect shapes (a 1-D entry is
            # a run of length <= 1) — the hoisted idiom, literally.
            run = token_run(tokens[i])
            emitted = bool(run)
            if emitted and req.first_token_at is None:
                req.first_token_at = now
            finished = False
            for t in run:
                req.tokens.append(t)
                if len(req.tokens) >= req.max_tokens:
                    finished = True
                    break
                if now >= req.deadline:
                    # Deadline mid-run: keep what settled, drop the
                    # accepted tail.
                    req.truncated = True
                    finished = True
                    break
            if not finished and now >= req.deadline:
                # Deadline mid-decode OR mid-prefill: return whatever
                # exists, marked truncated, at the step boundary —
                # the PR 2 bounded-p99 contract extended to prompts
                # still prefilling (possibly zero tokens).
                req.truncated = True
                finished = True
            if not finished and emitted and self.handoff is not None:
                # Prefill replica: the emit means prefill completed
                # (the step that processes the last prompt token emits
                # the first decode token), so the request's KV is
                # built and its decode regime belongs elsewhere.
                # Detach the lease (pages stay owned — a failed
                # transfer resumes here) and hand ownership to the
                # transfer plane. A retry that re-attached here first
                # re-decodes exactly one token and hands off again —
                # the stream stays byte-identical either way.
                detach = ex.kv_detach_slot(i)
                if detach is None:
                    # Settled concurrently by the handler thread (the
                    # finish choke point released the lease between
                    # the done-check above and the detach): pages
                    # already returned, nothing to hand off — just
                    # free the slot, like the req.done branch.
                    self._slots[i] = None
                    continue
                self.tracer.event(
                    "disagg.handoff", request_id=req.request_id,
                    parent_id=req.trace_parent,
                    attrs={"replica": self.replica,
                           "tokens": len(req.tokens),
                           "confirmed": detach["confirmed"]})
                self.tracer.decision("handoff",
                                     request_id=req.request_id,
                                     replica=self.replica)
                # Hand off BEFORE emptying the slot: the transfer
                # plane's _transferring counter must cover the request
                # before active() stops counting it, or a quiesce poll
                # landing in the gap reads the pool as drained around
                # a live hand-off (the supervisor's _seizing
                # discipline: flip the accounting flag first).
                self.handoff(req, detach)
                self._slots[i] = None
                continue
            if finished:
                ex.kv_release_slot(i, cache=True)
                self._count("serving_tokens_total",
                            {"replica": self.replica},
                            by=float(len(req.tokens)),
                            help="decoded tokens")
                req.finish()
                self.tracer.event(
                    "batcher.retire", request_id=req.request_id,
                    parent_id=req.trace_parent,
                    attrs={"replica": self.replica,
                           "tokens": len(req.tokens),
                           "truncated": req.truncated, "kv": True})
                self._slots[i] = None

    def _collect_retire_kv(self, submitted) -> Optional[float]:
        """Collect one in-flight KV step and settle it; returns the
        device-done timestamp (the gap clock's start), or None when a
        supervisor seize landed — the loop must exit without touching
        anything further."""
        handle, snap, step_no, rids = submitted
        ex = self.executor
        tc = time.monotonic()
        self.blocked_since = tc
        tokens = ex.collect(handle)
        self.blocked_since = None
        t_done = time.monotonic()
        n_active = sum(1 for r in snap if r is not None)
        self._observe_step(t_done - tc, n_active)
        if self.tracer.enabled and rids is not None:
            dev = self.tracer.record_span(
                "step.device", tc, t_done,
                attrs={"replica": self.replica, "step": step_no,
                       "mode": "kv", "n_active": n_active,
                       "request_ids": rids})
            self.tracer.record_span(
                "executor.collect", tc, t_done, parent_id=dev,
                attrs={"replica": self.replica, "step": step_no,
                       "request_ids": rids})
        with self._settle_lock:
            if self._abandoned:
                return None
            self._retire_kv(tokens, snap)
        return t_done

    def _run_kv(self) -> None:
        """Token-level loop over a paged-KV executor. Same skeleton
        and seize/watchdog contracts as _run_pipelined — admissions
        and settling under the settle lock, dispatch and collect
        outside it with blocked_since published — but the step payload
        is the EXECUTOR's chunked-prefill/decode plan (no row
        scatter), admission binds a KV lease, and retire understands
        NO_TOKEN. `pipelined` picks the shape: True settles step k-1
        while step k runs on the device (the decode recurrence chains
        on device, so dispatch needs no host token); False collects
        every step before the next dispatch — the measured baseline.
        Speculative executors ride EITHER shape with no loop branch
        here (collect just returns runs): sync drafts from the
        previous step's accepted tokens; pipelined (ISSUE 18) drafts
        window w+1 from window w's PROPOSED tokens inside the
        executor's plan, with epoch-gated rollback on
        mis-speculation. Token STREAMS are identical either way:
        rows decode independently and the plan depends only on
        committed cursors (the ISSUE 3 equivalence argument, carried
        to tokens — extended to speculation by the exact greedy
        prefix-match acceptance).

        The `gen` captured under the settle lock makes the
        documented dispatch-outside-the-lock window safe on the KV
        plane: a submit raced by a seize→reset lands with a stale
        generation and becomes a no-op handle instead of advancing
        the restarted session's cursors."""
        ex = self.executor
        self.blocked_since = time.monotonic()
        ex.reset()
        self.blocked_since = None
        prev = None  # (handle, slot snapshot, step no, occupant rids)
        t_gap_start = None
        while not self._stop.is_set():
            try:
                submitted = None
                admit_rids: List[str] = []
                with self._settle_lock:
                    if self._abandoned:
                        return
                    self._maybe_preempt_kv()
                    block = self.active == 0 and prev is None
                    for _i, req, _vec in self._pop_admissions(
                            block=block):
                        admit_rids.append(req.request_id)
                    snapshot = (list(self._slots) if self.active > 0
                                else None)
                    gen = ex.kv_gen()
                if snapshot is not None:
                    traced = self.tracer.enabled
                    cur_rids = ([r.request_id for r in snapshot
                                 if r is not None] if traced else None)
                    ts0 = time.monotonic()
                    if t_gap_start is not None:
                        self._observe_gap(ts0 - t_gap_start)
                        if traced:
                            self.tracer.record_span(
                                "step.host", t_gap_start, ts0,
                                attrs={"replica": self.replica,
                                       "step": self.steps + 1,
                                       "mode": "kv",
                                       "request_ids": cur_rids})
                    self.blocked_since = ts0
                    handle = ex.submit((), step=self.steps + 1,
                                       request_ids=admit_rids or None,
                                       gen=gen)
                    self.blocked_since = None
                    self.steps += 1
                    if traced:
                        self.tracer.record_span(
                            "executor.submit", ts0, time.monotonic(),
                            attrs={"replica": self.replica,
                                   "step": self.steps, "mode": "kv",
                                   "admits_landing": admit_rids or None,
                                   "request_ids": cur_rids})
                    submitted = (handle, snapshot, self.steps, cur_rids)
                if not self.pipelined:
                    # Sync shape: settle THIS step before the next
                    # dispatch; nothing ever carries across iterations.
                    if submitted is not None:
                        t_gap_start = self._collect_retire_kv(submitted)
                        if t_gap_start is None:
                            return
                    else:
                        t_gap_start = None
                    continue
                if prev is not None:
                    t_done = self._collect_retire_kv(prev)
                    if t_done is None:
                        return
                    t_gap_start = t_done
                if submitted is None:
                    t_gap_start = None  # pipeline drained: idle queue
                    # waits must not masquerade as host gap
                prev = submitted
            except Exception as e:
                self.blocked_since = None
                if self.crash_only:
                    raise
                log.exception("batcher %s: kv step failed",
                              self.replica)
                self._fail_occupants(e)
                prev = None
                t_gap_start = None
                try:
                    ex.reset()  # unbind poisoned slot states
                except Exception:
                    log.exception("batcher %s: executor reset failed",
                                  self.replica)

    def _fail_occupants(self, e: Exception) -> None:
        # Under the settle lock, like every other settle path (GL012):
        # the legacy loops call this bare from their except handlers,
        # and a concurrent stop() — which fails occupants itself —
        # used to interleave with this loop and settle the same
        # request twice (its error overwritten after the handler
        # thread already woke). _abandoned re-checked under the lock:
        # once a stop/seize owns the slots, they are not ours to fail.
        with self._settle_lock:
            if self._abandoned:
                return
            for i, req in enumerate(self._slots):
                if req is not None:
                    req.fail(f"executor failed: {e}")
                    self.tracer.event(
                        "batcher.fail", request_id=req.request_id,
                        parent_id=req.trace_parent,
                        attrs={"replica": self.replica,
                               "error": str(e)[:200]})
                    self._slots[i] = None
                    self._x[i] = 0.0

    def _run(self) -> None:
        try:
            # Every record this thread emits carries its replica (the
            # JSON-lines ContextFilter stamps it) — request ids are
            # bound per call site, the replica once here.
            with obs_logging.context(replica=self.replica):
                if self.kv_mode:
                    self._run_kv()
                elif self.pipelined:
                    self._run_pipelined()
                else:
                    self._run_sync()
        except Exception as e:
            # crash_only loops re-raise here; the recorded failure and
            # the dead thread ARE the signal the supervisor keys on.
            # (A legacy loop only reaches this for a harness bug — the
            # loops themselves absorb executor failures.)
            self.blocked_since = None
            self.failure = e
            log.error("batcher %s: replica failed (%s); awaiting "
                      "supervision", self.replica, e)
