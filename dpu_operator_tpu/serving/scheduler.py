"""Continuous batching: admit/retire at STEP boundaries, not batch ones.

The classic serving mistake is static batching — collect B requests,
run all their tokens, return, repeat — which makes every request wait
for the slowest member of its batch and leaves slots idle as members
finish early. Continuous batching (Orca, OSDI '22) re-forms the batch
every model step: a request occupies one SLOT, each step decodes one
token for every occupied slot, finished requests free their slot at
the step boundary and queued requests are admitted into free slots
before the next step. Occupancy tracks offered load step by step;
nobody waits for a stranger's tail.

The executor's batch shape is FIXED at [slots, d] (idle slots carry
zeros) so the jitted forward compiles once — occupancy varies, shapes
don't. One batcher per replica, one thread per batcher; the shared
AdmissionQueue is the only cross-replica coupling.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

import numpy as np

from .api import GenerateRequest

log = logging.getLogger(__name__)


class ContinuousBatcher:
    def __init__(self, executor, queue, registry=None,
                 replica: str = "replica0", idle_wait_s: float = 0.05):
        self.executor = executor
        self.queue = queue
        self.registry = registry
        self.replica = replica
        self.idle_wait_s = idle_wait_s
        self._slots: List[Optional[GenerateRequest]] = (
            [None] * executor.slots)
        self._x = np.zeros((executor.slots, executor.d), np.float32)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.steps = 0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"batcher-{self.replica}")
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        for i, req in enumerate(self._slots):
            if req is not None:
                req.fail("server stopped")
                self._slots[i] = None

    @property
    def active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    # -- the loop -------------------------------------------------------------

    def _observe(self, name: str, value: float, help: str = "",
                 buckets=None) -> None:
        if self.registry is not None:
            self.registry.observe(name, value, {"replica": self.replica},
                                  help=help, buckets=buckets)

    def _count(self, name: str, labels: dict, help: str = "",
               by: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, labels, by=by, help=help)

    def _admit(self) -> None:
        free = [i for i, r in enumerate(self._slots) if r is None]
        if not free:
            return
        # Block only when fully idle: a running batch polls (timeout 0)
        # so decode steps are never held hostage to admission.
        timeout = self.idle_wait_s if len(free) == len(self._slots) else 0.0
        for req in self.queue.get_many(len(free), timeout=timeout):
            try:
                i = free.pop(0)
                req.admitted_at = time.monotonic()
                self._slots[i] = req
                self._x[i] = req.prompt_vec
            except Exception as e:
                # A request popped from the queue has exactly one owner
                # now — losing it here would park its handler thread
                # for the full deadline.
                log.exception("batcher %s: admit failed", self.replica)
                if self._slots[i] is req:
                    self._slots[i] = None
                    self._x[i] = 0.0
                req.fail(f"admission failed: {e}")
            finally:
                # In a slot (or failed) — no longer "in flight between
                # queue and slot" for the drain quiesce accounting.
                self.queue.mark_placed(1)

    def _retire(self, y: np.ndarray) -> None:
        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.done:
                # Abandoned by the handler (wait timeout → 500): evict
                # rather than decode to max_tokens for nobody — zombie
                # slots are capacity loss exactly when capacity is short.
                self._slots[i] = None
                self._x[i] = 0.0
                continue
            req.tokens.append(int(np.argmax(y[i])))
            self._x[i] = y[i]  # decode recurrence: output is next state
            finished = len(req.tokens) >= req.max_tokens
            if not finished and now >= req.deadline:
                # Deadline mid-decode: return what exists, marked, at
                # the boundary — p99 for admitted work stays bounded by
                # deadline + one step, never by another request's tail.
                req.truncated = True
                finished = True
            if finished:
                self._count("serving_tokens_total",
                            {"replica": self.replica},
                            by=float(len(req.tokens)),
                            help="decoded tokens")
                req.finish()
                self._slots[i] = None
                self._x[i] = 0.0

    def _run(self) -> None:
        occupancy_buckets = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                             0.875, 1.0)
        while not self._stop.is_set():
            # Any failure in this body must cost at most the CURRENT
            # occupants — never the thread. A dead batcher is a replica
            # that silently serves nothing while /healthz stays green.
            try:
                self._admit()
                n_active = self.active
                if n_active == 0:
                    continue
                t0 = time.perf_counter()
                y = self.executor.step(self._x)
                dt = time.perf_counter() - t0
                self.steps += 1
                self._observe("serving_step_seconds", dt,
                              help="model step wall time")
                self._observe("serving_batch_occupancy",
                              n_active / self.executor.slots,
                              help="occupied fraction of batch slots",
                              buckets=occupancy_buckets)
                self._retire(y)
            except Exception as e:  # broken replica must not wedge waiters
                log.exception("batcher %s: step failed", self.replica)
                for i, req in enumerate(self._slots):
                    if req is not None:
                        req.fail(f"executor failed: {e}")
                        self._slots[i] = None
                        self._x[i] = 0.0
