"""HTTP front-end of the serving plane: /v1/generate, /healthz, /metrics.

Follows the k8s/http_server.py idiom (ThreadingHTTPServer, handler
back-references through the server object, quiet logs) with the
serving-specific contract on top:

  POST /v1/generate   {"prompt": str | "prompt_vec": [d floats],
                       "max_tokens": int, "deadline_ms": int}
      200 {"id", "tokens", "truncated", "timings": {queue_ms,
           decode_ms, total_ms}}
      400 malformed body / wrong prompt_vec width
      503 + Retry-After on queue-full, drain, or deadline shed — the
          backpressure answer: overload is REJECTED at the door so
          admitted requests keep a bounded p99 (never parked into an
          unbounded queue).
  GET /healthz        liveness: 200 while anything serves or is coming
                      back; 503 "dead" only when zero replicas are
                      live AND every breaker is open (nothing will
                      ever restart — a process restart is the only
                      medicine left)
  GET /readyz         readiness — what a k8s Service endpoint should
                      key on: 503 while draining, 503 "degraded" while
                      live replicas < the pool's quorum, else 200
  GET /metrics        utils/metrics.Registry exposition
  GET /debug/traces?request_id=...
                      span tree for one request (obs/trace.py): queue
                      wait → admit → per-step segments → retire, plus
                      any supervisor recovery chain. Every generate
                      response carries its id in X-Request-Id.
  GET /debug/flight   on-demand flight-recorder snapshot (the same
                      JSON the supervisor writes to disk on wedge/
                      death/breaker — see docs/observability.md)

SIGTERM drain (install_signal_handlers): stop admitting (everything new
gets 503), let queued + in-flight requests finish, then — when a
drain.Drainer and node name are wired — cordon the node and evict
fabric pods exactly as the daemon's repartition path does, so the
replica disappears from scheduling before the process exits.
"""

from __future__ import annotations

import json
import logging
import math
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..obs import trace as obs_trace
from ..obs.flight import FlightRecorder
from ..utils.metrics import Registry
from .api import (DEADLINE_QUEUED_ERROR, KV_OOM_ERROR, PRIORITIES,
                  RETRIES_EXHAUSTED_ERROR, Draining, QueueFull,
                  TenantOverBudget, GenerateRequest,
                  bounded_tenant_label, encode_prompt,
                  encode_prompt_tokens)
from .executor import Executor, ReplicaPool
from .queue import AdmissionQueue

log = logging.getLogger(__name__)

_DEADLINE_CAP_MS = 24 * 3600 * 1000.0  # nobody waits a day for tokens
_MAX_BODY_BYTES = 1 << 20  # prompt_vec of a few thousand floats fits 100x over


class ServingServer:
    def __init__(self, executors: Sequence[Executor], *,
                 host: str = "127.0.0.1", port: int = 0,
                 max_queue_depth: int = 64,
                 default_max_tokens: int = 16,
                 max_tokens_cap: int = 1024,
                 default_deadline_s: float = 30.0,
                 retry_after_s: float = 1.0,
                 tenants: Optional[dict] = None,
                 default_budget=None,
                 registry: Optional[Registry] = None,
                 drainer=None, node_name: Optional[str] = None,
                 pool_opts: Optional[dict] = None,
                 pool_factory=None,
                 tracer=None, flight_dir: Optional[str] = None):
        # Per-server registry by default: tests and benches run several
        # servers in one process; sharing default_registry would blend
        # their series.
        self.registry = registry if registry is not None else Registry()
        # The tracer is process-global by default (spans carry request
        # ids and replica names, so cross-server series disambiguate by
        # id) — faults and the fabric transport record into the same
        # one, which is what puts an injected fault on the same
        # timeline as the recovery that answers it.
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        self.flight = FlightRecorder(tracer=self.tracer,
                                     flight_dir=flight_dir,
                                     registry=self.registry)
        # tenants maps tenant name → queue.TenantBudget (rate/burst/
        # weight); default_budget meters tenants not named there. Both
        # None (the default) keeps the single-tenant contract: one
        # global depth bound, FIFO, nobody ever sees a 429.
        self.queue = AdmissionQueue(max_depth=max_queue_depth,
                                    retry_after_s=retry_after_s,
                                    registry=self.registry,
                                    tracer=self.tracer,
                                    tenants=tenants,
                                    default_budget=default_budget)
        # Bounded tenant label values for THIS server's request series
        # (api.bounded_tenant_label): tenant names arrive from the
        # wire, and metrics cardinality must not be client-controlled.
        self._tenant_seen: set = set()
        self._tenant_seen_lock = threading.Lock()
        # pool_opts passes supervision knobs through (supervise,
        # watchdog_s, max_attempts, quorum, backoff/breaker tuning) —
        # the pool's defaults are the production contract.
        # pool_factory swaps the scheduler layer wholesale (the
        # disagg plane's role-typed DisaggPool): called with
        # (executors, queue, registry, tracer=, flight_recorder=), it
        # must return a ReplicaPool-shaped object — start/stop/
        # quiesce/live_count/states/all_parked/quorum/supervised/
        # executors — and `executors` passed to THIS constructor must
        # be the factory pool's full executor list (the front door
        # validates vocab/max_context/d across all of them).
        opts = dict(pool_opts or {})
        opts.setdefault("tracer", self.tracer)
        opts.setdefault("flight_recorder", self.flight)
        if pool_factory is not None:
            self.pool = pool_factory(executors, self.queue,
                                     self.registry,
                                     tracer=self.tracer,
                                     flight_recorder=self.flight)
        else:
            self.pool = ReplicaPool(executors, self.queue,
                                    registry=self.registry, **opts)
        # serving_trace_dropped_total is published as a DELTA against
        # the tracer's monotonic drop count at scrape time; init the
        # series so a zero-drop run still proves the bound exists.
        self._trace_dropped_pub = 0
        self._trace_pub_lock = threading.Lock()
        self.registry.counter_inc(
            "serving_trace_dropped_total", by=0.0,
            help="spans dropped by the tracer's bounded buffers "
                 "(per-thread overflow + ring eviction)")
        self.default_max_tokens = default_max_tokens
        self.max_tokens_cap = max_tokens_cap
        self.default_deadline_s = default_deadline_s
        kvs = {bool(getattr(ex, "kv", False)) for ex in executors}
        if len(kvs) != 1:
            # One front door, one request vocabulary: a pool mixing
            # token-plane and row-plane replicas could not validate a
            # prompt once at admission.
            raise ValueError("pool mixes paged-KV and row-plane "
                             "replicas")
        self.kv = kvs.pop()
        if self.kv:
            vocabs = {ex.vocab for ex in executors}
            ctxs = {ex.max_context for ex in executors}
            if len(vocabs) != 1 or len(ctxs) != 1:
                raise ValueError(
                    f"all KV replicas must share one vocab/max_context,"
                    f" got {sorted(vocabs)}/{sorted(ctxs)}")
            self.vocab = executors[0].vocab
            self.max_context = executors[0].max_context
            # Scrape-time delta state for the kv token counters
            # (published like serving_trace_dropped_total).
            self._kv_pub: dict = {}
            # Same discipline for the speculative-decoding counters
            # (present only on executors running mode="speculative").
            self._spec_pub: dict = {}
            # Per-tier prefix-hit deltas (ISSUE 17): hbm/host/remote.
            self._tier_pub: dict = {}
        dims = {ex.d for ex in executors}
        if len(dims) != 1:
            # prompt_vec width is validated once at the front door; a
            # mixed-d pool would admit vectors some replica cannot hold.
            raise ValueError(f"all replicas must share one feature dim, "
                             f"got {sorted(dims)}")
        self.d = executors[0].d
        self.drainer = drainer
        self.node_name = node_name
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._drain_ok = False
        self._stopped = False

        server_ref = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, body: dict,
                      headers: Optional[dict] = None) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, val in (headers or {}).items():
                    self.send_header(k, val)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    # Liveness goes red ONLY when zero replicas are
                    # live AND none is coming back (every breaker
                    # open) — then a process restart is the only
                    # medicine left. A replica mid-backoff is seconds
                    # from returning; killing the pod for that would
                    # turn every transient fault into a full restart.
                    # Degraded and draining are readiness problems.
                    live = server_ref.pool.live_count()
                    if server_ref.pool.supervised and live == 0 \
                            and server_ref.pool.all_parked():
                        return self._send(
                            503, {"status": "dead", "live_replicas": 0})
                    return self._send(
                        200, {"status": "ok", "live_replicas": live})
                if self.path == "/readyz":
                    if server_ref.draining:
                        return self._send(503, {"status": "draining"})
                    live = server_ref.pool.live_count()
                    quorum = server_ref.pool.quorum
                    if live < quorum:
                        # Below quorum: stop routing NEW traffic here
                        # (a Service endpoint keyed on readiness drops
                        # out) while in-flight work keeps completing.
                        return self._send(
                            503, {"status": "degraded",
                                  "live_replicas": live,
                                  "quorum": quorum})
                    return self._send(
                        200, {"status": "ready",
                              "live_replicas": live})
                if self.path == "/metrics":
                    server_ref.update_derived_metrics()
                    data = server_ref.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                parsed = urlparse(self.path)
                if parsed.path == "/debug/traces":
                    # Span tree for one request: queue → admit →
                    # per-step → retire (+ any recovery chain), JSON.
                    # ?recent=N lists the most recently active
                    # request ids instead — the discoverability mode
                    # for an operator with no X-Request-Id in hand.
                    qs = parse_qs(parsed.query)
                    recent = qs.get("recent", [None])[0]
                    if recent is not None:
                        try:
                            n = int(recent)
                            if not 1 <= n <= 1000:
                                raise ValueError(recent)
                        except (TypeError, ValueError):
                            return self._send(
                                400, {"error": "recent must be an "
                                               "int in [1, 1000]"})
                        return self._send(
                            200, {"recent":
                                  server_ref.tracer
                                  .recent_requests(n)})
                    rid = qs.get("request_id", [None])[0]
                    if not rid:
                        return self._send(
                            400, {"error": "need ?request_id= "
                                           "(or ?recent=N)"})
                    tree = server_ref.tracer.span_tree(rid)
                    if tree["span_count"] == 0:
                        # Stable contract under concurrency: an
                        # unknown (or fully evicted) id is ALWAYS
                        # this 404 — span_tree works on one snapshot,
                        # so a concurrently-draining tracer can never
                        # surface a half-drained tree.
                        return self._send(
                            404, {"error": f"no spans for request "
                                           f"{rid!r} (evicted or "
                                           f"unknown)"})
                    return self._send(200, tree)
                if parsed.path == "/debug/flight":
                    # On-demand flight snapshot: same payload the
                    # supervisor writes on wedge/death/breaker, served
                    # without touching disk.
                    return self._send(
                        200, server_ref.flight.snapshot(
                            "on_demand", write=False))
                self._send(404, {"error": "not found"})

            def do_POST(self):
                # Read the declared body BEFORE any reply: these are
                # HTTP/1.1 keep-alive connections, and replying with the
                # body still unread would desync the stream (the next
                # request line would parse from our leftover JSON).
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (ValueError, TypeError):
                    self.close_connection = True
                    return self._send(400,
                                      {"error": "bad Content-Length"})
                if length > _MAX_BODY_BYTES:
                    # Bounded like everything else on this front door —
                    # a declared multi-GB body must not buffer into a
                    # handler thread while /healthz stays green.
                    self.close_connection = True
                    return self._send(
                        413, {"error": f"body over {_MAX_BODY_BYTES} "
                                       f"bytes"})
                raw = self.rfile.read(length) if length > 0 else b""
                if self.path != "/v1/generate":
                    return self._send(404, {"error": "not found"})
                server_ref.handle_generate(self, raw)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def start(self) -> "ServingServer":
        self.pool.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="serving")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        # Refuse-new FIRST: a POST racing this teardown must get a
        # prompt 503, not a submit into a queue no batcher will ever
        # pop again (the handler would park its full wait timeout).
        self._draining.set()
        self.queue.begin_drain()
        self._httpd.shutdown()
        self._httpd.server_close()
        self.queue.fail_all("server stopped")
        self.pool.stop()
        # Again after the pool is down: a replica that died during
        # teardown may have requeued its occupants between the first
        # fail_all and the supervisor stopping — nobody will ever pop
        # them, so fail them here instead of parking their handlers.
        self.queue.fail_all("server stopped")
        if self._thread:
            self._thread.join(timeout=5)

    # -- drain ----------------------------------------------------------------

    def begin_drain(self, timeout: float = 30.0) -> bool:
        """SIGTERM path: refuse new work (503), finish queued +
        in-flight work, then cordon/evict via drain.Drainer when wired.
        Idempotent; returns True once quiesced."""
        self._draining.set()
        self.queue.begin_drain()
        ok = self.pool.quiesce(timeout)
        if ok and self.drainer is not None and self.node_name:
            try:
                self.drainer.drain_node(self.node_name)
            except Exception:
                log.exception("drain: Drainer.drain_node failed")
        self._drain_ok = ok
        self._drained.set()
        return ok

    def install_signal_handlers(self, stop_after: bool = True,
                                drain_timeout: float = 30.0):
        """SIGTERM → drain in a background thread (the handler itself
        must return immediately — it runs on the main thread mid-
        whatever). Returns the previous handler."""

        def _on_sigterm(signum, frame):
            log.info("SIGTERM: draining serving plane")
            t = threading.Thread(target=self._drain_and_stop,
                                 args=(drain_timeout, stop_after),
                                 daemon=True, name="serving-drain")
            t.start()

        return signal.signal(signal.SIGTERM, _on_sigterm)

    def _drain_and_stop(self, timeout: float, stop_after: bool) -> None:
        self.begin_drain(timeout)
        if stop_after:
            self.stop()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """True only for a COMPLETED drain (everything in flight
        finished). A quiesce timeout unblocks waiters but returns
        False — an orchestrator keyed on this must not tear down a
        process still holding requests."""
        return self._drained.wait(timeout) and self._drain_ok

    # -- request handling ------------------------------------------------------

    def update_derived_metrics(self) -> None:
        """Scrape-time derived gauges: the in-process p50/p99 estimate
        over the request-latency histogram (Registry.quantile — the SLO
        number an operator alerts on, computed where the buckets live
        instead of in PromQL)."""
        for q, name in ((0.5, "serving_request_p50_seconds"),
                        (0.99, "serving_request_p99_seconds")):
            est = self.registry.quantile(
                "serving_request_seconds", q, {"outcome": "ok"})
            if est is not None:
                self.registry.gauge_set(
                    name, round(est, 6),
                    help=f"estimated q={q} of serving_request_seconds "
                         f"(ok outcomes)")
        # Per-tenant p99 (ISSUE 20): same estimator over the tenant-
        # labelled histogram, one gauge per admitted tenant label —
        # the isolation number the QoS bench gates on, visible to an
        # operator without PromQL.
        for key in self.registry.histogram_totals(
                "serving_tenant_request_seconds"):
            lbl = dict(key)
            if lbl.get("outcome") != "ok":
                continue
            est = self.registry.quantile(
                "serving_tenant_request_seconds", 0.99, lbl)
            if est is not None:
                self.registry.gauge_set(
                    "serving_tenant_request_p99_seconds",
                    round(est, 6), {"tenant": lbl["tenant"]},
                    help="estimated q=0.99 of per-tenant request wall "
                         "time (ok outcomes, bounded tenant label)")
        # The ring bound, proven: spans lost to either tracer bound
        # (per-thread overflow, ring eviction) surface as a counter —
        # published as the delta since the last scrape so the series
        # stays monotonic per server. Read-modify-write under a lock:
        # each connection gets its own handler thread, so two
        # concurrent /metrics scrapes would otherwise both see the
        # same delta and double-count the drops.
        with self._trace_pub_lock:
            dropped = self.tracer.dropped_total()
            delta = dropped - self._trace_dropped_pub
            self._trace_dropped_pub = dropped
        if delta > 0:
            self.registry.counter_inc(
                "serving_trace_dropped_total", by=float(delta),
                help="spans dropped by the tracer's bounded buffers "
                     "(per-thread overflow + ring eviction)")
        # Paged-KV plane (ISSUE 7): allocator occupancy, prefix-cache
        # effectiveness, and the prefill/decode token counters —
        # executor-authoritative values published at scrape time
        # (gauges as snapshots, counters as deltas so the series stay
        # monotonic per server).
        if self.kv:
            agg = {"used": 0, "free": 0, "shared": 0,
                   "hit": 0, "lookup": 0}
            deltas = {"prefill": 0, "decode": 0}
            tier_deltas = {"hbm": 0, "host": 0, "remote": 0}
            spec_agg = {"proposed": 0, "accepted": 0, "runs": 0,
                        "depth": 0, "peak": 0}
            spec_deltas = {"proposed": 0, "accepted": 0, "replans": 0}
            spec_path_deltas: dict = {}
            spec_seen = False
            rank_agg: dict = {}
            with self._trace_pub_lock:
                for idx, ex in enumerate(self.pool.executors):
                    st = ex.kv_stats()
                    agg["used"] += st["blocks_used"]
                    agg["free"] += st["blocks_free"]
                    agg["shared"] += st["blocks_shared"]
                    if hasattr(ex, "kv_rank_stats"):
                        # Context-parallel pools (ISSUE 16): the same
                        # gauge, decomposed per shard rank — one extra
                        # label on sharded-KV executors only, the
                        # aggregate series above stays as-is.
                        for r, rst in ex.kv_rank_stats().items():
                            for state in ("used", "free"):
                                key = (r, state)
                                rank_agg[key] = (
                                    rank_agg.get(key, 0)
                                    + rst[f"blocks_{state}"])
                    agg["hit"] += st["prefix_hit_tokens"]
                    agg["lookup"] += st["prefix_lookup_tokens"]
                    # Per-tier hit split (ISSUE 17): counters as
                    # deltas, like every executor-authoritative total.
                    # Executors predating the split report the sum as
                    # hbm — the only tier that existed.
                    tlast = self._tier_pub.get(idx, (0, 0, 0))
                    tcur = (st.get("prefix_hit_tokens_hbm",
                                   st["prefix_hit_tokens"]),
                            st.get("prefix_hit_tokens_host", 0),
                            st.get("prefix_hit_tokens_remote", 0))
                    for j, tname in enumerate(("hbm", "host",
                                               "remote")):
                        tier_deltas[tname] += tcur[j] - tlast[j]
                    self._tier_pub[idx] = tcur
                    last = self._kv_pub.get(idx, (0, 0))
                    deltas["prefill"] += st["prefill_tokens"] - last[0]
                    deltas["decode"] += st["decode_tokens"] - last[1]
                    self._kv_pub[idx] = (st["prefill_tokens"],
                                         st["decode_tokens"])
                    if "spec_proposed_tokens" in st:
                        # Speculative replica (ISSUE 15): acceptance
                        # counters as deltas, rates as scrape-time
                        # gauges over the cumulative totals.
                        spec_seen = True
                        spec_agg["proposed"] += st[
                            "spec_proposed_tokens"]
                        spec_agg["accepted"] += st[
                            "spec_accepted_tokens"]
                        spec_agg["runs"] += st["spec_verify_steps"]
                        # Pipelined speculation (ISSUE 18): in-flight
                        # plan-ahead depth is a live gauge; re-plans
                        # and the accepted path-length histogram are
                        # deltas like every executor total.
                        spec_agg["depth"] += st.get(
                            "spec_pipeline_depth", 0)
                        spec_agg["peak"] = max(
                            spec_agg["peak"],
                            st.get("spec_pipeline_peak", 0))
                        slast = self._spec_pub.get(
                            idx, (0, 0, 0, {}))
                        spec_deltas["proposed"] += (
                            st["spec_proposed_tokens"] - slast[0])
                        spec_deltas["accepted"] += (
                            st["spec_accepted_tokens"] - slast[1])
                        spec_deltas["replans"] += (
                            st.get("spec_replans", 0) - slast[2])
                        paths = dict(st.get("spec_path_len", {}))
                        for plen, n in paths.items():
                            d = n - slast[3].get(plen, 0)
                            if d > 0:
                                spec_path_deltas[plen] = (
                                    spec_path_deltas.get(plen, 0) + d)
                        self._spec_pub[idx] = (
                            st["spec_proposed_tokens"],
                            st["spec_accepted_tokens"],
                            st.get("spec_replans", 0), paths)
            for state in ("used", "free", "shared"):
                self.registry.gauge_set(
                    "serving_kv_blocks", float(agg[state]),
                    {"state": state},
                    help="paged KV blocks by allocator state "
                         "(shared = refcount > 1)")
            for (r, state), n in sorted(rank_agg.items()):
                self.registry.gauge_set(
                    "serving_kv_blocks", float(n),
                    {"state": state, "rank": str(r)},
                    help="paged KV blocks by allocator state "
                         "(shared = refcount > 1)")
            self.registry.gauge_set(
                "serving_kv_prefix_hit_frac",
                round(agg["hit"] / agg["lookup"], 6)
                if agg["lookup"] else 0.0,
                help="fraction of looked-up prompt tokens served from "
                     "the prefix cache")
            for tname in ("hbm", "host", "remote"):
                self.registry.counter_inc(
                    "serving_prefix_hit_tokens_total",
                    {"tier": tname},
                    by=float(max(0, tier_deltas[tname])),
                    help="prefix-cache hit tokens by the tier that "
                         "served them (hbm resident, host-tier "
                         "restore, cross-replica pull)")
            self.registry.gauge_set(
                "serving_prefix_hit_frac",
                round(agg["hit"] / agg["lookup"], 6)
                if agg["lookup"] else 0.0,
                help="fraction of looked-up prompt tokens served from "
                     "any prefix-cache tier (scrape-time, cumulative)")
            self.registry.counter_inc(
                "serving_prefill_tokens_total", by=float(
                    max(0, deltas["prefill"])),
                help="prompt tokens processed through chunked prefill")
            self.registry.counter_inc(
                "serving_decode_tokens_total", by=float(
                    max(0, deltas["decode"])),
                help="decode tokens emitted by paged-KV steps")
            if spec_seen:
                self.registry.counter_inc(
                    "serving_spec_proposed_tokens_total", by=float(
                        max(0, spec_deltas["proposed"])),
                    help="draft tokens fed to speculative verify "
                         "steps")
                self.registry.counter_inc(
                    "serving_spec_accepted_tokens_total", by=float(
                        max(0, spec_deltas["accepted"])),
                    help="draft tokens the target model accepted")
                self.registry.gauge_set(
                    "serving_spec_accept_rate",
                    round(spec_agg["accepted"] / spec_agg["proposed"],
                          6) if spec_agg["proposed"] else 0.0,
                    help="accepted fraction of proposed draft tokens "
                         "(cumulative)")
                self.registry.gauge_set(
                    "serving_spec_tokens_per_step",
                    round((spec_agg["accepted"] + spec_agg["runs"])
                          / spec_agg["runs"], 6)
                    if spec_agg["runs"] else 0.0,
                    help="emitted tokens per verify step (accepted "
                         "drafts + the bonus; 1.0 = the one-token "
                         "baseline)")
                self.registry.counter_inc(
                    "serving_spec_replans_total", by=float(
                        max(0, spec_deltas["replans"])),
                    help="pipelined plan-ahead windows invalidated by "
                         "a mis-speculated verify (watermark rollback "
                         "+ re-plan; always 0 in sync spec mode)")
                self.registry.gauge_set(
                    "serving_spec_pipeline_depth",
                    float(spec_agg["depth"]),
                    help="speculative verify windows currently in "
                         "flight across replicas (0 = drained; 2 = "
                         "draft overlapping verify)")
                self.registry.gauge_set(
                    "serving_spec_pipeline_peak",
                    float(spec_agg["peak"]),
                    help="max simultaneous in-flight speculative "
                         "windows any replica reached (lifetime)")
                for plen in sorted(spec_path_deltas):
                    for _ in range(spec_path_deltas[plen]):
                        self.registry.observe(
                            "serving_spec_tree_path_len", float(plen),
                            help="tokens emitted per verify window "
                                 "(accepted root-to-leaf path + "
                                 "bonus; 1 = full rejection)",
                            buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0,
                                     12.0, 16.0))
        # Per-replica host-gap share of the decode loop: the overlap
        # number an operator watches — near 0 means host scheduling
        # hides behind device steps; climbing toward 1 means the device
        # waits on python (ISSUE 3's regression signal, visible in
        # /metrics, not just the bench artifact).
        device = self.registry.histogram_totals(
            "serving_step_device_seconds")
        for key, (gap_sum, _n) in self.registry.histogram_totals(
                "serving_host_gap_seconds").items():
            total = gap_sum + device.get(key, (0.0, 0))[0]
            if total > 0:
                self.registry.gauge_set(
                    "serving_host_gap_fraction",
                    round(gap_sum / total, 6), dict(key),
                    help="host-gap share of decode-loop wall time "
                         "(host_gap / (host_gap + device))")

    def _finish(self, handler, code: int, body: dict, outcome: str,
                headers: Optional[dict] = None,
                elapsed_s: Optional[float] = None,
                req: Optional[GenerateRequest] = None,
                tenant: Optional[str] = None) -> None:
        if tenant is None:
            tenant = req.tenant if req is not None else "default"
        with self._tenant_seen_lock:
            tlabel = bounded_tenant_label(tenant, self._tenant_seen)
        self.registry.counter_inc(
            "serving_requests_total", {"code": str(code),
                                       "outcome": outcome,
                                       "tenant": tlabel},
            help="generate requests by outcome")
        if elapsed_s is not None:
            self.registry.observe(
                "serving_request_seconds", elapsed_s,
                {"outcome": outcome},
                help="end-to-end request wall time")
            # Per-tenant latency rides a SEPARATE histogram: the p50/
            # p99 derived gauges key on serving_request_seconds'
            # exact label set {outcome}, and the registry matches
            # label keys exactly — adding tenant there would orphan
            # those series.
            self.registry.observe(
                "serving_tenant_request_seconds", elapsed_s,
                {"outcome": outcome, "tenant": tlabel},
                help="end-to-end request wall time by tenant "
                     "(bounded label)")
        if req is not None:
            # Every response for a request that got an id carries it —
            # the handle a client quotes to /debug/traces.
            headers = dict(headers or {})
            headers["X-Request-Id"] = req.request_id
            span = getattr(req, "_root_span", None)
            if span is not None:
                self.tracer.finish(span, attrs={"outcome": outcome,
                                                "code": code})
        handler._send(code, body, headers)

    def handle_generate(self, handler, raw: bytes) -> None:
        t0 = time.monotonic()
        retry = {"Retry-After": str(max(1, int(round(
            self.queue.retry_after_s))))}
        if self.draining:
            return self._finish(handler, 503, {"error": "draining"},
                                "draining", retry)
        try:
            body = json.loads(raw) if raw else {}
        except (ValueError, TypeError):
            return self._finish(handler, 400,
                                {"error": "malformed JSON body"}, "bad")
        if not isinstance(body, dict):
            return self._finish(handler, 400,
                                {"error": "body must be an object"}, "bad")
        # Multi-tenant QoS (ISSUE 20): tenant from the JSON body, then
        # the X-Tenant header, then "default"; priority must be a known
        # class — a typo'd priority is a 400, not a silent new class.
        tenant = body.get("tenant")
        if tenant is None:
            tenant = handler.headers.get("X-Tenant") or "default"
        if not isinstance(tenant, str) or not tenant \
                or len(tenant) > 256:
            return self._finish(
                handler, 400,
                {"error": "tenant must be a non-empty string "
                          "(<= 256 chars)"}, "bad")
        priority = body.get("priority", "interactive")
        if priority not in PRIORITIES:
            return self._finish(
                handler, 400,
                {"error": f"unknown priority class {priority!r} "
                          f"(expected one of {list(PRIORITIES)})"},
                "bad", tenant=tenant)
        try:
            vec = self._prompt_vec(body) if not self.kv else None
        except (ValueError, TypeError) as e:
            # TypeError too: np.asarray raises it for non-numeric JSON
            # (e.g. prompt_vec as an object) — that's a client error,
            # not a dropped connection.
            return self._finish(handler, 400, {"error": str(e)}, "bad",
                                tenant=tenant)
        try:
            max_tokens = int(body.get("max_tokens",
                                      self.default_max_tokens))
            deadline_ms = float(body.get("deadline_ms",
                                         self.default_deadline_s * 1000))
        except (TypeError, ValueError):
            return self._finish(
                handler, 400,
                {"error": "max_tokens/deadline_ms must be numbers"},
                "bad", tenant=tenant)
        if not 1 <= max_tokens <= self.max_tokens_cap:
            return self._finish(
                handler, 400,
                {"error": f"max_tokens must be in [1, "
                          f"{self.max_tokens_cap}]"}, "bad",
                tenant=tenant)
        # Finite and capped, not just positive: json.loads accepts
        # Infinity/NaN, and a NaN deadline poisons every expiry
        # comparison while an astronomic one overflows Event.wait.
        if not (math.isfinite(deadline_ms)
                and 0 < deadline_ms <= _DEADLINE_CAP_MS):
            return self._finish(
                handler, 400,
                {"error": f"deadline_ms must be a finite number in "
                          f"(0, {_DEADLINE_CAP_MS:.0f}]"}, "bad",
                tenant=tenant)

        toks = None
        if self.kv:
            try:
                toks = self._prompt_tokens(body, max_tokens)
            except (ValueError, TypeError) as e:
                return self._finish(handler, 400, {"error": str(e)},
                                    "bad", tenant=tenant)

        req = GenerateRequest(prompt_vec=vec, max_tokens=max_tokens,
                              deadline=t0 + deadline_ms / 1000.0,
                              prompt_tokens=toks,
                              tenant=tenant, priority=priority)
        # Root span of the request's trace: every downstream span
        # (queue, admit, retire, supervisor requeue) parents onto it
        # through req.trace_parent; _finish closes it with the outcome.
        span = self.tracer.start(
            "request", request_id=req.request_id,
            attrs={"max_tokens": max_tokens,
                   "deadline_ms": deadline_ms})
        if not obs_trace.is_noop(span):
            req.trace_parent = span.span_id
            req._root_span = span
        try:
            self.queue.submit(req)
        except TenantOverBudget as e:
            # 429, not 503: the SERVER has headroom, this tenant has
            # spent its share — the client-side fix is slow down, not
            # retry elsewhere.
            return self._finish(
                handler, 429,
                {"error": str(e), "tenant": e.tenant}, "over_budget",
                {"Retry-After": str(max(1, int(round(e.retry_after_s))))},
                req=req)
        except QueueFull as e:
            return self._finish(
                handler, 503,
                {"error": "overloaded: admission queue full",
                 "queue_depth": e.depth}, "queue_full",
                {"Retry-After": str(max(1, int(round(e.retry_after_s))))},
                req=req)
        except Draining:
            return self._finish(handler, 503, {"error": "draining"},
                                "draining", retry, req=req)
        except Exception as e:
            # Anything else out of the admission path (a poisoned
            # queue, an injected fault) must cost THIS request a JSON
            # 500, not the connection — the plane keeps serving.
            log.exception("generate: admission failed (request %s)",
                          req.request_id)
            return self._finish(
                handler, 500,
                {"error": f"internal: admission failed: {e}"}, "error",
                elapsed_s=time.monotonic() - t0, req=req)

        # The handler thread parks on the request event; the batcher
        # completes it. Grace past the deadline covers the final step +
        # hand-off — a miss here means the scheduler plane wedged.
        req.wait(deadline_ms / 1000.0 + 10.0)
        elapsed = time.monotonic() - t0
        if not req.done:
            req.fail("scheduler wedged")  # unparks nothing; marks it
            return self._finish(handler, 500,
                                {"error": "internal: request lost"},
                                "lost", elapsed_s=elapsed, req=req)
        if req.error is not None:
            shed = req.error in (DEADLINE_QUEUED_ERROR, KV_OOM_ERROR)
            code = 503 if shed else 500
            if req.error == DEADLINE_QUEUED_ERROR:
                outcome = "deadline_queue"
            elif req.error == KV_OOM_ERROR:
                # KV admission shed: pages free as in-flight requests
                # finish — back off and retry, like queue_full.
                outcome = "kv_oom"
            elif req.error == RETRIES_EXHAUSTED_ERROR:
                # The supervisor's give-up: the request rode its full
                # attempts budget through replica failures.
                outcome = "retries_exhausted"
            else:
                outcome = "error"
            return self._finish(handler, code, {"error": req.error},
                                outcome,
                                retry if code == 503 else None,
                                elapsed_s=elapsed, req=req)
        body_out = {
            "id": req.request_id,
            "tokens": req.tokens,
            "truncated": req.truncated,
            "timings": req.timings_ms(),
        }
        lease = req.kv_lease
        if lease is not None:
            # How much prefill the prefix cache skipped — the client-
            # visible proof that sharing worked (bench section 8 keys
            # on it) — and WHERE the skip was served from (ISSUE 17:
            # cached_tokens alone can't distinguish an HBM hit from a
            # host-tier restore or a cross-replica pull).
            body_out["kv"] = {"cached_tokens": lease.cached_tokens,
                              "blocks": len(lease.blocks),
                              "cached_by_tier": dict(
                                  lease.cached_by_tier)}
        self._finish(handler, 200, body_out, "ok", elapsed_s=elapsed,
                     req=req)

    def _prompt_tokens(self, body: dict, max_tokens: int) -> list:
        """Token-plane prompt parsing (paged-KV pools): explicit
        ``prompt_tokens`` (ints in [0, vocab)) or a ``prompt`` string
        through the deterministic stand-in tokenizer. Validated once
        at the front door, like prompt_vec: width AND the worst-case
        context (prompt + max_tokens must fit the replicas' block
        tables)."""
        if "prompt_tokens" in body:
            toks = body["prompt_tokens"]
            if (not isinstance(toks, list) or not toks
                    or not all(isinstance(t, int)
                               and not isinstance(t, bool)
                               and 0 <= t < self.vocab for t in toks)):
                raise ValueError(
                    f"prompt_tokens must be a non-empty list of ints "
                    f"in [0, {self.vocab})")
        else:
            prompt = body.get("prompt")
            if not isinstance(prompt, str) or not prompt:
                raise ValueError(
                    "need 'prompt' (string) or 'prompt_tokens'")
            n = min(16, max(1, self.max_context - max_tokens))
            toks = encode_prompt_tokens(prompt, n, self.vocab)
        if len(toks) + max_tokens > self.max_context:
            raise ValueError(
                f"prompt ({len(toks)} tokens) + max_tokens "
                f"({max_tokens}) exceeds max context "
                f"{self.max_context}")
        return toks

    def _prompt_vec(self, body: dict) -> np.ndarray:
        if "prompt_vec" in body:
            vec = np.asarray(body["prompt_vec"], dtype=np.float32)
            if vec.shape != (self.d,):
                raise ValueError(
                    f"prompt_vec must be [{self.d}] floats, "
                    f"got shape {list(vec.shape)}")
            if not np.isfinite(vec).all():
                # Same json.loads quirk as deadline_ms: Infinity/NaN
                # literals parse fine and would decode garbage tokens.
                raise ValueError("prompt_vec must be finite")
            return vec
        prompt = body.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            raise ValueError("need 'prompt' (string) or 'prompt_vec'")
        return encode_prompt(prompt, self.d)
