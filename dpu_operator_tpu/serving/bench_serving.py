"""bench_serving — open- and closed-loop load over the REAL HTTP path.

Decompose-then-optimize, the serving edition. The plane under test is
the one this package owns: HTTP front-end → admission queue →
continuous-batching scheduler → executor seam. The model's step cost
is bench_tpu's domain, so the HEADLINE figures drive a FIXED-cost
executor (SyntheticExecutor, --step-ms): on an MXU-bound chip a decode
step prices a full batch the same as one row — the premise continuous
batching exploits — and pinning that cost makes the figures move on
scheduler/queue/HTTP regressions and NOTHING else (a jitted CPU matmul
would re-measure the host's FLOPs and drown the plane in model noise).
The real jitted path (LocalExecutor over the train_step model on a jax
mesh) runs alongside as `serving_local_*` so every bench run exercises
the full stack end to end.

Sections:
  0. (below as section 6) fault recovery — one replica killed mid-run
     at 2x overload by a deterministic injected executor failure:
     serving_recovery_ms (kill → pool back to full live replicas) and
     serving_fault_goodput_retention (completion rate during the
     outage vs before it)
  1. closed-loop, continuous batching  → serving_reqs_per_s,
     serving_tok_per_s, serving_p50/p95/p99_ms
  2. closed-loop, serial batch=1       → serving_serial_reqs_per_s,
     serving_batching_speedup (the continuous-batching win)
  3. open-loop at ~2x measured capacity, small queue → bounded p99 for
     admitted work + 503 shed fraction (serving_overload_p99_ms,
     serving_overload_shed_frac, serving_overload_admitted_per_s) and
     the server must still answer /healthz after the storm
  4. the jitted-model path             → serving_local_reqs_per_s,
     serving_local_p99_ms
  5. decode-loop decomposition (ISSUE 3): the REAL scheduler over the
     REAL jitted model, queue preloaded, no HTTP — the synchronous
     PR 2 LocalExecutor vs the device-resident pipelined one at the
     same slot count. steps/s here is USEFUL steps (decoded tokens ÷
     slots per second): pipeline hand-off steps and partial-occupancy
     drain count against it, so the figure cannot be inflated by
     decoding stale rows. → serving_steps_per_s (pipelined, headline),
     serving_sync_steps_per_s, serving_pipeline_speedup, and the
     device-vs-host-gap split (serving_step_device_ms,
     serving_host_gap_ms, serving_host_gap_frac) from the scheduler's
     own histograms.
  7. tracing overhead (ISSUE 6): the section-5 pipelined loop with the
     obs tracer enabled vs disabled, interleaved best-of →
     serving_trace_overhead_frac (absolute gate <= 0.02 — always-on
     tracing must stay always-on cheap), serving_traced_steps_per_s.
  8. paged-KV decode (ISSUE 7): token-plane replicas (chunked prefill
     + prefix cache) through the real HTTP path at 2x overload, with
     and without prefix sharing → serving_tokens_per_s (headline,
     gated >= 0.85x rolling median), serving_tokens_per_s_user,
     serving_kv_prefix_speedup (shared/unique), the shared arm's
     serving_kv_prefix_hit_frac, and serving_prefill_stall_frac
     (decode steps that co-ran with prefill chunks; gated <= 1.35x
     rolling median — creeping stall means the chunk budget is
     rotting).
  9. sharded-vs-local decode decomposition (ISSUE 8): the REAL
     scheduler over a FabricExecutor whose replica spans a
     SyntheticShardSet (fixed per-shard compute + collective cost —
     the shard plane's accelerator cost model, same reasoning as the
     fixed-cost headline figures: the numbers move on
     coordinator/shard-plane scheduling regressions and nothing
     else), vs the single-host SyntheticExecutor paying only the
     compute. → serving_sharded_steps_per_s (gated >= 0.85x rolling
     median), serving_shard_collective_frac (share of the run wall
     the step spent BLOCKED on the collective; gated <= 1.35x — creep
     means the coordinator is serializing around the reduce). Since
     ISSUE 9 the headline arm runs OVERLAP-ON (forward_overlapped's
     double-buffered block schedule hides collective time behind the
     next block's compute), with the overlap-off twin recorded
     alongside (serving_shard_collective_frac_off,
     serving_sharded_steps_per_s_off — the paired best-of-3 the
     overlap claim is made against), plus
     serving_sharded_vs_local_frac and serving_shard_step_skew_ms
     (informational: the fabric tax and the shard imbalance).
 10. cross-process tracing overhead (ISSUE 11): a DIRECT-COST
     decomposition — the exact per-step op sequences the traced shard
     plane adds (worker: records + harvest + ship flush + spans json;
     coordinator: shard.step + per-rank ClockSync + ingest, ×world)
     measured in a deterministic tight loop, divided by the untraced
     sharded pipelined step wall (section-9 cost model through the
     executor seam) → serving_sharded_trace_overhead_frac (absolute
     gate <= 0.02), with serving_sharded_trace_worker/coord_us and
     traced/untraced seam steps/s alongside. Three throughput-ratio
     designs were measured and rejected (GIL-convoy amplification on
     thread shards; 3-5x cgroup-throttle swings on subprocess
     workers) — see the section docstring. The piggyback adds zero
     protocol round trips by construction; this section prices its
     CPU side.
 11. fused paged attention + quantized KV residency (ISSUE 13): one
     PagedDecodeStep step timed block_until_ready at steady full-slot
     decode — serving_paged_attn_device_ms (deployed kernel: pallas
     on TPU, compiled XLA on CPU; gated <= 1.35x rolling median) with
     the xla/fp32/pallas decomposition alongside, a live
     interpret-mode Pallas-vs-XLA equivalence check on CPU
     (serving_paged_attn_equiv_ok — correctness instead of perf, per
     the acceptance), and the residency accounting:
     serving_kv_bytes_per_slot (int8) vs fp32 →
     serving_kv_bytes_reduction, gated ABSOLUTE >= 3.5x.

Protocol: exactly one JSON object on stdout; progress on stderr.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple


def _post_full(url: str, body: dict, timeout: float = 120.0
               ) -> Tuple[int, float, Optional[dict]]:
    """(status, latency_ms, parsed_200_body_or_None): ONE copy of the
    request/error discipline every section shares — HTTPError bodies
    drained, connection-level failures under an overload thread storm
    counted as code 0 instead of crashing the client thread."""
    data = json.dumps(body).encode()
    parsed = None
    t0 = time.perf_counter()
    try:
        r = urllib.request.urlopen(
            urllib.request.Request(url + "/v1/generate", data=data),
            timeout=timeout)
        raw = r.read()
        code = r.status
        if code == 200:
            try:
                parsed = json.loads(raw)
            except ValueError:
                pass
    except urllib.error.HTTPError as e:
        try:
            e.read()
        except OSError:
            pass
        code = e.code
    except OSError:
        code = 0
    return code, (time.perf_counter() - t0) * 1000.0, parsed


def _post(url: str, body: dict, timeout: float = 120.0
          ) -> Tuple[int, float, int]:
    """(status, latency_ms, n_tokens). n_tokens is the ACTUAL decoded
    token count from a 200 body (-1 otherwise): deadline-truncated
    responses are 200s with fewer than max_tokens tokens, and any
    per-user throughput derived from the request's max_tokens would
    overstate exactly the overloaded regime the bench measures."""
    code, ms, parsed = _post_full(url, body, timeout)
    ntok = -1
    if parsed is not None:
        try:
            ntok = len(parsed.get("tokens", ()))
        except (AttributeError, TypeError):
            pass
    return code, ms, ntok


def nearest_rank(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank (ceil) percentile over a SORTED sample list: p99
    over <100 samples must still be able to land on the worst
    observation — int() truncation would exclude it. The one percentile
    convention for serving measurements (tests import it too)."""
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(0, math.ceil(n * q) - 1))]


def _quantiles(lat: List[float]) -> dict:
    if not lat:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(lat)
    return {"p50": round(nearest_rank(s, 0.50), 2),
            "p95": round(nearest_rank(s, 0.95), 2),
            "p99": round(nearest_rank(s, 0.99), 2)}


def closed_loop(url: str, clients: int, per_client: int,
                max_tokens: int, deadline_ms: float = 120_000.0):
    lat, codes = [], []
    lock = threading.Lock()

    def run(c):
        for i in range(per_client):
            code, ms, _ = _post(url, {"prompt": f"c{c}-{i}",
                                      "max_tokens": max_tokens,
                                      "deadline_ms": deadline_ms})
            with lock:
                codes.append(code)
                if code == 200:
                    lat.append(ms)

    ts = [threading.Thread(target=run, args=(c,)) for c in range(clients)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    return wall, lat, codes


def open_loop(url: str, rate_per_s: float, seconds: float,
              max_tokens: int, deadline_ms: float,
              on_tick=None, completions: Optional[list] = None,
              body_fn=None, tok_lat: Optional[list] = None):
    """Fixed-rate arrivals regardless of completions — the load shape
    that exposes queue growth (closed-loop self-throttles; an open
    loop does not, which is why overload must be measured this way).
    `on_tick(elapsed_s)` runs once per arrival before it is paced
    (the fault-recovery section arms its mid-run kill there);
    `completions`, when given, collects (code, time.monotonic())
    per finished request (same section's goodput windows); `body_fn(i)`
    overrides the request body (the paged-KV section posts
    prompt_tokens instead of a prompt string); `tok_lat`, when given,
    collects (n_tokens, latency_ms) per 200 — the actual decoded
    count, so truncated responses weigh what they delivered."""
    lat, codes = [], []
    lock = threading.Lock()
    threads: List[threading.Thread] = []

    def one(i):
        body = (body_fn(i) if body_fn is not None
                else {"prompt": f"o{i}"})
        body.setdefault("max_tokens", max_tokens)
        body.setdefault("deadline_ms", deadline_ms)
        code, ms, ntok = _post(url, body)
        with lock:
            codes.append(code)
            if code == 200:
                lat.append(ms)
                if tok_lat is not None and ntok >= 0:
                    tok_lat.append((ntok, ms))
            if completions is not None:
                completions.append((code, time.monotonic()))

    n = int(rate_per_s * seconds)
    t0 = time.perf_counter()
    for i in range(n):
        now = time.perf_counter()
        if on_tick is not None:
            on_tick(now - t0)
        target = t0 + i / rate_per_s
        if target > now:
            time.sleep(target - now)
        th = threading.Thread(target=one, args=(i,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=deadline_ms / 1000.0 + 30)
    wall = time.perf_counter() - t0
    return wall, lat, codes


def decode_loop_rates(slots: int, model: dict, n_req: int,
                      toks: int, trace, repeats: int = 3) -> dict:
    """Section 5: steps/s through the real ContinuousBatcher for the
    sync vs pipelined LocalExecutor. The queue is preloaded and driven
    without HTTP so the figure measures the decode loop (scheduler
    bookkeeping vs device step), not the GIL-bound front-end. Each
    executor compiles once, then the modes run INTERLEAVED `repeats`
    times and the best wall per mode is kept — the shared-box defense:
    a noisy neighbour lands on both modes or neither, and best-of
    discards the hits (same reasoning as the fabric bench's paired
    in-bench samples). The device/host-gap split comes from the
    scheduler's own histograms on the best pipelined run."""
    import time as _time

    from ..utils.metrics import Registry
    from .api import GenerateRequest, encode_prompt
    from .executor import LocalExecutor
    from .queue import AdmissionQueue
    from .scheduler import ContinuousBatcher

    out: dict = {}
    tok_total = n_req * toks
    execs: dict = {}
    for mode in ("sync", "pipelined"):
        t0 = _time.perf_counter()
        execs[mode] = LocalExecutor(slots=slots, mode=mode, **model)
        if mode == "pipelined":
            out["serving_decode_compile_s"] = round(
                _time.perf_counter() - t0, 2)

    def one_run(mode):
        ex = execs[mode]
        reg = Registry()
        q = AdmissionQueue(max_depth=n_req + 1)
        b = ContinuousBatcher(ex, q, registry=reg)
        reqs = [GenerateRequest(
            prompt_vec=encode_prompt(f"decode-{i}", ex.d),
            max_tokens=toks, deadline=_time.monotonic() + 600.0)
            for i in range(n_req)]
        for r in reqs:
            q.submit(r)
        t0 = _time.perf_counter()
        b.start()
        ok = all(r.wait(timeout=600) for r in reqs)
        wall = _time.perf_counter() - t0
        b.stop()
        if not ok or any(r.error for r in reqs):
            raise RuntimeError(next(
                (r.error for r in reqs if r.error), "request lost"))
        # Useful steps: tokens delivered / slots — pipeline hand-off
        # steps and drain-tail partial occupancy count AGAINST the
        # rate, so stale-row decodes can't inflate it.
        return (tok_total / slots) / wall, reg, b.steps

    try:
        for mode in ("sync", "pipelined"):
            one_run(mode)  # unrecorded warm-up: first post-compile
            # loop runs measurably cold (allocator/cache warmth)
        best: dict = {}
        for rep in range(repeats):
            for mode in ("sync", "pipelined"):
                rate, reg, steps = one_run(mode)
                trace(f"decode {mode} rep{rep}: {rate:.0f} useful "
                      f"steps/s ({steps} loop steps)")
                if mode not in best or rate > best[mode][0]:
                    best[mode] = (rate, reg)
    finally:
        for ex in execs.values():
            ex.close()

    out["serving_sync_steps_per_s"] = round(best["sync"][0], 1)
    out["serving_steps_per_s"] = round(best["pipelined"][0], 1)
    out["serving_pipeline_speedup"] = round(
        best["pipelined"][0] / best["sync"][0], 2)
    reg = best["pipelined"][1]
    dev = sum(s for s, _ in reg.histogram_totals(
        "serving_step_device_seconds").values())
    dev_n = sum(n for _, n in reg.histogram_totals(
        "serving_step_device_seconds").values())
    gap = sum(s for s, _ in reg.histogram_totals(
        "serving_host_gap_seconds").values())
    gap_n = sum(n for _, n in reg.histogram_totals(
        "serving_host_gap_seconds").values())
    if dev + gap > 0:
        out["serving_host_gap_frac"] = round(gap / (dev + gap), 3)
    if dev_n:
        out["serving_step_device_ms"] = round(dev / dev_n * 1000, 3)
    if gap_n:
        out["serving_host_gap_ms"] = round(gap / gap_n * 1000, 3)
    trace(f"decode: pipelined {out['serving_steps_per_s']} vs sync "
          f"{out['serving_sync_steps_per_s']} useful steps/s = "
          f"{out['serving_pipeline_speedup']}x, host-gap frac "
          f"{out.get('serving_host_gap_frac')}")
    return out


def trace_overhead(slots: int, model: dict, n_req: int, toks: int,
                   trace, repeats: int = 5) -> dict:
    """Section 7 (ISSUE 6): the always-on price of tracing. The SAME
    pipelined decode loop as section 5, run with the tracer enabled vs
    disabled in back-to-back INTERLEAVED pairs; the figure is the
    MEDIAN of the per-pair rate ratios:

      serving_trace_overhead_frac = max(0, 1 - median(on_i / off_i))

    Per-pair ratios, not best-of-per-arm: each run here is a few
    hundred ms, and on a shared 2-core box run-to-run swing (~10-15%)
    dwarfs the effect being measured — a slow patch lands on BOTH
    halves of a pair and cancels in the ratio, and the median discards
    the pairs a noisy neighbour split down the middle (best-of compares
    two different patches of box weather and measured tracing as
    *negative* overhead as often as 15%).

    Gated ABSOLUTE in bench.py at <= 0.02 — tracing that costs more
    than 2% of steps/s is a regression no rolling median should ever
    absorb, because the whole design premise ("always-on cheap") dies
    with it."""
    import statistics
    import time as _time

    from ..obs import trace as obs_trace
    from .api import GenerateRequest, encode_prompt
    from .executor import LocalExecutor
    from .queue import AdmissionQueue
    from .scheduler import ContinuousBatcher

    ex = LocalExecutor(slots=slots, mode="pipelined", **model)
    tok_total = n_req * toks

    def one_run() -> float:
        q = AdmissionQueue(max_depth=n_req + 1)
        b = ContinuousBatcher(ex, q)
        reqs = [GenerateRequest(
            prompt_vec=encode_prompt(f"trace-{i}", ex.d),
            max_tokens=toks, deadline=_time.monotonic() + 600.0)
            for i in range(n_req)]
        for r in reqs:
            q.submit(r)
        t0 = _time.perf_counter()
        b.start()
        ok = all(r.wait(timeout=600) for r in reqs)
        wall = _time.perf_counter() - t0
        b.stop()
        if not ok or any(r.error for r in reqs):
            raise RuntimeError(next(
                (r.error for r in reqs if r.error), "request lost"))
        return (tok_total / slots) / wall

    out: dict = {}
    tr = obs_trace.get_tracer()
    ratios: List[float] = []
    rates = {"on": [], "off": []}
    try:
        for arm in (True, False):  # unrecorded warm-up per arm
            tr.enabled = arm
            one_run()
        for rep in range(repeats):
            pair = {"on": 0.0, "off": 0.0}
            # Best-of-2 per arm INSIDE the pair, arms alternating and
            # the leading arm flipping per pair: a CPU-throttle window
            # (the dominant noise on CI-class containers — whole runs
            # halve) is discarded by the inner best-of, and slow drift
            # cannot systematically favour one arm.
            order = ("on", "off", "on", "off") if rep % 2 == 0 \
                else ("off", "on", "off", "on")
            for arm in order:
                tr.enabled = arm == "on"
                r = one_run()
                pair[arm] = max(pair[arm], r)
                rates[arm].append(r)
            ratios.append(pair["on"] / pair["off"])
            trace(f"trace pair {rep}: on {pair['on']:.0f} / off "
                  f"{pair['off']:.0f} steps/s = ratio "
                  f"{ratios[-1]:.3f}")
            # Bound tracer memory across reps: each run's batcher
            # thread leaves a buffer until drained.
            tr.clear()
    finally:
        tr.enabled = True
        ex.close()

    out["serving_traced_steps_per_s"] = round(max(rates["on"]), 1)
    out["serving_untraced_steps_per_s"] = round(max(rates["off"]), 1)
    out["serving_trace_overhead_frac"] = round(
        max(0.0, 1.0 - statistics.median(ratios)), 4)
    trace(f"trace overhead: {out['serving_trace_overhead_frac']} "
          f"(median of {len(ratios)} paired ratios)")
    return out


def sharded_trace_overhead(slots: int, trace, world: int = 3,
                           iters: int = 4000,
                           step_ms: float = 2.0,
                           coll_ms: float = 1.0) -> dict:
    """Section 10 (ISSUE 11): the always-on price of CROSS-PROCESS
    tracing, as a DIRECT-COST decomposition:

      serving_sharded_trace_overhead_frac =
          (per-step tracing cost) / (untraced sharded step wall)

    The numerator is measured as a tight loop over the EXACT per-step
    op sequences the traced plane adds — the worker side (reserve +
    shard.compute/reduce_blocked records + tracer harvest + ship
    flush + the reply's spans json) and the coordinator side
    (shard.step reserve/record + per-rank ClockSync.observe/estimate
    + per-rank Tracer.ingest of a representative shipment, ×world) —
    deterministic CPU-bound work a throttled container measures to µs
    precision. The denominator is the untraced sharded pipelined step
    wall: the section-9 cost model (2 ms compute + 1 ms collective)
    driven through the FabricExecutor seam with one step in flight,
    median of 3 runs.

    Why not a traced-vs-untraced throughput ratio like section 7?
    Three of them were built and rejected with data: (a) synthetic
    thread shards share the GIL with the coordinator, so µs of
    coordinator-side recording amplify through the interpreter's 5 ms
    switch interval into a fake ~7% "overhead" no multi-process
    deployment pays; (b/c) real shard_worker subprocesses (world 1
    and 2) put the effect under genuine shipping, but this
    cpu-share-throttled container swings identical runs 3-5x, so a
    ±2% bound is unresolvable at any affordable repeat count (pair
    ratios observed 0.6-2.2). The direct decomposition prices every
    op the traced plane adds to the hot path — a regression in any of
    them (a slow record, an O(n²) ingest, a leaking harvest) moves
    the numerator immediately — while staying deterministic. Gated
    ABSOLUTE ≤ 0.02 in bench.py. The piggyback itself adds zero
    protocol round trips by construction (spans/metrics/clock stamps
    ride reply frames that exist anyway); the json term above is its
    entire marginal wire-side CPU."""
    import json as _json
    import statistics
    import time as _time

    from ..obs import trace as obs_trace
    from ..obs.xproc import ClockSync, SpanShip
    from ..utils.metrics import Registry
    from .sharded import FabricExecutor, SyntheticShardSet

    import numpy as np

    d = 16
    out: dict = {}

    # -- numerator: per-step tracing cost, worker side ------------------------
    wtr = obs_trace.Tracer()
    ship = SpanShip(cap=512)
    wreg = Registry()
    t0 = _time.perf_counter()
    for k in range(iters):
        sid = wtr.reserve_id()
        m = _time.monotonic()
        wtr.record_span("shard.reduce_blocked", m, m + 0.001,
                        parent_id=sid,
                        attrs={"rank": 0, "step": k, "stage": 0})
        wtr.record_span("shard.compute", m, m + 0.002, span_id=sid,
                        attrs={"rank": 0, "step": k,
                               "compute_s": 0.001,
                               "collective_s": 0.001,
                               "xparent": 12345})
        wreg.observe("shard_step_compute_seconds", 0.001)
        wreg.observe("shard_step_collective_seconds", 0.001)
        wreg.counter_inc("shard_steps_total")
        ship.harvest(wtr)
        wire = ship.flush()
        _json.dumps({"op": "tokens", "step": k, "compute_s": 0.001,
                     "collective_s": 0.001, "t_rx": m, "t_tx": m,
                     "spans": wire, "spans_dropped": 0})
    worker_us = (_time.perf_counter() - t0) / iters * 1e6

    # -- numerator: coordinator side (ingest scales with world) ---------------
    def rank_shipment(r):
        # FRESH tuples+dicts per iteration, like the real path (each
        # reply's spans parse off the wire into new objects): ingest
        # takes ownership and mutates attrs in place, so reusing one
        # shipment would measure the xparent branch exactly once and
        # alias every ingested span onto one dict.
        return [
            ("shard.compute", 2 * r + 1, None, None, "span", 1.0,
             1.002, {"rank": r, "step": 1, "compute_s": 0.001,
                     "collective_s": 0.001, "xparent": 12345}),
            ("shard.reduce_blocked", 2 * r + 2, 2 * r + 1, None,
             "span", 1.0, 1.001, {"rank": r, "step": 1, "stage": 0}),
        ]

    ctr = obs_trace.Tracer()
    syncs = [ClockSync() for _ in range(world)]
    rids = [f"req-{i}" for i in range(slots)]
    coord_acc = 0.0
    for k in range(iters):
        # Shipment construction sits OUTSIDE the timed region: on the
        # real path those dicts come off the wire via recv_msg's json
        # parse — protocol cost, not the tracing plane's.
        ships = [rank_shipment(r) for r in range(world)]
        t0 = _time.perf_counter()
        sid = ctr.reserve_id()
        m = _time.monotonic()
        ctr.record_span("shard.step", m, m + 0.003, span_id=sid,
                        attrs={"replica": "bench", "step": k,
                               "world": world, "codec": "fp32",
                               "request_ids": rids})
        for r in range(world):
            syncs[r].observe(m, m + 0.0005, m + 0.0025, m + 0.003)
            off, unc = syncs[r].estimate
            ctr.ingest(ships[r], offset=off,
                       attrs={"clock_offset_s": round(off, 6),
                              "clock_unc_s": round(unc, 6)})
        coord_acc += _time.perf_counter() - t0
        if k % 64 == 0:
            # Realistic ring churn: a server's scrape path drains.
            ctr.clear()
    coord_us = coord_acc / iters * 1e6

    # -- denominator + informational steps/s: the seam loop -------------------
    def seam_run(ex, n_steps=200):
        row = np.ones(d, np.float32)
        t0 = _time.perf_counter()
        prev = ex.submit([(0, row)], occupants=rids[:1])
        for _ in range(n_steps - 1):
            h = ex.submit([(0, row)], occupants=rids[:1])
            # Bounded inside: FabricExecutor.collect gathers under
            # its own step_timeout_s deadline (the GL010 contract
            # lives one layer down).
            ex.collect(prev)  # graftlint: disable=GL010
            prev = h
        ex.collect(prev)
        return n_steps / (_time.perf_counter() - t0)

    tr = obs_trace.get_tracer()
    rates = {"on": [], "off": []}
    ex = FabricExecutor(
        SyntheticShardSet(world=world, slots=slots, d=d, seed=7,
                          step_time_s=step_ms / 1000.0,
                          collective_time_s=coll_ms / 1000.0),
        mode="pipelined", name="trace-bench")
    try:
        ex.reset()
        for arm in ("on", "off"):
            tr.enabled = arm == "on"
            seam_run(ex, n_steps=50)  # warm-up
            for _ in range(3):
                rates[arm].append(seam_run(ex))
            tr.clear()
    finally:
        tr.enabled = True
        ex.close()
    step_wall_us = 1e6 / statistics.median(rates["off"])

    frac = (worker_us + coord_us) / step_wall_us
    out["serving_sharded_trace_cost_us"] = round(
        worker_us + coord_us, 1)
    out["serving_sharded_trace_worker_us"] = round(worker_us, 1)
    out["serving_sharded_trace_coord_us"] = round(coord_us, 1)
    out["serving_sharded_traced_steps_per_s"] = round(
        statistics.median(rates["on"]), 1)
    out["serving_sharded_untraced_steps_per_s"] = round(
        statistics.median(rates["off"]), 1)
    out["serving_sharded_trace_overhead_frac"] = round(frac, 4)
    trace(f"sharded trace overhead: worker {worker_us:.1f}us + "
          f"coord {coord_us:.1f}us per step over a "
          f"{step_wall_us:.0f}us untraced step = {frac:.4f}")
    return out


def fault_recovery(slots: int, step_s: float, reqs_per_s: float,
                   trace, seconds: float = 4.0, kill_at_s: float = 1.2
                   ) -> dict:
    """Section 6 (ISSUE 5): self-healing under fire. Two synthetic
    replicas behind the supervised pool, an open loop at ~2x measured
    capacity, and ONE deterministic injected replica kill mid-run
    (times=1 spec armed at t=kill_at_s; the fire timestamp is the
    kill's ground truth). Records:

      serving_recovery_ms            kill -> pool back to full live
                                     replica count (sampled at 2 ms)
      serving_fault_goodput_retention  200-completions/s inside the
                                     outage window / the pre-kill rate
      serving_fault_requeued         requests seized + re-admitted

    The recovery gate in bench.py holds serving_recovery_ms to 1.35x
    its rolling median — restart/backoff/watchdog regressions move it
    even when throughput noise hides them."""
    from dpu_operator_tpu import faults

    from .executor import SyntheticExecutor
    from .server import ServingServer

    plan = faults.install(seed=0)
    site = "bench-r0"
    ex0 = faults.FaultyExecutor(
        SyntheticExecutor(slots=slots, d=16, step_time_s=step_s),
        site=site)
    ex1 = SyntheticExecutor(slots=slots, d=16, step_time_s=step_s)
    srv = ServingServer(
        [ex0, ex1], max_queue_depth=4 * slots,
        pool_opts=dict(watchdog_s=1.0, restart_backoff_s=0.02,
                       poll_s=0.002, max_attempts=5)).start()
    out: dict = {}
    try:
        closed_loop(srv.url, 2, 2, 2)  # warm the path
        rate = 2.0 * max(reqs_per_s, 1.0)
        done: List[Tuple[int, float]] = []  # (code, finish time)
        live_samples: List[Tuple[float, int, int]] = []
        stop_sampler = threading.Event()

        def sampler():
            while not stop_sampler.is_set():
                live_samples.append(
                    (time.monotonic(), srv.pool.live_count(),
                     sum(srv.pool.restarts)))
                stop_sampler.wait(0.002)

        armed = [False]

        def arm_kill(elapsed_s):
            if not armed[0] and elapsed_s >= kill_at_s:
                plan.inject(f"{site}.step",
                            exc=RuntimeError("bench: injected kill"),
                            times=1)
                armed[0] = True

        samp = threading.Thread(target=sampler, daemon=True)
        samp.start()
        t0 = time.monotonic()
        open_loop(srv.url, rate, seconds, 8, 4000.0,
                  on_tick=arm_kill, completions=done)
        stop_sampler.set()
        samp.join(timeout=1.0)

        kill_ts = plan.fired_at.get(f"{site}.step")
        if not kill_ts:
            out["serving_fault_error"] = "kill never fired"
            return out
        kill_t = kill_ts[0]
        # Recovery = kill -> (a restart has happened AND the pool is
        # back at full strength). Gating on the restart counter keeps
        # a pre-detection "still looks live" sample from reading as an
        # instant recovery.
        recovered_t = next(
            (ts for ts, live, restarts in live_samples
             if ts > kill_t and restarts >= 1 and live == 2), None)
        if recovered_t is None:
            out["serving_fault_error"] = "pool never recovered"
            return out
        out["serving_recovery_ms"] = round(
            (recovered_t - kill_t) * 1000.0, 1)

        # Goodput retention: completion RATE inside the outage window
        # against the pre-kill steady rate. Windows padded to 0.25 s
        # (a sub-poll recovery must not divide by a sliver) and the
        # pre-kill window clamped to the load's actual start — letting
        # it reach before t0 would count an empty stretch as "steady
        # state" and flatter the retention figure.
        window = max(recovered_t - kill_t, 0.25)
        pre_window = min(window, max(kill_t - t0, 0.25))
        pre = sum(1 for c, ts in done
                  if c == 200 and kill_t - pre_window <= ts < kill_t)
        during = sum(1 for c, ts in done
                     if c == 200 and kill_t <= ts < kill_t + window)
        if pre > 0:
            out["serving_fault_goodput_retention"] = round(
                min((during / window) / (pre / pre_window), 1.0), 3)
        out["serving_fault_requeued"] = int(srv.queue.requeued)
        out["serving_fault_restarts"] = int(sum(srv.pool.restarts))
        trace(f"fault recovery: {out['serving_recovery_ms']} ms to "
              f"full strength, goodput retention "
              f"{out.get('serving_fault_goodput_retention')}, "
              f"{out['serving_fault_requeued']} requeued")
        return out
    finally:
        faults.uninstall()
        srv.stop()


def kv_paged_serving(slots: int, step_s: float, trace,
                     seconds: float = 2.5, max_tokens: int = 12,
                     prompt_len: int = 24) -> dict:
    """Section 8 (ISSUE 7): paged-KV decode through the REAL HTTP
    path. Two open-loop arms at ~2x measured capacity over synthetic
    token-plane replicas (fixed step cost — the scheduler/KV plane is
    what moves, not the host's FLOPs):

      * SHARED — every request draws one of 4 prompts, so after the
        first wave the prefix cache absorbs most prefill: the
        headline serving_tokens_per_s and serving_kv_prefix_hit_frac;
      * UNIQUE — per-request prompts, no sharing possible: the
        prefill-heavy arm, whose serving_prefill_stall_frac (decode
        steps that co-ran with prefill chunks / all decode steps) is
        the chunked-prefill interleave exposure the gate watches.

    serving_kv_prefix_speedup = shared/unique decode-token throughput:
    what prefix reuse is worth at 2x overload."""
    import statistics

    from .api import encode_prompt_tokens
    from .kvcache import SyntheticKVExecutor
    from .server import ServingServer

    out: dict = {}
    arms: dict = {}
    chunk = 8
    for arm in ("shared", "unique"):
        ex = SyntheticKVExecutor(
            slots=slots, vocab=64, block_size=4, num_blocks=1024,
            max_blocks_per_req=16, prefill_chunk=chunk,
            step_time_s=step_s, pipelined=True)
        srv = ServingServer([ex], max_queue_depth=4 * slots).start()
        try:
            def body(i, arm=arm):
                text = (f"kv-{i % 4}" if arm == "shared"
                        else f"kv-uniq-{i}")
                return {"prompt_tokens": encode_prompt_tokens(
                    text, prompt_len, 64)}

            # Warm the path (indices far outside the measured range so
            # the unique arm's cache stays cold), then drive 2x the
            # ANALYTIC capacity: slots / (per-request steps x step
            # cost) — the serial warm posts under-measure a
            # continuous-batching server by ~slots x.
            for i in range(2 * slots):
                _post(srv.url, dict(body(10 ** 6 + i),
                                    max_tokens=max_tokens,
                                    deadline_ms=30000))
            steps_per_req = -(-prompt_len // chunk) + max_tokens
            cap = slots / max(steps_per_req * step_s, 1e-4)
            rate = 2.0 * max(cap, 4.0)
            pre = ex.kv_stats()
            tok_lat: list = []
            t0 = time.perf_counter()
            wall, lat, codes = open_loop(
                srv.url, rate, seconds, max_tokens, 4000.0,
                body_fn=body, tok_lat=tok_lat)
            post = ex.kv_stats()
            n_ok = sum(1 for c in codes if c == 200)
            dec = post["decode_tokens"] - pre["decode_tokens"]
            lookup = (post["prefix_lookup_tokens"]
                      - pre["prefix_lookup_tokens"])
            hit = (post["prefix_hit_tokens"]
                   - pre["prefix_hit_tokens"])
            dsteps = post["steps_decode"] - pre["steps_decode"]
            msteps = post["steps_mixed"] - pre["steps_mixed"]
            arms[arm] = {
                "tok_per_s": dec / wall,
                # Actual decoded tokens per response, NOT max_tokens:
                # deadline-truncated 200s deliver fewer, and they
                # cluster exactly in the overload this section drives.
                "tok_per_s_user": (statistics.mean(
                    n / (ms / 1000.0) for n, ms in tok_lat)
                    if tok_lat else 0.0),
                "hit_frac": hit / lookup if lookup else 0.0,
                "stall_frac": msteps / dsteps if dsteps else 0.0,
                "admitted_per_s": n_ok / wall,
                "shed_frac": sum(1 for c in codes
                                 if c == 503) / max(1, len(codes)),
            }
            trace(f"kv {arm} @{rate:.0f}/s: "
                  f"{arms[arm]['tok_per_s']:.0f} tok/s "
                  f"({arms[arm]['tok_per_s_user']:.0f}/user), hit "
                  f"{arms[arm]['hit_frac']:.2f}, stall "
                  f"{arms[arm]['stall_frac']:.2f}, shed "
                  f"{arms[arm]['shed_frac']:.2f}")
        finally:
            srv.stop()
            ex.close()
        ex.allocator.assert_clean()

    out["serving_tokens_per_s"] = round(arms["shared"]["tok_per_s"], 1)
    out["serving_tokens_per_s_user"] = round(
        arms["shared"]["tok_per_s_user"], 1)
    out["serving_kv_prefix_hit_frac"] = round(
        arms["shared"]["hit_frac"], 3)
    # Stall exposure from the prefill-heavy arm: the shared arm's
    # cache absorbs prefill, which would make the gate's signal (a
    # rotting chunk budget) vanish into cache-hit noise.
    out["serving_prefill_stall_frac"] = round(
        arms["unique"]["stall_frac"], 3)
    if arms["unique"]["tok_per_s"] > 0:
        out["serving_kv_prefix_speedup"] = round(
            arms["shared"]["tok_per_s"] / arms["unique"]["tok_per_s"],
            2)
    return out


def disagg_serving(trace, slots: int = 4, step_ms: float = 2.0,
                   tok_ms: float = 0.4, seconds: float = 2.5) -> dict:
    """Section 12 (ISSUE 14): disaggregated vs colocated serving
    under a PREFILL FLOOD — the cross-replica isolation claim,
    measured. Cost model: SyntheticKVExecutor with a per-planned-
    token cost on top of the fixed floor (a step co-running an
    8-token prefill chunk really costs more than a pure-decode
    step — the physics that makes prefill able to stall decode
    INSIDE a shared batcher at all). Two arms, same total hardware
    (2 replicas), same workload:

      * decode-class requests (short prompt, 12 tokens) closed-loop,
        measuring PER-TOKEN decode latency from the response's own
        decode_ms/tokens decomposition;
      * a concurrent open-loop flood of long prompts (96 tokens,
        max_tokens=1 — pure prefill work) at ~2x the prefill plane's
        analytic capacity.

    Colocated: flood chunks co-run in the decode requests' steps, so
    every decode token pays the chunk's token cost (PR 7's budget
    bounds prefill per step; it cannot make co-scheduled tokens
    free). Disagg: no decode replica ever plans a prefill chunk, so
    decode per-token p99 holds flat — gated <= 1.35x rolling median
    as serving_decode_p99_ms, with the colocated twin and the
    isolation ratio informational. Also measured here: the page
    stream's transfer Gb/s on a realistic block payload (a pure
    loopback microbench of the framing + int8 codec path) and the
    transfer-vs-re-prefill breakeven."""
    from ..utils.metrics import Registry
    from .api import encode_prompt_tokens
    from .disagg import DisaggPool, KVPageStream, KVPageStreamServer
    from .disagg.spec import KVSpec
    from .kvcache import SyntheticKVExecutor
    from .server import ServingServer

    out: dict = {}
    step_s, tok_s = step_ms / 1000.0, tok_ms / 1000.0
    dec_prompt, dec_toks = 8, 12
    flood_prompt, chunk = 96, 8

    def mk():
        return SyntheticKVExecutor(
            slots=slots, vocab=64, block_size=4, num_blocks=4096,
            max_blocks_per_req=32, prefill_chunk=chunk,
            step_time_s=step_s, token_time_s=tok_s, pipelined=True)

    def post_body(url, body):
        code, _ms, parsed = _post_full(url, body, timeout=60)
        return code, parsed

    def run_arm(kind):
        reg = Registry()
        if kind == "disagg":
            pre, dec = mk(), mk()
            execs = [pre, dec]

            def factory(_execs, q, registry, tracer, flight_recorder):
                return DisaggPool([pre], [dec], q, registry=registry,
                                  tracer=tracer,
                                  flight_recorder=flight_recorder)

            srv = ServingServer(execs, registry=reg,
                                max_queue_depth=max(64, 8 * slots),
                                pool_factory=factory).start()
        else:
            execs = [mk(), mk()]
            srv = ServingServer(execs, registry=reg,
                                max_queue_depth=max(64, 8 * slots)
                                ).start()
        per_tok: list = []
        lock = threading.Lock()
        stop = threading.Event()
        try:
            # Warm both classes through the path once.
            post_body(srv.url, {
                "prompt_tokens": encode_prompt_tokens(
                    "warm-d", dec_prompt, 64),
                "max_tokens": 2, "deadline_ms": 20000})

            def decode_client(c):
                i = 0
                while not stop.is_set():
                    code, body = post_body(srv.url, {
                        "prompt_tokens": encode_prompt_tokens(
                            f"dec-{kind}-{c}-{i}", dec_prompt, 64),
                        "max_tokens": dec_toks,
                        "deadline_ms": 20000})
                    if code == 200 and body and body["tokens"]:
                        with lock:
                            per_tok.append(
                                body["timings"]["decode_ms"]
                                / len(body["tokens"]))
                    i += 1

            def flood_client(i):
                post_body(srv.url, {
                    "prompt_tokens": encode_prompt_tokens(
                        f"fl-{kind}-{i}", flood_prompt, 64),
                    "max_tokens": 1, "deadline_ms": 20000})

            dec_threads = [threading.Thread(target=decode_client,
                                            args=(c,), daemon=True)
                           for c in range(2)]
            for t in dec_threads:
                t.start()
            # Open-loop flood at ~2x the prefill plane's analytic
            # capacity: one flood request = ceil(96/8) chunk-steps,
            # each costing ~(step + slots*chunk*tok) at full
            # occupancy, over `slots` slots of one replica.
            steps_per_flood = -(-flood_prompt // chunk)
            step_wall = step_s + slots * chunk * tok_s
            cap = slots / max(steps_per_flood * step_wall, 1e-4)
            rate = 2.0 * cap
            n = int(rate * seconds)
            t0 = time.perf_counter()
            flood_threads = []
            for i in range(n):
                target = t0 + i / rate
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                th = threading.Thread(target=flood_client, args=(i,),
                                      daemon=True)
                th.start()
                flood_threads.append(th)
            for th in flood_threads:
                th.join(timeout=60)
            stop.set()
            for t in dec_threads:
                t.join(timeout=60)
        finally:
            stop.set()
            srv.stop()
        for ex in execs:
            ex.allocator.assert_clean()
            ex.close()
        samples = sorted(per_tok)
        res = {
            "p99_ms_per_tok": (nearest_rank(samples, 0.99)
                               if samples else None),
            "p50_ms_per_tok": (nearest_rank(samples, 0.50)
                               if samples else None),
            "n_decode": len(samples),
            "flood_rate": rate,
        }
        if kind == "disagg":
            bt = reg.counter_value("serving_kv_transfer_bytes_total",
                                   {"codec": "fp32"}) or 0.0
            totals = reg.histogram_totals("serving_kv_transfer_seconds")
            ssum = sum(v[0] for v in totals.values())
            scnt = sum(v[1] for v in totals.values())
            res["transfers"] = scnt
            res["transfer_ms_mean"] = (1000.0 * ssum / scnt
                                       if scnt else None)
            res["transfer_bytes"] = bt
        return res

    arms = {kind: run_arm(kind) for kind in ("colocated", "disagg")}
    for kind, a in arms.items():
        p99, p50 = (round(a[k], 2) if a[k] is not None else None
                    for k in ("p99_ms_per_tok", "p50_ms_per_tok"))
        trace(f"disagg arm {kind}: decode p99 {p99} ms/tok "
              f"(p50 {p50}) over {a['n_decode']} requests, "
              f"flood @{a['flood_rate']:.0f}/s")
    dis, col = arms["disagg"], arms["colocated"]
    # A loaded box can starve one arm's decode clients for the whole
    # window (all 503/deadline): report what exists instead of
    # crashing the section out of the gated metric.
    if dis["p99_ms_per_tok"] is not None:
        out["serving_decode_p99_ms"] = round(dis["p99_ms_per_tok"], 3)
    if col["p99_ms_per_tok"] is not None:
        out["serving_colocated_decode_p99_ms"] = round(
            col["p99_ms_per_tok"], 3)
    if dis["p99_ms_per_tok"] and col["p99_ms_per_tok"]:
        out["serving_disagg_isolation_x"] = round(
            col["p99_ms_per_tok"] / dis["p99_ms_per_tok"], 2)
    out["serving_kv_transfers"] = dis["transfers"]
    if dis["transfer_ms_mean"]:
        out["serving_kv_transfer_ms"] = round(dis["transfer_ms_mean"],
                                              3)
        # Breakeven: what re-prefilling a FLOOD-sized context would
        # cost in this cost model vs shipping its pages.
        reprefill_ms = (-(-flood_prompt // chunk)
                        * (step_ms + chunk * tok_ms))
        out["serving_kv_transfer_breakeven_x"] = round(
            reprefill_ms / dis["transfer_ms_mean"], 1)

    # The page stream's wire throughput on a REALISTIC block payload
    # (16-token blocks, 8 heads x 128 d_head, int8 codes + scales —
    # ~2 MiB/plane for 64 blocks), loopback, import discarded: prices
    # the framing + codec path itself, not the serving plane around
    # it.
    spec = KVSpec(model="paged", block_size=16, heads=8, d_head=128,
                  vocab=64, max_blocks_per_req=64, pool_dtype="int8")
    gb_srv = KVPageStreamServer(spec, lambda meta, planes: {})
    try:
        st = KVPageStream(spec, gb_srv.addr)
        n_blocks = 64
        rng = __import__("numpy").random.RandomState(0)
        codes = rng.randint(-127, 127, size=(
            n_blocks, 16, 8, 128)).astype("int8")
        scales = rng.rand(n_blocks).astype("float32")
        meta = {"req": "bench", "n_blocks": n_blocks,
                "tokens": n_blocks * 16}
        wire_bytes = spec.wire_block_nbytes("int8") * n_blocks
        st.send_pages(meta, [(codes, scales), (codes, scales)])  # warm
        walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            st.send_pages(meta, [(codes, scales), (codes, scales)])
            walls.append(time.perf_counter() - t0)
        st.close()
        best = min(walls)
        out["serving_kv_transfer_gbps"] = round(
            wire_bytes * 8 / 1e9 / best, 3)
        trace(f"kv page stream: {wire_bytes / 1e6:.1f} MB in "
              f"{best * 1e3:.2f} ms = "
              f"{out['serving_kv_transfer_gbps']} Gb/s (loopback)")
    finally:
        gb_srv.close()
    return out


def speculative_decode(trace, slots: int = 4, n_req: int = 24,
                       toks: int = 16, step_ms: float = 2.0,
                       tok_ms: float = 0.05, k: int = 4,
                       accept: float = 0.75, repeats: int = 3) -> dict:
    """Section 13 (ISSUE 15): speculative draft/verify decode vs the
    one-token baseline — ACCEPTED tokens/s/slot through the real
    ContinuousBatcher (queue preloaded, no HTTP), interleaved
    best-of-3. Cost model: SyntheticKVExecutor with a fixed per-step
    floor plus a per-planned-token cost, so a verify step really
    costs more than a one-token step (its window is k+1 wide) and
    the speedup is the honest ratio of that physics — fixed floor
    amortized over ~E[accepted+1] tokens — not a free lunch. The
    draft is the OracleDraft at a CONTROLLED per-position acceptance
    rate (`accept`), the dial the ISSUE 15 acceptance criterion
    (>= 1.5x at the controlled rate) is stated against.

      * serving_spec_tokens_per_s — accepted tokens/s/slot, spec arm
        (gated >= 0.85x rolling median in bench.py);
      * serving_spec_baseline_tokens_per_s — the PR 7 one-token
        pipelined arm on the same cost model;
      * serving_spec_speedup — the paired ratio (gated ABSOLUTE
        >= 1.5 in bench.py: the acceptance criterion itself);
      * serving_spec_accept_rate / serving_spec_tokens_per_step —
        the acceptance decomposition (realized rate: positions after
        a run's first miss count as rejected);
      * serving_spec_step_ms / serving_spec_baseline_step_ms — the
        per-step-cost decomposition (a verify step IS dearer; the
        win is tokens per step, and these two lines prove both
        halves)."""
    import time as _time

    from .api import GenerateRequest
    from .kvcache import SyntheticKVExecutor
    from .queue import AdmissionQueue
    from .scheduler import ContinuousBatcher
    from .spec import OracleDraft, SpecConfig

    out: dict = {}
    step_s, tok_s = step_ms / 1000.0, tok_ms / 1000.0
    prompt_len, vocab = 8, 64
    tok_total = n_req * toks

    def one_run(kind):
        spec = None
        if kind == "spec":
            spec = SpecConfig(OracleDraft(k=k, accept_rate=accept,
                                          vocab=vocab, target_seed=0),
                              k)
        ex = SyntheticKVExecutor(
            slots=slots, vocab=vocab, block_size=4, num_blocks=2048,
            max_blocks_per_req=16, prefill_chunk=8,
            step_time_s=step_s, token_time_s=tok_s,
            pipelined=kind == "baseline", spec=spec,
            prefix_cache=False)
        q = AdmissionQueue(max_depth=n_req + 1)
        b = ContinuousBatcher(ex, q)
        reqs = [GenerateRequest(
            prompt_vec=None, max_tokens=toks,
            deadline=_time.monotonic() + 600.0,
            prompt_tokens=[(3 * i + j) % vocab
                           for j in range(prompt_len)])
            for i in range(n_req)]
        for r in reqs:
            q.submit(r)
        t0 = _time.perf_counter()
        b.start()
        ok = all(r.wait(timeout=600) for r in reqs)
        wall = _time.perf_counter() - t0
        b.stop()
        if not ok or any(r.error for r in reqs):
            raise RuntimeError(next(
                (r.error for r in reqs if r.error), "request lost"))
        delivered = sum(len(r.tokens) for r in reqs)
        assert delivered == tok_total, (delivered, tok_total)
        stats = ex.kv_stats()
        steps = ex._step_no
        ex.allocator.assert_clean()
        ex.close()
        return (tok_total / slots) / wall, wall, steps, stats

    # Interleaved best-of-3: both arms share each rep's box weather,
    # the section-5/9 shared-box defense.
    best: dict = {}
    for rep in range(repeats):
        for kind in ("spec", "baseline"):
            rate, wall, steps, stats = one_run(kind)
            trace(f"spec-decode {kind} rep{rep}: {rate:.0f} accepted "
                  f"tok/s/slot over {steps} steps")
            if kind not in best or rate > best[kind][0]:
                best[kind] = (rate, wall, steps, stats)

    sp_rate, sp_wall, sp_steps, sp_stats = best["spec"]
    bl_rate, bl_wall, bl_steps, _ = best["baseline"]
    out["serving_spec_tokens_per_s"] = round(sp_rate, 1)
    out["serving_spec_baseline_tokens_per_s"] = round(bl_rate, 1)
    if bl_rate > 0:
        out["serving_spec_speedup"] = round(sp_rate / bl_rate, 2)
    out["serving_spec_accept_rate"] = sp_stats["spec_accept_rate"]
    out["serving_spec_tokens_per_step"] = sp_stats[
        "spec_tokens_per_step"]
    out["serving_spec_step_ms"] = round(sp_wall / sp_steps * 1000, 3)
    out["serving_spec_baseline_step_ms"] = round(
        bl_wall / bl_steps * 1000, 3)
    trace(f"speculative decode: {out['serving_spec_tokens_per_s']} "
          f"vs baseline {out['serving_spec_baseline_tokens_per_s']} "
          f"accepted tok/s/slot = "
          f"{out.get('serving_spec_speedup')}x at realized accept "
          f"rate {out['serving_spec_accept_rate']} "
          f"({out['serving_spec_tokens_per_step']} tok/verify-step; "
          f"step cost {out['serving_spec_step_ms']} vs "
          f"{out['serving_spec_baseline_step_ms']} ms)")
    return out


def pipelined_speculative_decode(trace, slots: int = 4,
                                 n_req: int = 16, toks: int = 32,
                                 step_ms: float = 2.0,
                                 tok_ms: float = 0.05,
                                 draft_ms: float = 2.8, k: int = 4,
                                 accept: float = 0.97,
                                 repeats: int = 3) -> dict:
    """Section 16 (ISSUE 18): pipelined speculative decode vs the PR
    15 sync-spec loop vs the one-token pipelined loop — ACCEPTED
    tokens/s/slot through the real ContinuousBatcher, interleaved
    best-of-3. Cost model: the section-13 SyntheticKVExecutor physics
    (fixed per-step floor + per-planned-token cost) PLUS a priced
    draft — DelayDraft sleeps ``draft_ms`` per batched proposal on
    the batcher thread, the host-side compute a real draft model
    costs. The sync loop SERIALIZES that sleep behind every device
    step; the pipelined loop plans window w+1 (draft included) while
    window w's device step runs on the worker thread, so the draft
    cost hides under the device floor — and mis-speculated plan-ahead
    windows burn a device step each (the re-plan price), so the
    speedup is the honest net of overlap minus waste at the
    controlled acceptance rate.

      * serving_pspec_tokens_per_s — accepted tokens/s/slot,
        pipelined-spec arm (rolling-median gated in bench.py);
      * serving_pspec_sync_tokens_per_s — the PR 15 sync-spec arm on
        the same cost model (same draft price);
      * serving_pspec_onetok_tokens_per_s — the PR 3 one-token
        pipelined arm (no draft, no spec);
      * serving_pspec_speedup — pipelined-spec / sync-spec (gated
        ABSOLUTE >= 1.25 in bench.py: the ISSUE 18 criterion);
      * serving_pspec_speedup_vs_onetok — the compounded figure
        (~1.8-2x the one-token loop at the default dials);
      * serving_pspec_accept_rate / serving_pspec_replan_rate — the
        acceptance decomposition: realized accept rate and stale
        plan-ahead windows per verify run (the overlap's waste term);
      * serving_pspec_step_ms / _sync_step_ms / _onetok_step_ms —
        the per-step-cost decomposition (a pipelined step costs
        max(draft, device), a sync step their sum)."""
    import time as _time

    import numpy as np

    from .api import GenerateRequest
    from .kvcache import SyntheticKVExecutor
    from .queue import AdmissionQueue
    from .scheduler import ContinuousBatcher
    from .spec import OracleDraft, SpecConfig

    out: dict = {}
    step_s, tok_s = step_ms / 1000.0, tok_ms / 1000.0
    draft_s = draft_ms / 1000.0
    prompt_len, vocab = 8, 64
    tok_total = n_req * toks

    class DelayDraft:
        """OracleDraft with a priced proposal: one ``draft_ms`` sleep
        per batched draft call — propose() and the fused
        propose_full() each cost one window latency, the way a real
        draft model's single forward pass does."""

        def __init__(self, inner):
            self._inner = inner
            self.k = inner.k

        def propose(self, last, ctx):
            _time.sleep(draft_s)
            return self._inner.propose(last, ctx)

        def propose_full(self, last, ctx):
            _time.sleep(draft_s)
            p = np.asarray(self._inner.propose(last, ctx), np.int32)
            q = np.asarray(self._inner.propose(
                p[:, -1], np.asarray(ctx, np.int64) + self.k),
                np.int32)
            return np.concatenate([p, q[:, :1]], axis=1)

    def one_run(kind):
        spec = None
        if kind in ("pspec", "sspec"):
            spec = SpecConfig(DelayDraft(OracleDraft(
                k=k, accept_rate=accept, vocab=vocab,
                target_seed=0)), k)
        ex = SyntheticKVExecutor(
            slots=slots, vocab=vocab, block_size=4, num_blocks=2048,
            max_blocks_per_req=16, prefill_chunk=8,
            step_time_s=step_s, token_time_s=tok_s,
            pipelined=kind in ("pspec", "onetok"), spec=spec,
            prefix_cache=False)
        q = AdmissionQueue(max_depth=n_req + 1)
        b = ContinuousBatcher(ex, q)
        reqs = [GenerateRequest(
            prompt_vec=None, max_tokens=toks,
            deadline=_time.monotonic() + 600.0,
            prompt_tokens=[(3 * i + j) % vocab
                           for j in range(prompt_len)])
            for i in range(n_req)]
        for r in reqs:
            q.submit(r)
        t0 = _time.perf_counter()
        b.start()
        ok = all(r.wait(timeout=600) for r in reqs)
        wall = _time.perf_counter() - t0
        b.stop()
        if not ok or any(r.error for r in reqs):
            raise RuntimeError(next(
                (r.error for r in reqs if r.error), "request lost"))
        delivered = sum(len(r.tokens) for r in reqs)
        assert delivered == tok_total, (delivered, tok_total)
        stats = ex.kv_stats()
        steps = ex._step_no
        ex.allocator.assert_clean()
        ex.close()
        return (tok_total / slots) / wall, wall, steps, stats

    # Interleaved best-of-3: all three arms share each rep's box
    # weather, the section-5/9/13 shared-box defense.
    best: dict = {}
    for rep in range(repeats):
        for kind in ("pspec", "sspec", "onetok"):
            rate, wall, steps, stats = one_run(kind)
            trace(f"pipelined-spec {kind} rep{rep}: {rate:.0f} "
                  f"accepted tok/s/slot over {steps} steps")
            if kind not in best or rate > best[kind][0]:
                best[kind] = (rate, wall, steps, stats)

    pp_rate, pp_wall, pp_steps, pp_stats = best["pspec"]
    sy_rate, sy_wall, sy_steps, _ = best["sspec"]
    ot_rate, ot_wall, ot_steps, _ = best["onetok"]
    out["serving_pspec_tokens_per_s"] = round(pp_rate, 1)
    out["serving_pspec_sync_tokens_per_s"] = round(sy_rate, 1)
    out["serving_pspec_onetok_tokens_per_s"] = round(ot_rate, 1)
    if sy_rate > 0:
        out["serving_pspec_speedup"] = round(pp_rate / sy_rate, 2)
    if ot_rate > 0:
        out["serving_pspec_speedup_vs_onetok"] = round(
            pp_rate / ot_rate, 2)
    out["serving_pspec_accept_rate"] = pp_stats["spec_accept_rate"]
    runs = max(1, pp_stats["spec_verify_steps"])
    out["serving_pspec_replan_rate"] = round(
        pp_stats["spec_replans"] / runs, 3)
    out["serving_pspec_step_ms"] = round(
        pp_wall / pp_steps * 1000, 3)
    out["serving_pspec_sync_step_ms"] = round(
        sy_wall / sy_steps * 1000, 3)
    out["serving_pspec_onetok_step_ms"] = round(
        ot_wall / ot_steps * 1000, 3)
    trace(f"pipelined spec: {out['serving_pspec_tokens_per_s']} vs "
          f"sync-spec {out['serving_pspec_sync_tokens_per_s']} vs "
          f"one-token {out['serving_pspec_onetok_tokens_per_s']} "
          f"accepted tok/s/slot = {out.get('serving_pspec_speedup')}x "
          f"over sync spec "
          f"({out.get('serving_pspec_speedup_vs_onetok')}x over "
          f"one-token; replan rate "
          f"{out['serving_pspec_replan_rate']}/run; step cost "
          f"{out['serving_pspec_step_ms']} vs "
          f"{out['serving_pspec_sync_step_ms']} vs "
          f"{out['serving_pspec_onetok_step_ms']} ms)")
    return out


def sharded_decode(slots: int, trace, world: int = 3, n_req: int = 48,
                   toks: int = 16, step_ms: float = 2.0,
                   coll_ms: float = 1.0, repeats: int = 3) -> dict:
    """Section 9 (ISSUE 8): one replica sharded across `world` shard
    workers vs the same decode single-host, through the REAL
    ContinuousBatcher (queue preloaded, no HTTP). The shard plane is
    the SyntheticShardSet with a fixed per-shard compute cost and a
    fixed modelled collective cost — the deterministic cost model, so
    the figures regress on coordinator/scheduler changes (broadcast
    fan-out, collect gather, pipelined overlap) and nothing else.

    serving_sharded_steps_per_s is USEFUL steps/s (decoded tokens ÷
    slots per second — hand-off steps count against it, same
    definition as section 5). serving_shard_collective_frac is the
    share of the best run's wall the step spent inside the collective
    (sum of per-step slowest-shard collective time / wall): with a
    preloaded queue the shard plane is near-saturated, so the ratio
    is the decode decomposition, not an idle-time artifact.

    ISSUE 9: the sharded arm runs TWICE per rep — overlap ON
    (forward_overlapped's double-buffered block schedule; the gated
    collective frac, which counts only the NON-HIDDEN wait) and
    overlap OFF (the serialized loop; the `_off` twins) — interleaved
    so the on-vs-off comparison is a paired best-of-3."""
    import time as _time

    from ..utils.metrics import Registry
    from .api import GenerateRequest, encode_prompt
    from .executor import SyntheticExecutor
    from .queue import AdmissionQueue
    from .scheduler import ContinuousBatcher
    from .sharded import FabricExecutor, SyntheticShardSet

    out: dict = {}
    d = 16
    tok_total = n_req * toks
    step_s, coll_s = step_ms / 1000.0, coll_ms / 1000.0

    def one_run(kind):
        reg = Registry()
        if kind in ("sharded", "sharded-off"):
            # "sharded" = overlap ON (forward_overlapped's double-
            # buffered block schedule — the headline arm); "sharded-
            # off" = the serialized partial→reduce→finish loop, kept
            # as the paired comparison the overlap claim is made
            # against.
            ex = FabricExecutor(
                SyntheticShardSet(world=world, slots=slots, d=d,
                                  seed=7, step_time_s=step_s,
                                  collective_time_s=coll_s,
                                  overlap=(kind == "sharded")),
                mode="pipelined", registry=reg, name="bench")
        else:
            # The single-host twin pays the compute but no collective
            # — the delta is the fabric tax at this cost model.
            ex = SyntheticExecutor(slots=slots, d=d, seed=7,
                                   step_time_s=step_s, pipelined=True)
        q = AdmissionQueue(max_depth=n_req + 1)
        b = ContinuousBatcher(ex, q, registry=reg)
        reqs = [GenerateRequest(
            prompt_vec=encode_prompt(f"sh-{i}", d),
            max_tokens=toks, deadline=_time.monotonic() + 600.0)
            for i in range(n_req)]
        for r in reqs:
            q.submit(r)
        t0 = _time.perf_counter()
        b.start()
        ok = all(r.wait(timeout=600) for r in reqs)
        wall = _time.perf_counter() - t0
        b.stop()
        ex.close()
        if not ok or any(r.error for r in reqs):
            raise RuntimeError(next(
                (r.error for r in reqs if r.error), "request lost"))
        return (tok_total / slots) / wall, wall, reg

    # No warm-up arm: every run constructs its own executor/shard set
    # (spawns included in its wall), so runs are iid and best-of-N
    # already discards any first-call python/allocator cold cost. The
    # three arms run INTERLEAVED so overlap-on vs overlap-off is a
    # paired best-of-3 (the ISSUE 9 acceptance comparison), same
    # shared-box defense as section 5.
    best: dict = {}
    for rep in range(repeats):
        for kind in ("sharded", "sharded-off", "local"):
            rate, wall, reg = one_run(kind)
            trace(f"sharded-decode {kind} rep{rep}: {rate:.0f} "
                  f"useful steps/s")
            if kind not in best or rate > best[kind][0]:
                best[kind] = (rate, wall, reg)

    def coll_frac(kind):
        rate, wall, reg = best[kind]
        coll = reg.histogram_totals("serving_shard_collective_seconds")
        return sum(s for s, _ in coll.values()) / wall

    # Headline steps/s: the FASTER sharded configuration. The overlap
    # win is payload- and box-dependent (at the synthetic plane's ms
    # scale, per-block thread handoffs on a 2-cpu box can cost more
    # than the compute they hide; on the real ring the hidden time is
    # socket time), so the headline tracks what an operator would
    # deploy, and both arms stay in the artifact.
    sh_rate = max(best["sharded"][0], best["sharded-off"][0])
    sh_reg = best["sharded"][2]
    out["serving_sharded_steps_per_s"] = round(sh_rate, 1)
    out["serving_sharded_tok_per_s"] = round(sh_rate * slots, 1)
    # The gated collective fraction is the OVERLAP-ON arm's: under
    # overlap the executor observes only the non-hidden wait, so the
    # figure is "what the fabric still costs after hiding" — creep
    # means the overlap schedule is rotting back toward serialized.
    # The acceptance comparison (overlap lowers the blocked fraction)
    # is the _off twin next to it, from the same paired best-of-3.
    out["serving_shard_collective_frac"] = round(coll_frac("sharded"),
                                                 3)
    out["serving_shard_collective_frac_off"] = round(
        coll_frac("sharded-off"), 3)
    out["serving_sharded_steps_per_s_overlap"] = round(
        best["sharded"][0], 1)
    out["serving_sharded_steps_per_s_off"] = round(
        best["sharded-off"][0], 1)
    if best["sharded-off"][0] > 0:
        out["serving_shard_overlap_speedup"] = round(
            best["sharded"][0] / best["sharded-off"][0], 2)
    skew = sh_reg.histogram_totals("serving_shard_step_skew_seconds")
    skew_sum = sum(s for s, _ in skew.values())
    skew_n = sum(n for _, n in skew.values())
    if skew_n:
        out["serving_shard_step_skew_ms"] = round(
            skew_sum / skew_n * 1000, 3)
    if best["local"][0] > 0:
        out["serving_sharded_vs_local_frac"] = round(
            sh_rate / best["local"][0], 3)
    trace(f"sharded decode: {out['serving_sharded_steps_per_s']} "
          f"useful steps/s over {world} shards, collective frac "
          f"{out['serving_shard_collective_frac']} (overlap off "
          f"{out['serving_shard_collective_frac_off']}), vs local "
          f"{out.get('serving_sharded_vs_local_frac')}x")
    return out


def paged_attn_bench(trace, iters: int = 40, repeats: int = 3) -> dict:
    """Section 11 (ISSUE 13): the fused-paged-attention decomposition.

    Times ONE PagedDecodeStep step (embed → append → paged attention
    → logits, ``block_until_ready`` — pure device wall, no scheduler)
    at steady-state full-slot decode over three layouts on the same
    shapes:

      * ``serving_paged_attn_device_ms`` — the DEPLOYED kernel's
        per-step device time (the fused Pallas kernel on a TPU
        backend; the compiled XLA composition on CPU, where pallas
        would run interpreted and time the interpreter, not the
        kernel). Gated <= 1.35x its rolling median.
      * ``serving_paged_attn_xla_ms`` / ``_pallas_ms`` — the
        decomposition pair (``_pallas_ms`` only on TPU).
      * ``serving_paged_attn_fp32_ms`` — the fp32-resident twin of
        the deployed arm: the dtype half of the decomposition (int8
        reads 4x fewer pool bytes per gather).

    On CPU the acceptance criterion is correctness, not speed:
    ``serving_paged_attn_equiv_ok`` records a live interpret-mode
    Pallas-vs-XLA equivalence check at reduced shapes (bitwise pools,
    identical argmax tokens — the tests/test_paged_attn.py contract,
    re-proven in the bench artifact every round).

    Residency accounting rides along: ``serving_kv_bytes_per_slot``
    (int8 resident layout), its fp32 twin, and
    ``serving_kv_bytes_reduction`` — gated ABSOLUTE >= 3.5x (the
    acceptance floor; the layout either delivers its 4x-ish HBM win
    or the round fails)."""
    import time as _time

    import numpy as np

    from .kvcache.paged import PagedDecodeStep, kv_bytes_per_slot

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    dims = dict(slots=4, vocab=256, d=64, heads=4, block_size=16,
                num_blocks=256, max_blocks_per_req=16, chunk=8, seed=3)
    out: dict = {}

    def steady_plan(step_obj):
        """Full-occupancy decode plan: every slot mid-decode with a
        half-full table — the shape the decode hot path actually
        runs."""
        S, C = step_obj.slots, step_obj.chunk
        B, bs = step_obj.max_blocks_per_req, step_obj.block_size
        rng = np.random.RandomState(7)
        tables = np.arange(S * B, dtype=np.int32).reshape(S, B)
        ctx = np.full((S,), (B // 2) * bs, np.int32)
        n_new = np.ones((S,), np.int32)
        host = rng.randint(0, step_obj.vocab,
                           size=(S, C)).astype(np.int32)
        use_host = np.ones((S,), bool)
        return (step_obj.init_prev(), host, use_host, ctx, n_new,
                tables)

    def time_arm(kernel, pool_dtype):
        st = PagedDecodeStep(kernel=kernel, pool_dtype=pool_dtype,
                             **dims)
        pools = st.init_pools()
        prev, host, use_host, ctx, n_new, tables = steady_plan(st)
        best = float("inf")
        for _ in range(repeats):
            # Warm one step, then time the loop; pools thread
            # linearly (donation on accelerator backends).
            p = st(*pools, prev, host, use_host, ctx, n_new, tables)
            pools, tok = p[:4], p[4]
            tok.block_until_ready()
            t0 = _time.perf_counter()
            for _ in range(iters):
                p = st(*pools, prev, host, use_host, ctx, n_new,
                       tables)
                pools, tok = p[:4], p[4]
            tok.block_until_ready()
            best = min(best,
                       (_time.perf_counter() - t0) / iters * 1000.0)
        return best

    xla_ms = time_arm("xla", "int8")
    fp32_ms = time_arm("xla", "fp32")
    out["serving_paged_attn_xla_ms"] = round(xla_ms, 3)
    out["serving_paged_attn_fp32_ms"] = round(fp32_ms, 3)
    if on_tpu:
        pallas_ms = time_arm("pallas", "int8")
        out["serving_paged_attn_pallas_ms"] = round(pallas_ms, 3)
        # The headline tracks the DEPLOY-DEFAULT kernel — which on a
        # TPU backend is unconditionally pallas (PagedDecodeStep's
        # kernel=None auto-select), NOT min(pallas, xla): a
        # Pallas-only regression must move the gated figure, and the
        # acceptance comparison (pallas <= the XLA composition on the
        # same shapes) is gated separately and absolutely in bench.py
        # via the recorded pair.
        out["serving_paged_attn_device_ms"] = round(pallas_ms, 3)
        out["serving_paged_attn_kernel"] = "pallas"
    else:
        out["serving_paged_attn_device_ms"] = round(xla_ms, 3)
        out["serving_paged_attn_kernel"] = "xla"
        # Correctness instead of perf on CPU: a live interpret-mode
        # equivalence spot check at reduced shapes.
        small = dict(slots=2, vocab=32, d=16, heads=2, block_size=4,
                     num_blocks=32, max_blocks_per_req=4, chunk=4,
                     seed=5)
        eq = True
        toks = {}
        pools = {}
        for kern in ("xla", "pallas"):
            st = PagedDecodeStep(kernel=kern, pool_dtype="int8",
                                 interpret=True, **small)
            p = st.init_pools()
            prev = st.init_prev()
            tables = np.arange(8, dtype=np.int32).reshape(2, 4)
            ctx = np.zeros((2,), np.int32)
            rng = np.random.RandomState(11)
            emitted = []
            for stepno in range(4):
                host = rng.randint(0, 32, size=(2, 4)).astype(np.int32)
                n_new = np.full((2,), 4 if stepno == 0 else 1,
                                np.int32)
                use_host = np.ones((2,), bool)
                r = st(*p, prev, host, use_host, ctx, n_new, tables)
                p, tok = r[:4], r[4]
                ctx = ctx + n_new
                prev = tok
                emitted.append(np.asarray(tok).tolist())
            toks[kern] = emitted
            pools[kern] = [np.asarray(a) for a in p]
        eq = toks["xla"] == toks["pallas"] and all(
            np.array_equal(a, b) for a, b in zip(pools["xla"],
                                                 pools["pallas"]))
        out["serving_paged_attn_equiv_ok"] = bool(eq)

    d = dims
    dh = d["d"] // d["heads"]
    int8_bytes = kv_bytes_per_slot(d["max_blocks_per_req"],
                                   d["block_size"], d["heads"], dh,
                                   "int8")
    fp32_bytes = kv_bytes_per_slot(d["max_blocks_per_req"],
                                   d["block_size"], d["heads"], dh,
                                   "fp32")
    out["serving_kv_bytes_per_slot"] = int8_bytes
    out["serving_kv_bytes_per_slot_fp32"] = fp32_bytes
    out["serving_kv_bytes_reduction"] = round(fp32_bytes / int8_bytes,
                                              2)
    trace(f"paged-attn: {out['serving_paged_attn_kernel']} "
          f"{out['serving_paged_attn_device_ms']} ms/step (xla int8 "
          f"{out['serving_paged_attn_xla_ms']}, fp32 "
          f"{out['serving_paged_attn_fp32_ms']}, pallas "
          f"{out.get('serving_paged_attn_pallas_ms', 'n/a — cpu')}); "
          f"kv bytes/slot {int8_bytes} vs fp32 {fp32_bytes} = "
          f"{out['serving_kv_bytes_reduction']}x"
          + ("" if on_tpu else
             f"; interpret equivalence "
             f"{'ok' if out.get('serving_paged_attn_equiv_ok') else 'FAILED'}"))
    return out


def sharded_kv_scaling(trace, slots: int = 2, n_req: int = 6,
                       toks: int = 8, repeats: int = 2) -> dict:
    """Section 14 (ISSUE 16): context-parallel paged KV — what
    sharding the K/V pools across shard workers buys, in three
    measurements.

    1. Resident context per replica vs world (1, 2, 4): PURE KVSpec
       arithmetic from the blessed derivation site
       (``rank_resident_nbytes`` on the realistic ISSUE-13 layout —
       16-token blocks, 8 heads x 128 d_head, int8 codes + scales).
       Fix the per-worker HBM budget at what a single worker pins for
       a 4096-block pool, then size the largest replica pool whose
       WORST rank still fits that budget.
       serving_ctx_per_replica_scaling is the world-2/world-1 token
       ratio, taken as the MIN over both shard axes (page keeps full
       heads per block; head pays the unsharded per-block scale), and
       is gated ABSOLUTE >= 1.7 in bench.py — the acceptance
       criterion itself. Arithmetic, not a timing: a layout
       regression is never box weather. The _w4 twin is the
       linearity artifact.

    2. Measured decode: tokens/s and per-token p99 through the REAL
       ContinuousBatcher over ShardedPagedKVExecutor (page axis — the
       ring/long-context path) at world 1/2/4 thread shards, paired
       interleaved with the single-worker PagedKVExecutor twin on the
       same dims, best-of-N. serving_shard_kv_tokens_per_s (world 2)
       holds 0.85x its rolling median and serving_shard_kv_p99_ms
       (world 2) gets the 1.35x latency band against its own rolling
       median — the bounded-p99 half of the ISSUE 16 acceptance as
       this harness can state it. NOTE both twins run the same tiny
       CPU payload, where real attention compute is microseconds: the
       sharded figure IS the coordinator hand-off + partial merge
       cost, so the absolute vs-single comparison is structurally
       >1x here and rides the artifact informationally
       (serving_shard_kv_p99_vs_single) for real-chip rounds, where
       attention dominates and the ratio is the meaningful one; the
       gated rolling medians are what catch creep either way.

    3. Per-rank transfer decomposition: a sharded lease ships as
       ``world`` point-to-point sub-streams, each framed by its
       ``rank_view``. Loopback microbench with both rank streams
       CONCURRENT (the bandwidth-multiplication claim is parallelism
       of independent links): aggregate Gb/s across both links plus
       the per-rank figures."""
    import numpy as np

    from .api import GenerateRequest
    from .disagg import KVPageStream, KVPageStreamServer
    from .disagg.spec import KVSpec
    from .kvcache import PagedKVExecutor
    from .kvcache.sharded import ShardedPagedKVExecutor
    from .queue import AdmissionQueue
    from .scheduler import ContinuousBatcher

    out: dict = {}

    # -- 1: resident context per replica (KVSpec arithmetic) -------------
    layout = dict(model="paged", block_size=16, heads=8, d_head=128,
                  vocab=64, max_blocks_per_req=64, pool_dtype="int8")
    base_blocks = 4096
    budget = KVSpec(**layout).rank_resident_nbytes(0, base_blocks)

    def ctx_tokens(axis, world):
        if world == 1:
            return base_blocks * layout["block_size"]
        spec = KVSpec(**layout, shard_axis=axis, world=world)

        def fits(m):
            return all(spec.rank_resident_nbytes(r, m) <= budget
                       for r in range(world))

        lo, hi = world, 2 * world * base_blocks
        while lo < hi:  # largest pool whose worst rank fits the budget
            mid = (lo + hi + 1) // 2
            lo, hi = (mid, hi) if fits(mid) else (lo, mid - 1)
        return lo * spec.block_size

    w1_tokens = ctx_tokens("none", 1)
    scal = {(axis, w): ctx_tokens(axis, w) / w1_tokens
            for axis in ("page", "head") for w in (2, 4)}
    out["serving_ctx_per_replica_scaling"] = round(
        min(scal[("page", 2)], scal[("head", 2)]), 3)
    out["serving_ctx_per_replica_scaling_w4"] = round(
        min(scal[("page", 4)], scal[("head", 4)]), 3)
    trace(f"sharded-kv context scaling: {w1_tokens} tokens/replica at "
          f"world 1 -> x{out['serving_ctx_per_replica_scaling']} at "
          f"world 2 (page {scal[('page', 2)]:.3f} / head "
          f"{scal[('head', 2)]:.3f}), "
          f"x{out['serving_ctx_per_replica_scaling_w4']} at world 4")

    # -- 2: measured decode vs world (real batcher, real paged JAX) ------
    dims = dict(slots=slots, vocab=32, d=16, heads=2, block_size=4,
                num_blocks=64, max_blocks_per_req=8, prefill_chunk=8,
                seed=0)
    prompt_len = 12

    def one_run(world):
        # world 0 = the single-worker PagedKVExecutor twin; otherwise
        # the page-axis thread-shard set (per-rank pool shapes differ
        # per world, so rep 0 pays each world's jit compile once and
        # best-of-N discards it).
        if world == 0:
            ex = PagedKVExecutor(mode="pipelined", **dims)
        else:
            ex = ShardedPagedKVExecutor(world=world, shard_axis="page",
                                        mode="pipelined", **dims)
        q = AdmissionQueue(max_depth=n_req + 1)
        b = ContinuousBatcher(ex, q)
        reqs = [GenerateRequest(
            prompt_vec=None, max_tokens=toks,
            deadline=time.monotonic() + 600.0,
            prompt_tokens=[(5 * i + j) % dims["vocab"]
                           for j in range(prompt_len)])
            for i in range(n_req)]
        for r in reqs:
            q.submit(r)
        t0 = time.perf_counter()
        b.start()
        ok = all(r.wait(timeout=600) for r in reqs)
        wall = time.perf_counter() - t0
        b.stop()
        if not ok or any(r.error for r in reqs):
            raise RuntimeError(next(
                (r.error for r in reqs if r.error), "request lost"))
        ex.allocator.assert_clean()
        if world:
            assert ex.shards.outstanding() == 0
        ex.close()
        per_tok = sorted(
            (r.finished_at - r.admitted_at) * 1000.0 / len(r.tokens)
            for r in reqs)
        return n_req * toks / wall, nearest_rank(per_tok, 0.99)

    best: dict = {}
    for rep in range(repeats):
        for world in (0, 1, 2, 4):
            name = "single" if world == 0 else f"w{world}"
            rate, p99 = one_run(world)
            trace(f"sharded-kv decode {name} rep{rep}: {rate:.0f} "
                  f"tok/s, p99 {p99:.2f} ms/tok")
            if name not in best or rate > best[name][0]:
                best[name] = (rate, p99)

    out["serving_shard_kv_tokens_per_s"] = round(best["w2"][0], 1)
    out["serving_shard_kv_p99_ms"] = round(best["w2"][1], 3)
    out["serving_shard_kv_single_tokens_per_s"] = round(
        best["single"][0], 1)
    out["serving_shard_kv_single_p99_ms"] = round(best["single"][1], 3)
    out["serving_shard_kv_tokens_per_s_w1"] = round(best["w1"][0], 1)
    out["serving_shard_kv_tokens_per_s_w4"] = round(best["w4"][0], 1)
    if best["single"][1] > 0:
        out["serving_shard_kv_p99_vs_single"] = round(
            best["w2"][1] / best["single"][1], 2)
    trace(f"sharded-kv decode: world 2 "
          f"{out['serving_shard_kv_tokens_per_s']} tok/s at p99 "
          f"{out['serving_shard_kv_p99_ms']} ms/tok "
          f"({out.get('serving_shard_kv_p99_vs_single')}x the "
          f"single-worker twin)")

    # -- 3: per-rank transfer decomposition (concurrent loopback) --------
    spec = KVSpec(**layout, shard_axis="head", world=2)
    n_blocks, iters = 64, 3
    barrier = threading.Barrier(spec.world + 1)
    rank_res: dict = {}

    def pump(rank):
        rv = spec.rank_view(rank)
        srv = KVPageStreamServer(rv, lambda meta, planes: {})
        try:
            st = KVPageStream(rv, srv.addr)
            rng = np.random.RandomState(rank)
            codes = rng.randint(-127, 127, size=(
                n_blocks, rv.block_size, rv.heads,
                rv.d_head)).astype("int8")
            scales = rng.rand(n_blocks).astype("float32")
            meta = {"req": f"bench-r{rank}", "n_blocks": n_blocks,
                    "tokens": n_blocks * rv.block_size}
            planes = [(codes, scales), (codes, scales)]
            st.send_pages(meta, planes)  # warm (connect + first frame)
            barrier.wait()
            t0 = time.perf_counter()
            for _ in range(iters):
                st.send_pages(meta, planes)
            rank_res[rank] = (t0, time.perf_counter())
            st.close()
        finally:
            srv.close()

    threads = [threading.Thread(target=pump, args=(r,), daemon=True)
               for r in range(spec.world)]
    for t in threads:
        t.start()
    barrier.wait()
    for t in threads:
        t.join(timeout=60)
    rank_bytes = {r: spec.rank_wire_block_nbytes(r, "int8") * n_blocks
                  for r in range(spec.world)}
    if len(rank_res) == spec.world:
        # Aggregate over the union of the timed windows (close/shutdown
        # costs after a rank's last send don't count against the wire).
        agg_wall = (max(t1 for _, t1 in rank_res.values())
                    - min(t0 for t0, _ in rank_res.values()))
        out["serving_shard_kv_transfer_gbps"] = round(
            sum(rank_bytes.values()) * iters * 8 / 1e9 / agg_wall, 3)
        for r in range(spec.world):
            t0, t1 = rank_res[r]
            out[f"serving_shard_kv_transfer_rank{r}_gbps"] = round(
                rank_bytes[r] * iters * 8 / 1e9 / (t1 - t0), 3)
        trace(f"sharded-kv transfer: "
              f"{out['serving_shard_kv_transfer_gbps']} Gb/s aggregate "
              f"over {spec.world} concurrent rank streams (rank0 "
              f"{out['serving_shard_kv_transfer_rank0_gbps']}, rank1 "
              f"{out['serving_shard_kv_transfer_rank1_gbps']})")
    return out


def cluster_prefix(trace, n_tenants: int = 7, reqs_per_tenant: int = 8,
                   prefix_tokens: int = 24, suffix_tokens: int = 4,
                   max_tokens: int = 6, block_size: int = 4,
                   slots: int = 2, num_blocks: int = 28,
                   tier_budget_bytes: int = 640,
                   token_time_ms: float = 0.5) -> dict:
    """Section 15 (ISSUE 17): the cluster-wide prefix cache — what
    prefix-aware routing + KV tiering buy over prefix-blind placement.

    Workload: ``n_tenants`` tenants, each with a shared
    ``prefix_tokens``-token system prompt and per-request unique
    suffixes — the multi-tenant shape where the same bytes get
    prefilled again and again. Capacity is the forcing function: HBM
    (``num_blocks``) holds in-flight work plus only ~2 resident
    chains, and the host tier (``tier_budget_bytes``) holds roughly
    HALF the tenant set's spilled chains — so a replica can keep the
    tenants it OWNS warm but not everybody's. The routed arm
    partitions tenants across replicas (each chain lives HBM-or-host
    on one owner); round-robin sprays every tenant across BOTH
    replicas, overflows both tiers, and the LRU drops chains that
    then re-prefill from scratch. ``n_tenants`` is deliberately odd:
    with an even tenant count a 2-replica round-robin degenerates
    into perfect parity affinity (tenant t -> replica t%2) and
    measures nothing.

    Two arms on identical machinery and identical request order:

      * routed  — PrefixRouter(policy="prefix"), gossip + affinity +
        the affinity-miss pull;
      * rr      — PrefixRouter(policy="round_robin"), same replicas,
        no scoring, no pulls.

    Headline (gated in bench.py): serving_prefix_hit_frac holds an
    ABSOLUTE floor AND serving_prefix_route_uplift_x (routed hit frac
    / rr hit frac) >= 1.5 — the ISSUE 17 acceptance; TTFT p99 is
    gated via serving_ttft_vs_rr_x <= 0.7 (absolute) plus a 1.35x
    rolling-median band on serving_ttft_p99_ms. The spill/restore/
    pull byte rates decompose where the moved bytes actually went."""
    from .api import GenerateRequest
    from .kvcache import SyntheticKVExecutor
    from .queue import AdmissionQueue
    from .router import PrefixRouter, RouterReplica
    from .scheduler import ContinuousBatcher
    from ..utils.metrics import Registry

    rng = __import__("numpy").random.RandomState(1717)
    vocab = 32
    tenant_prefix = [
        [int(t) for t in rng.randint(0, vocab, size=prefix_tokens)]
        for _ in range(n_tenants)]
    suffixes = [
        [[int(t) for t in rng.randint(0, vocab, size=suffix_tokens)]
         for _ in range(reqs_per_tenant)]
        for _ in range(n_tenants)]
    deadline = lambda: time.monotonic() + 120.0

    def mk_replica(name):
        ex = SyntheticKVExecutor(
            slots=slots, vocab=vocab, block_size=block_size,
            num_blocks=num_blocks, max_blocks_per_req=16,
            token_time_s=token_time_ms / 1000.0,
            host_tier_bytes=tier_budget_bytes)
        return RouterReplica(name, AdmissionQueue(max_depth=256), ex)

    def run_arm(policy):
        replicas = [mk_replica("a"), mk_replica("b")]
        reg = Registry()
        router = PrefixRouter(replicas, policy=policy, cadence_s=0.0,
                              max_load_skew=8, registry=reg)
        batchers = [ContinuousBatcher(r.executor, r.queue)
                    for r in replicas]
        for b in batchers:
            b.start()
        reqs = []
        steady = []
        t0 = time.monotonic()
        try:
            # Tenant-interleaved arrival, closed-loop per wave: the
            # next wave is routed against the gossip the last one
            # produced — submit-all-upfront would route every round
            # against an EMPTY board and measure only tie-breaks.
            for i in range(reqs_per_tenant):
                wave = []
                for t in range(n_tenants):
                    r = GenerateRequest(
                        prompt_vec=None, max_tokens=max_tokens,
                        deadline=deadline(),
                        prompt_tokens=(tenant_prefix[t]
                                       + suffixes[t][i]))
                    wave.append(r)
                    router.submit(r)
                for r in wave:
                    if not r.wait(timeout=120.0):
                        raise RuntimeError("bench request lost")
                reqs.extend(wave)
                # TTFT is a STEADY-STATE figure: wave 0 is the
                # unavoidable first-touch prefill in EITHER arm, and
                # with it in-sample p99 measures cold-start, not
                # placement. Hit-frac accounting keeps every wave.
                if i > 0:
                    steady.extend(wave)
        finally:
            for b in batchers:
                b.stop()
        wall = time.monotonic() - t0
        errs = [r.error for r in reqs if r.error]
        if errs:
            raise RuntimeError(f"{len(errs)} request(s) failed: "
                               f"{errs[0]}")
        ttfts = sorted(r.timings_ms()["ttft_ms"] for r in steady)
        hits = lookups = 0
        tier = {"spilled_bytes": 0, "restored_bytes": 0,
                "spilled_blocks": 0, "restored_blocks": 0,
                "corrupt_blocks": 0}
        for rep in replicas:
            st = rep.executor.kv_stats()
            hits += st["prefix_hit_tokens"]
            lookups += st["prefix_lookup_tokens"]
            for k in tier:
                tier[k] += st[f"tier_{k}"]
        pulls = dict(
            blocks=reg.counter_value(
                "serving_router_pulled_blocks_total") or 0.0,
            nbytes=reg.counter_value(
                "serving_router_pull_bytes_total") or 0.0,
            seconds=reg.counter_value(
                "serving_router_pull_seconds_total") or 0.0,
            failed=reg.counter_value(
                "serving_router_pull_failed_total") or 0.0)
        # Teardown hygiene: the bench enforces the same two-ledger
        # contract the tests do — a leak here is a real leak.
        for rep in replicas:
            rep.executor.prefix.flush()
            rep.executor.allocator.assert_clean()
            rep.executor.tier.assert_clean()
        router.close()
        for rep in replicas:
            rep.executor.close()
        return dict(wall=wall, ttfts=ttfts,
                    hit_frac=hits / max(1, lookups), tier=tier,
                    pulls=pulls, n=len(reqs))

    out: dict = {}
    routed = run_arm("prefix")
    rr = run_arm("round_robin")

    out["serving_prefix_hit_frac"] = round(routed["hit_frac"], 4)
    out["serving_prefix_hit_frac_rr"] = round(rr["hit_frac"], 4)
    out["serving_prefix_route_uplift_x"] = round(
        routed["hit_frac"] / max(1e-9, rr["hit_frac"]), 3)
    p99 = lambda xs: nearest_rank(xs, 0.99)
    out["serving_ttft_p99_ms"] = round(p99(routed["ttfts"]), 3)
    out["serving_ttft_p99_rr_ms"] = round(p99(rr["ttfts"]), 3)
    out["serving_ttft_vs_rr_x"] = round(
        out["serving_ttft_p99_ms"]
        / max(1e-9, out["serving_ttft_p99_rr_ms"]), 3)
    out["serving_cluster_reqs"] = routed["n"]
    out["serving_tier_spilled_blocks"] = routed["tier"][
        "spilled_blocks"]
    out["serving_tier_restored_blocks"] = routed["tier"][
        "restored_blocks"]
    out["serving_router_pulled_blocks"] = int(routed["pulls"]["blocks"])
    out["serving_router_pull_failed"] = int(routed["pulls"]["failed"])
    if routed["pulls"]["seconds"] > 0:
        out["serving_router_pull_gbps"] = round(
            routed["pulls"]["nbytes"] * 8
            / routed["pulls"]["seconds"] / 1e9, 4)

    # Spill/restore bandwidth micro (same tier machinery, timed in
    # isolation — the arm runs interleave spills with decode, so their
    # rate is not separable there). Synthetic pool planes are tiny;
    # the figure tracks the tier's per-block overhead, and real-pool
    # byte rates ride the disagg section's stream numbers.
    ex = SyntheticKVExecutor(slots=2, vocab=vocab,
                             block_size=block_size, num_blocks=64,
                             max_blocks_per_req=16,
                             host_tier_bytes=8 << 20)
    try:
        q = AdmissionQueue(max_depth=4)
        b = ContinuousBatcher(ex, q)
        long_prompt = [int(t) for t in rng.randint(0, vocab, size=56)]
        r = GenerateRequest(prompt_vec=None, max_tokens=4,
                            deadline=deadline(),
                            prompt_tokens=long_prompt)
        q.submit(r)
        b.start()
        try:
            if not r.wait(timeout=60.0):
                raise RuntimeError("bench request lost")
        finally:
            b.stop()
        t0 = time.perf_counter()
        ex.prefix.evict(99)
        spill_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        blocks, cached = ex.kv_match_prefix(long_prompt, "bw")
        restore_s = time.perf_counter() - t0
        ex.allocator.release(blocks, "bw")
        st = ex.tier.stats()
        if spill_s > 0 and st["spilled_bytes"]:
            out["serving_tier_spill_gbps"] = round(
                st["spilled_bytes"] * 8 / spill_s / 1e9, 4)
        if restore_s > 0 and st["restored_bytes"]:
            out["serving_tier_restore_gbps"] = round(
                st["restored_bytes"] * 8 / restore_s / 1e9, 4)
        ex.prefix.flush()
        ex.allocator.assert_clean()
        ex.tier.assert_clean()
    finally:
        ex.close()

    trace(f"cluster-prefix: hit {out['serving_prefix_hit_frac']} vs "
          f"rr {out['serving_prefix_hit_frac_rr']} "
          f"(uplift {out['serving_prefix_route_uplift_x']}x), ttft "
          f"p99 {out['serving_ttft_p99_ms']} vs "
          f"{out['serving_ttft_p99_rr_ms']} ms "
          f"({out['serving_ttft_vs_rr_x']}x), pulled "
          f"{out['serving_router_pulled_blocks']} block(s)")
    return out


def multi_tenant_qos(trace, slots: int = 4, step_ms: float = 2.0,
                     good_rate: float = 40.0, flood_x: float = 10.0,
                     seconds: float = 2.0,
                     flood_budget_rate: float = 40.0,
                     prompt_tokens: int = 16, good_tokens: int = 16,
                     flood_tokens: int = 16,
                     burst_n: int = 24, reps: int = 3) -> dict:
    """Section 17 (ISSUE 20): trace-driven open-loop multi-tenant QoS
    on the paged-KV plane.

    One submission thread walks a precomputed arrival schedule into
    the AdmissionQueue (a "trace", in the request-log sense) and the
    requests settle through a real kv-mode ContinuousBatcher —
    latency is stamped server-side (arrival -> finished_at), so the
    figures move on admission/preemption regressions, never on bench
    client threads. SyntheticKVExecutor with a fixed per-step cost is
    the accelerator model; the host tier is armed so preemption has
    somewhere to park.

    Two claims, each its own arm pair:

      * isolation — the good tenant's interactive p99 with an
        adversarial tenant submitting batch-class work at ``flood_x``
        its rate vs the same schedule alone.
        ``serving_tenant_p99_isolation`` is the contended/solo ratio,
        gated ABSOLUTE (<= 1.35) in bench.py. Three mechanisms carry
        it: the flood's token bucket sheds most of its arrivals
        (429s it pays for itself), strict priority pops interactive
        ahead of every queued flood, and — the ISSUE 20 tentpole —
        KV-aware preemption parks a batch occupant the moment an
        interactive arrival finds every slot full, so the tail never
        waits out a flood request's full decode.
        ``serving_tenant_preemptions`` rides along: the gate passing
        WITHOUT parks would mean the test stopped exercising the
        mechanism. Each arm runs ``reps`` times and reports its BEST
        p99 — OS scheduler jitter only ever inflates a wall-clock
        tail, so the minimum over repetitions is the estimator
        closest to the arm's true p99 and keeps an absolute gate from
        flaking on a noisy host.
      * burst recovery — ``burst_n`` batch-class requests land at
        once on a quiet batcher; sequential interactive probes
        measure how long until latency returns under 2x the
        pre-burst baseline. ``serving_burst_recovery_ms`` rides the
        1.35x rolling-median band in bench.py (first-run-safe: no
        history, no gate).
    """
    import numpy as _np

    from .api import GenerateRequest, ServingError
    from .kvcache import SyntheticKVExecutor
    from .queue import AdmissionQueue, TenantBudget
    from .scheduler import ContinuousBatcher

    step_s = step_ms / 1000.0
    vocab = 32
    rng = _np.random.RandomState(2020)
    out: dict = {}

    def mk_req(tenant, priority, max_tokens):
        return GenerateRequest(
            prompt_vec=None, max_tokens=max_tokens,
            deadline=time.monotonic() + 30.0,
            prompt_tokens=[int(t) for t in
                           rng.randint(0, vocab, size=prompt_tokens)],
            tenant=tenant, priority=priority)

    def mk_plane():
        # prefill_budget covers every occupant's chunk in one step so
        # a flood prefill can delay a good-tenant prefill only through
        # slot occupancy (which preemption resolves), not by
        # serializing the chunk queue.
        ex = SyntheticKVExecutor(
            slots=slots, vocab=vocab, block_size=4, num_blocks=256,
            max_blocks_per_req=16, prefill_chunk=8,
            prefill_budget=8 * slots,
            step_time_s=step_s, host_tier_bytes=1 << 20)
        q = AdmissionQueue(
            max_depth=max(64, 4 * slots),
            tenants={"good": TenantBudget(weight=4.0),
                     "flood": TenantBudget(rate=flood_budget_rate,
                                           burst=8.0, weight=1.0)})
        return ex, q, ContinuousBatcher(ex, q)

    def run_arm(with_flood):
        ex, q, b = mk_plane()
        # The arrival trace: (t_offset, tenant) merged in time order.
        sched = [(i / good_rate, "good")
                 for i in range(int(good_rate * seconds))]
        if with_flood:
            fr = flood_x * good_rate
            sched += [(i / fr, "flood")
                      for i in range(int(fr * seconds))]
        sched.sort()
        good, sheds, flood_n = [], 0, 0
        b.start()
        t0 = time.perf_counter()
        try:
            for t_at, tenant in sched:
                dt = t0 + t_at - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                if tenant == "good":
                    r = mk_req("good", "interactive", good_tokens)
                    q.submit(r)
                    good.append(r)
                else:
                    flood_n += 1
                    try:
                        q.submit(mk_req("flood", "batch",
                                        flood_tokens))
                    except ServingError:
                        sheds += 1  # 429/503: the flood pays itself
            for r in good:
                if not r.wait(timeout=30.0):
                    raise RuntimeError("good-tenant request lost")
                if r.error is not None:
                    raise RuntimeError(f"good-tenant request failed: "
                                       f"{r.error}")
        finally:
            b.stop()
        lat = sorted(r.timings_ms()["total_ms"] for r in good)
        stats = dict(p99=nearest_rank(lat, 0.99),
                     preempted=ex.preempted_total,
                     resumed=ex.preempt_resumed_total,
                     shed_frac=sheds / max(1, flood_n))
        ex.prefix.flush()
        ex.tier.flush()
        ex.allocator.assert_clean()
        ex.tier.assert_clean()
        ex.close()
        return stats

    solos = [run_arm(with_flood=False) for _ in range(reps)]
    conts = [run_arm(with_flood=True) for _ in range(reps)]
    solo_p99 = min(s["p99"] for s in solos)
    cont_p99 = min(c["p99"] for c in conts)
    out["serving_tenant_p99_solo_ms"] = round(solo_p99, 3)
    out["serving_tenant_p99_contended_ms"] = round(cont_p99, 3)
    out["serving_tenant_p99_isolation"] = round(
        cont_p99 / max(1e-9, solo_p99), 3)
    out["serving_tenant_flood_shed_frac"] = round(
        sum(c["shed_frac"] for c in conts) / len(conts), 3)
    out["serving_tenant_preemptions"] = sum(
        c["preempted"] for c in conts)

    # Burst recovery: quiet batcher, pre-burst probe baseline, then a
    # batch-class wall of work and sequential interactive probes until
    # latency settles back under 2x the baseline.
    ex, q, b = mk_plane()
    b.start()
    try:
        def probe():
            r = mk_req("good", "interactive", good_tokens)
            q.submit(r)
            if not r.wait(timeout=30.0) or r.error is not None:
                raise RuntimeError(f"probe failed: {r.error}")
            return r.timings_ms()["total_ms"]

        base = sorted(probe() for _ in range(8))
        base_med = base[len(base) // 2]
        burst = [mk_req("good", "batch", flood_tokens)
                 for _ in range(burst_n)]
        t_burst = time.perf_counter()
        for r in burst:
            q.submit(r)
        recovery_ms = None
        while time.perf_counter() - t_burst < 10.0:
            if probe() <= 2.0 * base_med:
                recovery_ms = (time.perf_counter() - t_burst) * 1000
                break
        if recovery_ms is None:
            raise RuntimeError("burst never recovered inside 10s")
        for r in burst:
            r.wait(timeout=30.0)
        out["serving_burst_recovery_ms"] = round(recovery_ms, 3)
    finally:
        b.stop()
    ex.prefix.flush()
    ex.tier.flush()
    ex.allocator.assert_clean()
    ex.tier.assert_clean()
    ex.close()

    trace(f"multi-tenant qos: good p99 "
          f"{out['serving_tenant_p99_contended_ms']} ms contended vs "
          f"{out['serving_tenant_p99_solo_ms']} ms solo = "
          f"{out['serving_tenant_p99_isolation']}x (flood shed "
          f"{out['serving_tenant_flood_shed_frac']}, "
          f"{out['serving_tenant_preemptions']} preemption(s)); "
          f"burst recovery {out['serving_burst_recovery_ms']} ms")
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--step-ms", type=float, default=4.0,
                    help="fixed per-step executor cost (the accelerator "
                         "cost model)")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--per-client", type=int, default=3)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--overload-x", type=float, default=2.0)
    ap.add_argument("--overload-seconds", type=float, default=3.0)
    ap.add_argument("--overload-deadline-ms", type=float, default=2000.0)
    ap.add_argument("--skip-local", action="store_true",
                    help="skip the jitted-model sections (no jax)")
    ap.add_argument("--decode-reqs", type=int, default=64)
    ap.add_argument("--decode-tokens", type=int, default=32)
    ap.add_argument("--decode-S", type=int, default=2)
    ap.add_argument("--decode-d", type=int, default=64)
    ap.add_argument("--decode-h", type=int, default=128)
    args = ap.parse_args(argv)

    from .executor import SyntheticExecutor
    from .server import ServingServer

    def trace(msg):
        print(f"bench_serving: {msg}", file=sys.stderr, flush=True)

    out: dict = {}
    step_s = args.step_ms / 1000.0

    # 1+2: closed-loop, continuous vs serial, same fixed step cost.
    mk = lambda slots: ServingServer(
        [SyntheticExecutor(slots=slots, d=16, step_time_s=step_s)],
        max_queue_depth=max(64, 4 * args.clients)).start()
    cont, serial = mk(args.slots), mk(1)
    try:
        closed_loop(cont.url, 2, 2, 2)
        closed_loop(serial.url, 2, 2, 2)
        wall, lat, codes = closed_loop(
            cont.url, args.clients, args.per_client, args.max_tokens)
        n_ok = sum(1 for c in codes if c == 200)
        q = _quantiles(lat)
        out.update(
            serving_reqs_per_s=round(n_ok / wall, 2),
            serving_tok_per_s=round(n_ok * args.max_tokens / wall, 1),
            serving_p50_ms=q["p50"], serving_p95_ms=q["p95"],
            serving_p99_ms=q["p99"])
        trace(f"continuous: {out['serving_reqs_per_s']} req/s "
              f"p99={q['p99']} ms over {n_ok} reqs")

        wall_s, lat_s, codes_s = closed_loop(
            serial.url, args.clients, args.per_client, args.max_tokens)
        n_ok_s = sum(1 for c in codes_s if c == 200)
        out["serving_serial_reqs_per_s"] = round(n_ok_s / wall_s, 2)
        if out["serving_serial_reqs_per_s"]:
            out["serving_batching_speedup"] = round(
                out["serving_reqs_per_s"]
                / out["serving_serial_reqs_per_s"], 2)
        trace(f"serial: {out['serving_serial_reqs_per_s']} req/s → "
              f"speedup {out.get('serving_batching_speedup')}x")
    finally:
        cont.stop()
        serial.stop()

    # 3: open-loop overload at ~2x the measured closed-loop capacity,
    # queue barely deeper than the batch — the shed-don't-park test.
    ov = ServingServer(
        [SyntheticExecutor(slots=args.slots, d=16, step_time_s=step_s)],
        max_queue_depth=args.slots).start()
    try:
        closed_loop(ov.url, 2, 2, 2)
        rate = args.overload_x * max(out["serving_reqs_per_s"], 1.0)
        wall, lat, codes = open_loop(
            ov.url, rate, args.overload_seconds, args.max_tokens,
            args.overload_deadline_ms)
        n_ok = sum(1 for c in codes if c == 200)
        n_503 = sum(1 for c in codes if c == 503)
        q = _quantiles(lat)
        alive = False
        try:
            alive = urllib.request.urlopen(
                ov.url + "/healthz", timeout=5).status == 200
        except OSError:
            pass
        out.update(
            serving_overload_offered_per_s=round(rate, 1),
            serving_overload_admitted_per_s=round(n_ok / wall, 2),
            serving_overload_shed_frac=round(
                n_503 / max(1, len(codes)), 3),
            serving_overload_p99_ms=q["p99"],
            serving_overload_healthz_ok=alive,
            serving_overload_other_codes=sorted(
                {c for c in codes if c not in (200, 503)}))
        trace(f"overload @{rate:.0f}/s: admitted "
              f"{out['serving_overload_admitted_per_s']}/s, shed "
              f"{out['serving_overload_shed_frac']}, p99 {q['p99']} ms, "
              f"healthz={alive}")
    finally:
        ov.stop()

    # 6: fault recovery — a deterministic replica kill at 2x overload;
    # the self-healing plane's headline numbers.
    try:
        out.update(fault_recovery(args.slots, step_s,
                                  out.get("serving_reqs_per_s", 0.0),
                                  trace))
    except Exception as e:
        out["serving_fault_error"] = str(e)[:200]
        trace(f"fault-recovery section failed: {e}")

    # 8: paged-KV decode at 2x overload, with/without prefix sharing
    # (ISSUE 7). Synthetic token-plane replicas: the figure moves on
    # scheduler/KV regressions, nothing else.
    try:
        out.update(kv_paged_serving(args.slots, step_s, trace))
    except Exception as e:
        out["serving_kv_error"] = str(e)[:200]
        trace(f"paged-kv section failed: {e}")

    # 9: sharded-vs-local decode decomposition (ISSUE 8). Synthetic
    # shard plane (fixed compute + collective cost): the figures move
    # on coordinator/shard scheduling regressions, nothing else.
    try:
        out.update(sharded_decode(args.slots, trace))
    except Exception as e:
        out["serving_sharded_error"] = str(e)[:200]
        trace(f"sharded-decode section failed: {e}")

    # 10: cross-process tracing overhead (ISSUE 11) — the section-9
    # sharded pipelined loop, traced vs untraced, paired interleaved;
    # gated absolute (<= 0.02) in bench.py like section 7.
    try:
        out.update(sharded_trace_overhead(args.slots, trace))
    except Exception as e:
        out["serving_sharded_trace_error"] = str(e)[:200]
        trace(f"sharded-trace-overhead section failed: {e}")

    # 12: disaggregated prefill/decode vs colocated under a prefill
    # flood (ISSUE 14) — the cross-replica isolation gate
    # (serving_decode_p99_ms) + page-stream Gb/s and the transfer-vs-
    # re-prefill breakeven, all on the synthetic cost model.
    try:
        out.update(disagg_serving(trace))
    except Exception as e:
        out["serving_disagg_error"] = str(e)[:200]
        trace(f"disagg section failed: {e}")

    # 13: speculative draft/verify decode vs the one-token baseline
    # (ISSUE 15) — accepted tokens/s/slot at a controlled acceptance
    # rate on the synthetic cost model; gated >= 0.85x rolling median
    # (serving_spec_tokens_per_s) + the ABSOLUTE >= 1.5x speedup
    # acceptance gate in bench.py.
    try:
        out.update(speculative_decode(trace))
    except Exception as e:
        out["serving_spec_error"] = str(e)[:200]
        trace(f"speculative-decode section failed: {e}")

    # 16: pipelined speculative decode (ISSUE 18) — overlap the
    # priced draft with the device's verify step; pipelined-spec vs
    # the PR 15 sync-spec loop vs the one-token loop, with the
    # accept-rate + replan-rate + step-cost decomposition; gated on
    # the ABSOLUTE >= 1.25x over-sync-spec acceptance criterion +
    # a rolling-median throughput gate in bench.py.
    try:
        out.update(pipelined_speculative_decode(trace))
    except Exception as e:
        out["serving_pspec_error"] = str(e)[:200]
        trace(f"pipelined-spec section failed: {e}")

    # 15: cluster-wide prefix cache (ISSUE 17) — prefix-aware routing
    # + host-RAM KV tiering vs prefix-blind round-robin on identical
    # replicas and request order; gated on the ABSOLUTE >= 1.5x hit-
    # frac uplift + <= 0.7x TTFT-p99 acceptance pair in bench.py.
    try:
        out.update(cluster_prefix(trace))
    except Exception as e:
        out["serving_cluster_prefix_error"] = str(e)[:200]
        trace(f"cluster-prefix section failed: {e}")

    # 17: multi-tenant QoS (ISSUE 20) — tenant-isolation p99 ratio
    # under an adversarial batch-class flood (ABSOLUTE <= 1.35 gate in
    # bench.py) + interactive burst-recovery time (1.35x rolling-
    # median band), all on the synthetic fixed-step cost model.
    try:
        out.update(multi_tenant_qos(trace))
    except Exception as e:
        out["serving_qos_error"] = str(e)[:200]
        trace(f"multi-tenant-qos section failed: {e}")

    # 4: the real jitted path — forward-only train_step model on a mesh.
    if not args.skip_local:
        try:
            from .executor import LocalExecutor

            t0 = time.perf_counter()
            ex = LocalExecutor(slots=args.slots, S=1, d=8, h=8, E=1)
            out["serving_local_compile_s"] = round(
                time.perf_counter() - t0, 2)
            local = ServingServer([ex], max_queue_depth=64).start()
            try:
                closed_loop(local.url, 2, 2, 2)
                wall, lat, codes = closed_loop(
                    local.url, args.clients, args.per_client,
                    args.max_tokens)
                n_ok = sum(1 for c in codes if c == 200)
                out["serving_local_reqs_per_s"] = round(n_ok / wall, 2)
                out["serving_local_p99_ms"] = _quantiles(lat)["p99"]
                trace(f"local jitted model: "
                      f"{out['serving_local_reqs_per_s']} req/s")
            finally:
                local.stop()
        except Exception as e:  # the headline figures stand regardless
            out["serving_local_error"] = str(e)[:200]
            trace(f"local section failed: {e}")

        # 5: decode-loop decomposition — sync vs device-resident
        # pipelined over the same jitted model at the same slot count.
        try:
            out.update(decode_loop_rates(
                args.slots,
                dict(S=args.decode_S, d=args.decode_d, h=args.decode_h,
                     E=1),
                args.decode_reqs, args.decode_tokens, trace))
        except Exception as e:
            out["serving_decode_error"] = str(e)[:200]
            trace(f"decode section failed: {e}")

        # 7: tracing overhead (ISSUE 6) — traced vs untraced pipelined
        # decode over the same jitted model; gated absolute (<= 0.02)
        # in bench.py.
        try:
            out.update(trace_overhead(
                args.slots,
                dict(S=args.decode_S, d=args.decode_d, h=args.decode_h,
                     E=1),
                args.decode_reqs, args.decode_tokens, trace))
        except Exception as e:
            out["serving_trace_error"] = str(e)[:200]
            trace(f"trace-overhead section failed: {e}")

        # 11: fused paged attention + quantized KV residency
        # (ISSUE 13) — Pallas-vs-XLA device decomposition, int8
        # bytes/slot accounting, interpret-mode equivalence on CPU.
        try:
            out.update(paged_attn_bench(trace))
        except Exception as e:
            out["serving_paged_attn_error"] = str(e)[:200]
            trace(f"paged-attn section failed: {e}")

        # 14: context-parallel paged KV (ISSUE 16) — resident context
        # per replica vs world (KVSpec arithmetic, ABSOLUTE >= 1.7x
        # gate at world 2), measured sharded decode tokens/s + p99 vs
        # the single-worker twin, per-rank transfer decomposition.
        try:
            out.update(sharded_kv_scaling(trace))
        except Exception as e:
            out["serving_shard_kv_error"] = str(e)[:200]
            trace(f"sharded-kv section failed: {e}")

    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
