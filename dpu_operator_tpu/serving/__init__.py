"""Serving plane: continuous-batching inference over the TPU fabric.

The request path:

    HTTP POST /v1/generate (server.py)
      → bounded AdmissionQueue (queue.py — 503 + Retry-After past depth)
      → ContinuousBatcher slot (scheduler.py — admit/retire at step
        boundaries; pipelined: submit step k, settle step k-1 while
        the device runs)
      → Executor seam (executor.py: submit/collect two-phase decode,
        step(x) sync fallback; in-process jax replica today,
        fabric-worker replica later)
      → DecodeStep (infer.py — device-resident forward-only train_step
        model on a mesh; only token ids cross PCIe)

The paged-KV decode plane (ISSUE 7) lives in kvcache/: token-level
executors over device-resident attention state with block-granular
prefix reuse and chunked prefill, driven by the SAME queue/batcher/
pool machinery (the batcher picks its KV loop off ``executor.kv``).

The disaggregated plane (ISSUE 14) lives in disagg/: role-typed
prefill/decode ReplicaPools with KV pages streamed between their
pools over the fabric (``DisaggPool``; hand off via ``pool_factory=``
on the ServingServer) — see docs/serving.md.

Speculative decoding (ISSUE 15) lives in spec.py: the draft-model
contract, greedy-verify acceptance math and bookkeeping behind the KV
executors' third mode (``PagedKVExecutor(mode="speculative")`` /
``SyntheticKVExecutor(spec=SpecConfig(...))``) — k drafted tokens
verified per slot in one batched step, rejection truncated at the
collect-confirmed watermark.

Importing this package stays jax-free; jax loads only when a
LocalExecutor or PagedKVExecutor is constructed.
"""

from .api import (PRIORITIES, Draining, GenerateRequest, QueueFull,
                  ServingError, TenantOverBudget, encode_prompt,
                  encode_prompt_tokens)
from .autoscale import RoleAutoscaler
from .disagg import DisaggPool, KVSpec, KVSpecMismatch
from .executor import (Executor, LocalExecutor, ReplicaPool,
                       SyntheticExecutor)
from .kvcache import (HostKVTier, KVBlockAllocator, KVCacheOOM,
                      KVLease, PagedKVExecutor, ParkedKV, PrefixTree,
                      ShardedPagedKVExecutor, SyntheticKVExecutor)
from .queue import AdmissionQueue, TenantBudget
from .router import PrefixRouter, RouterReplica
from .scheduler import ContinuousBatcher
from .server import ServingServer
from .spec import NO_TOKEN, OracleDraft, SpecConfig, TruncatedDraft
from .sharded import (FabricExecutor, ShardProcessSet,
                      SyntheticShardSet)

__all__ = [
    "AdmissionQueue",
    "ContinuousBatcher",
    "DisaggPool",
    "Draining",
    "Executor",
    "FabricExecutor",
    "GenerateRequest",
    "HostKVTier",
    "KVBlockAllocator",
    "KVCacheOOM",
    "KVLease",
    "KVSpec",
    "KVSpecMismatch",
    "LocalExecutor",
    "NO_TOKEN",
    "OracleDraft",
    "PRIORITIES",
    "PagedKVExecutor",
    "ParkedKV",
    "PrefixRouter",
    "PrefixTree",
    "QueueFull",
    "RoleAutoscaler",
    "RouterReplica",
    "ReplicaPool",
    "ServingError",
    "ServingServer",
    "ShardProcessSet",
    "ShardedPagedKVExecutor",
    "SpecConfig",
    "SyntheticExecutor",
    "SyntheticKVExecutor",
    "SyntheticShardSet",
    "TenantBudget",
    "TenantOverBudget",
    "TruncatedDraft",
    "encode_prompt",
    "encode_prompt_tokens",
]
