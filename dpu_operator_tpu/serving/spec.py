"""Speculative decoding: the draft-model contract and acceptance math.

ISSUE 15 makes tokens-per-step the throughput lever: a cheap DRAFT
model proposes ``k`` tokens per decode slot, the target model verifies
all ``k + 1`` positions in ONE batched step (the chunked-prefill plan
machinery re-used: ``host_tok[s, :k+1]``, ``n_new[s] = k+1``), and
greedy argmax verification accepts the longest prefix on which the
draft matched the target — plus the target's one bonus token, so every
verify step emits at least the token the one-token baseline would
have.

The verify recurrence, 0-indexed over one slot's step window:

  * inputs fed:   ``[last, d_1, .., d_k]`` at positions
    ``ctx .. ctx+k`` (``last`` = the slot's last settled token);
  * target out:   ``t_j`` = the target's argmax after consuming input
    ``j`` (per-position logits — the ISSUE 15 kernel change);
  * acceptance:   ``t_0`` always (it equals exactly the non-spec
    step's emit); ``t_j`` for ``j >= 1`` iff ``d_j == t_{j-1}`` and
    every earlier draft matched — i.e. ``a = accept_length(draft,
    target)`` leading matches accept ``t_0 .. t_a``: ``a + 1`` tokens.

Rejection is a WATERMARK TRUNCATION, not a device unwind: the plan
advanced ``st.ctx`` by ``k + 1`` assuming full acceptance, and collect
rolls it back to ``plan_ctx + a + 1`` while the collect-confirmed
watermark (built in PR 7 precisely so uncollected positions can never
poison the prefix cache) advances only to the accepted extent. KV
written at rejected positions is dead bytes the next append
overwrites — K/V at a position depends only on that position's input
embedding, so the re-append after a rollback writes exactly what an
unspeculated run would have.

This module is the jax-free plane of the contract (numpy only — the
scheduler imports it): the sentinel + emit-masking idiom shared by
both collect paths, the acceptance math, the bookkeeping, and the two
shipped drafts. ``TruncatedDraft`` lazy-imports jax in its
constructor only.

Draft contract
--------------

``draft.propose(last[S] int32, ctx[S] int32) -> [S, k] int32`` —
called ONCE per planned step with fixed-shape full-slot arrays (rows
for slots not in decode regime carry zeros and are ignored), so a
jitted draft AOT-compiles one executable. ``k`` is fixed at draft
construction and must satisfy ``k + 1 <= prefill_chunk`` (the verify
window rides the prefill chunk's compiled width). Draft proposals
chain on the draft's OWN tokens (after a mispredict the tail is dead
anyway — it can never be accepted past the first mismatch).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

#: collect() sentinel for "no token emitted at this position" — ONE
#: definition shared by the one-token collect path
#: (kvcache/executor.py), the speculative collect path, and the
#: scheduler's retire, so the two collect paths cannot drift.
NO_TOKEN = -1


def token_run(row) -> List[int]:
    """The per-slot emit-masking idiom, hoisted (ISSUE 15 cleanup):
    the emitted-token run of one collect row — the leading prefix of
    valid (``>= 0``) tokens, stopped at the first NO_TOKEN pad. Both
    collect shapes normalize through it: a scalar/0-d entry is a run
    of length <= 1, a speculative row is the accepted run."""
    arr = np.atleast_1d(np.asarray(row))
    out: List[int] = []
    for t in arr:
        if int(t) < 0:
            break
        out.append(int(t))
    return out


def accept_length(draft, target) -> int:
    """Greedy-verify acceptance: the number ``a`` of leading draft
    positions where ``draft[j] == target[j]`` — the target tokens
    ``target[:a + 1]`` (matches plus the bonus) are the step's
    accepted run. Deterministic: greedy argmax on both sides means no
    sampling correction is needed (the Leviathan/Chen rejection-
    sampling machinery degenerates to exact prefix match)."""
    draft = np.asarray(draft).reshape(-1)
    target = np.asarray(target).reshape(-1)
    a = 0
    while a < len(draft) and a < len(target) \
            and int(draft[a]) == int(target[a]):
        a += 1
    return a


def synthetic_next_token(tok: int, pos: int, seed: int,
                         vocab: int) -> int:
    """The synthetic token plane's target recurrence — ONE definition
    shared by SyntheticKVExecutor's device and the OracleDraft that
    predicts it, so the oracle can never drift from the model it
    drafts for."""
    return (31 * int(tok) + 7 * int(pos) + int(seed)) % int(vocab)


class SpecStats:
    """Acceptance bookkeeping, mutated ONLY under the executor's
    collect owner-guard (proposed at plan time is the one exception —
    a proposal exists whether or not its step survives, and a stale
    step's proposals correctly depress the measured rate)."""

    __slots__ = ("proposed", "accepted", "runs")

    def __init__(self):
        self.proposed = 0   # draft tokens fed to verify steps
        self.accepted = 0   # draft tokens the target confirmed
        self.runs = 0       # verify steps collected

    def accept_rate(self) -> float:
        """Accepted fraction of proposed draft tokens (positions after
        a run's first mismatch count as rejected — this is the
        REALIZED rate, which is what the speedup math depends on, not
        the per-position oracle rate)."""
        return self.accepted / self.proposed if self.proposed else 0.0

    def tokens_per_step(self) -> float:
        """Emitted tokens per verify step: accepted drafts + the bonus
        token every step carries. 1.0 = the one-token baseline."""
        return ((self.accepted + self.runs) / self.runs
                if self.runs else 0.0)


class SpecConfig:
    """One executor's speculative-decoding configuration: the draft,
    the per-slot proposal depth ``k``, and the acceptance stats. The
    executor validates ``k + 1 <= prefill_chunk`` (the verify window
    is the compiled chunk width) and that it runs the sync loop shape
    — the next plan needs the previous step's ACCEPTED length, so
    collect-before-plan is structural, not a tuning choice."""

    def __init__(self, draft, k: int):
        if k < 1:
            raise ValueError(f"spec k must be >= 1, got {k}")
        draft_k = getattr(draft, "k", None)
        if draft_k is not None and int(draft_k) != int(k):
            raise ValueError(
                f"draft proposes k={draft_k} tokens but the config "
                f"asks for k={k}")
        self.draft = draft
        self.k = int(k)
        self.stats = SpecStats()


class OracleDraft:
    """Controlled-acceptance draft for the synthetic token plane: it
    KNOWS the target recurrence (synthetic_next_token) and corrupts
    each proposal with a deterministic hash of (token, position) so
    the per-position hit rate is ``accept_rate`` — the dial the bench
    and the equivalence tests turn. Pure function of (last, ctx):
    byte-identical streams across runs, loop shapes, and resumes."""

    def __init__(self, k: int, accept_rate: float = 0.7,
                 vocab: int = 64, target_seed: int = 0,
                 seed: int = 0):
        if not 0.0 <= accept_rate <= 1.0:
            raise ValueError(f"accept_rate must be in [0, 1], got "
                             f"{accept_rate}")
        self.k = int(k)
        self.accept_rate = float(accept_rate)
        self.vocab = int(vocab)
        self.target_seed = int(target_seed)
        self.seed = int(seed)

    def _hit(self, tok: int, pos: int) -> bool:
        # LCG-style mix: deterministic, position- and token-sensitive,
        # cheap. The 23-bit hash compares against a threshold in the
        # SAME domain (no modulo fold — a `% 1e6` over 2^23 residues
        # would bias mid rates by ~1.4 points), so the per-position
        # rate is accept_rate to within 2^-23 and 0.0/1.0 are exact.
        h = (1103515245 * (tok * 131 + pos * 7919 + self.seed)
             + 12345) & 0x7FFFFFFF
        return (h >> 8) < int(round(self.accept_rate * (1 << 23)))

    def propose(self, last, ctx) -> np.ndarray:
        last = np.asarray(last, np.int64)
        ctx = np.asarray(ctx, np.int64)
        out = np.zeros((len(last), self.k), np.int32)
        for s in range(len(last)):
            t = int(last[s])
            for j in range(self.k):
                pos = int(ctx[s]) + j
                nxt = synthetic_next_token(t, pos, self.target_seed,
                                           self.vocab)
                if not self._hit(t, pos):
                    nxt = (nxt + 1) % self.vocab  # deliberate miss
                out[s, j] = nxt
                t = nxt  # chain on own proposal (dead past a miss)
        return out


class TruncatedDraft:
    """The jitted plane's cheap draft: a TRUNCATED-STAGE variant of
    the target PagedDecodeStep — the SAME embed/positional/output
    weights with the attention and MLP stages cut, so the draft is
    attention-free (no KV, no block tables, no gather) and one AOT
    executable proposes all k tokens for every slot in one dispatch:

        x_j = embed[t_j] + wpos[pos_j];  t_{j+1} = argmax(x_j @ wout)

    Acceptance against the full target is whatever the truncation
    earns — correctness never depends on it (a 0%-accept draft still
    yields byte-identical streams at one bonus token per step); the
    CONTROLLED-rate speedup measurements use OracleDraft on the
    synthetic plane instead."""

    def __init__(self, embed, wpos, wout, k: int, slots: int):
        import jax
        import jax.numpy as jnp

        self.k = int(k)
        T = int(wpos.shape[0])

        def propose(last, ctx):
            t = last
            cols = []
            for j in range(self.k):
                pos = jnp.clip(ctx + j, 0, T - 1)
                x = embed[t] + wpos[pos]
                t = jnp.argmax(x @ wout, axis=-1).astype(jnp.int32)
                cols.append(t)
            return jnp.stack(cols, axis=1)

        z = jnp.zeros((int(slots),), jnp.int32)
        self._fn = jax.jit(propose).lower(z, z).compile()

    @classmethod
    def from_paged(cls, paged_step, k: int) -> "TruncatedDraft":
        """Build from a kvcache/paged.PagedDecodeStep — the weights
        are the ones its executable already closed over, so draft and
        target can never disagree on the token space."""
        embed, wpos, wout = paged_step.draft_params
        return cls(embed, wpos, wout, k, paged_step.slots)

    def propose(self, last, ctx) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(self._fn(jnp.asarray(last, jnp.int32),
                                   jnp.asarray(ctx, jnp.int32)),
                          np.int32)


def clamp_spec_k(k: int, ctx: int, max_total: int, chunk: int) -> int:
    """Per-slot draft depth under the page-reservation bound. With
    ``r = max_total - ctx - 1`` tokens still owed (``max_total =
    plen + max_tokens``), drafting beyond ``r - 1`` can only propose
    tokens past the request's budget — and, critically, would append
    KV past the worst-case pages reserved at admission (the plan's
    clipped table gather would silently scatter into table entry
    B-1's block — another slot era's data). Clamped, the maximum
    position a verify step writes equals the one-token loop's
    maximum, so ADMISSION MATH IS UNCHANGED: no extra slack pages,
    no new OOM class. Also bounded by the compiled chunk width
    (``k + 1 <= chunk``)."""
    owed = int(max_total) - int(ctx) - 1
    return max(0, min(int(k), owed - 1, int(chunk) - 1))


__all__ = [
    "NO_TOKEN",
    "OracleDraft",
    "SpecConfig",
    "SpecStats",
    "TruncatedDraft",
    "accept_length",
    "clamp_spec_k",
    "synthetic_next_token",
    "token_run",
]
