"""Speculative decoding: the draft-model contract and acceptance math.

ISSUE 15 makes tokens-per-step the throughput lever: a cheap DRAFT
model proposes ``k`` tokens per decode slot, the target model verifies
all ``k + 1`` positions in ONE batched step (the chunked-prefill plan
machinery re-used: ``host_tok[s, :k+1]``, ``n_new[s] = k+1``), and
greedy argmax verification accepts the longest prefix on which the
draft matched the target — plus the target's one bonus token, so every
verify step emits at least the token the one-token baseline would
have.

The verify recurrence, 0-indexed over one slot's step window:

  * inputs fed:   ``[last, d_1, .., d_k]`` at positions
    ``ctx .. ctx+k`` (``last`` = the slot's last settled token);
  * target out:   ``t_j`` = the target's argmax after consuming input
    ``j`` (per-position logits — the ISSUE 15 kernel change);
  * acceptance:   ``t_0`` always (it equals exactly the non-spec
    step's emit); ``t_j`` for ``j >= 1`` iff ``d_j == t_{j-1}`` and
    every earlier draft matched — i.e. ``a = accept_length(draft,
    target)`` leading matches accept ``t_0 .. t_a``: ``a + 1`` tokens.

Rejection is a WATERMARK TRUNCATION, not a device unwind: the plan
advanced ``st.ctx`` by ``k + 1`` assuming full acceptance, and collect
rolls it back to ``plan_ctx + a + 1`` while the collect-confirmed
watermark (built in PR 7 precisely so uncollected positions can never
poison the prefix cache) advances only to the accepted extent. KV
written at rejected positions is dead bytes the next append
overwrites — K/V at a position depends only on that position's input
embedding, so the re-append after a rollback writes exactly what an
unspeculated run would have.

This module is the jax-free plane of the contract (numpy only — the
scheduler imports it): the sentinel + emit-masking idiom shared by
both collect paths, the acceptance math, the bookkeeping, and the two
shipped drafts. ``TruncatedDraft`` lazy-imports jax in its
constructor only.

Draft contract
--------------

``draft.propose(last[S] int32, ctx[S] int32) -> [S, k] int32`` —
called ONCE per planned step with fixed-shape full-slot arrays (rows
for slots not in decode regime carry zeros and are ignored), so a
jitted draft AOT-compiles one executable. ``k`` is fixed at draft
construction and must satisfy ``k + 1 <= prefill_chunk`` (the verify
window rides the prefill chunk's compiled width). Draft proposals
chain on the draft's OWN tokens (after a mispredict the tail is dead
anyway — it can never be accepted past the first mismatch).

ISSUE 18 widens the contract two ways, both optional:

* PIPELINED plan-ahead needs one proposal PAST the chain —
  ``propose_full`` wraps any chain draft and returns ``[S, k+1]``
  (two fixed-shape propose calls), so the planner can seed window
  ``w+1`` from window ``w``'s own predicted bonus token while the
  device still verifies window ``w``.
* TREE drafts branch at the FIRST draft position (where acceptance
  entropy concentrates — the Medusa/SpecInfer observation):
  ``draft.tree_width = W >= 2`` plus
  ``draft.propose_sibs(last[S], ctx[S]) -> [S, W-1] int32`` —
  alternative candidates for the trunk's first proposal. The verify
  window scores trunk AND siblings in one batched step under a
  tree-causal mask; ``accept_tree`` picks the longest matching
  root-to-leaf path (trunk wins ties), still exact greedy prefix
  match.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

#: collect() sentinel for "no token emitted at this position" — ONE
#: definition shared by the one-token collect path
#: (kvcache/executor.py), the speculative collect path, and the
#: scheduler's retire, so the two collect paths cannot drift.
NO_TOKEN = -1


def token_run(row) -> List[int]:
    """The per-slot emit-masking idiom, hoisted (ISSUE 15 cleanup):
    the emitted-token run of one collect row — the leading prefix of
    valid (``>= 0``) tokens, stopped at the first NO_TOKEN pad. Both
    collect shapes normalize through it: a scalar/0-d entry is a run
    of length <= 1, a speculative row is the accepted run."""
    arr = np.atleast_1d(np.asarray(row))
    out: List[int] = []
    for t in arr:
        if int(t) < 0:
            break
        out.append(int(t))
    return out


def accept_length(draft, target) -> int:
    """Greedy-verify acceptance: the number ``a`` of leading draft
    positions where ``draft[j] == target[j]`` — the target tokens
    ``target[:a + 1]`` (matches plus the bonus) are the step's
    accepted run. Deterministic: greedy argmax on both sides means no
    sampling correction is needed (the Leviathan/Chen rejection-
    sampling machinery degenerates to exact prefix match)."""
    draft = np.asarray(draft).reshape(-1)
    target = np.asarray(target).reshape(-1)
    a = 0
    while a < len(draft) and a < len(target) \
            and int(draft[a]) == int(target[a]):
        a += 1
    return a


def synthetic_next_token(tok: int, pos: int, seed: int,
                         vocab: int) -> int:
    """The synthetic token plane's target recurrence — ONE definition
    shared by SyntheticKVExecutor's device and the OracleDraft that
    predicts it, so the oracle can never drift from the model it
    drafts for."""
    return (31 * int(tok) + 7 * int(pos) + int(seed)) % int(vocab)


class SpecStats:
    """Acceptance bookkeeping, mutated ONLY under the executor's
    collect owner-guard (proposed at plan time is the one exception —
    a proposal exists whether or not its step survives, and a stale
    step's proposals correctly depress the measured rate)."""

    __slots__ = ("proposed", "accepted", "runs", "replans",
                 "path_len", "pipeline_peak")

    def __init__(self):
        self.proposed = 0   # draft tokens fed to verify steps
        self.accepted = 0   # draft tokens the target confirmed
        self.runs = 0       # verify steps collected
        self.replans = 0    # plan-ahead windows invalidated by a
        #                     rollback (collected as epoch-stale no-ops)
        self.path_len: dict = {}  # accepted path length -> count
        #                     (root-to-leaf tokens settled per run)
        self.pipeline_peak = 0  # max spec windows in flight at once

    def record_run(self, accepted: int, path_len: int) -> None:
        """One collected verify step: ``accepted`` draft tokens
        confirmed, ``path_len`` tokens settled (accepted + bonus, or
        the sibling path's 2)."""
        self.runs += 1
        self.accepted += int(accepted)
        n = int(path_len)
        self.path_len[n] = self.path_len.get(n, 0) + 1

    def accept_rate(self) -> float:
        """Accepted fraction of proposed draft tokens (positions after
        a run's first mismatch count as rejected — this is the
        REALIZED rate, which is what the speedup math depends on, not
        the per-position oracle rate)."""
        return self.accepted / self.proposed if self.proposed else 0.0

    def tokens_per_step(self) -> float:
        """Emitted tokens per verify step: accepted drafts + the bonus
        token every step carries. 1.0 = the one-token baseline."""
        return ((self.accepted + self.runs) / self.runs
                if self.runs else 0.0)


class SpecConfig:
    """One executor's speculative-decoding configuration: the draft,
    the per-slot proposal depth ``k``, the tree width, the adaptive
    dial, and the acceptance stats. The executor validates
    ``k + 1 <= prefill_chunk`` (the verify window is the compiled
    chunk width). Since ISSUE 18 the config no longer forces the sync
    loop shape: a pipelined executor drafts window ``w+1`` from window
    ``w``'s PROPOSED tokens (provisional ctx, the same provisional-
    advance discipline the plan already uses) and a mis-speculation is
    the existing watermark rollback plus a re-plan.

    ``adaptive=True`` turns on the per-slot accept-rate EWMA dial: a
    slot whose realized rate decays stops paying full draft depth
    (``k`` shrinks toward ``k_min`` through ``clamp_spec_k``) and a
    hot slot climbs back; tree width drops to 1 while the trunk is
    hot (siblings only pay when the first position misses)."""

    def __init__(self, draft, k: int, tree_width: Optional[int] = None,
                 adaptive: bool = False, k_min: int = 1,
                 ewma_alpha: float = 0.3):
        if k < 1:
            raise ValueError(f"spec k must be >= 1, got {k}")
        draft_k = getattr(draft, "k", None)
        if draft_k is not None and int(draft_k) != int(k):
            raise ValueError(
                f"draft proposes k={draft_k} tokens but the config "
                f"asks for k={k}")
        if tree_width is None:
            tree_width = int(getattr(draft, "tree_width", 1) or 1)
        if tree_width < 1:
            raise ValueError(
                f"tree_width must be >= 1, got {tree_width}")
        if tree_width > 1 and not hasattr(draft, "propose_sibs"):
            raise ValueError(
                "tree_width > 1 needs a draft with propose_sibs()")
        if not 1 <= int(k_min) <= int(k):
            raise ValueError(
                f"k_min must be in [1, k={k}], got {k_min}")
        if not 0.0 < float(ewma_alpha) <= 1.0:
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.draft = draft
        self.k = int(k)
        self.tree_width = int(tree_width)
        self.adaptive = bool(adaptive)
        self.k_min = int(k_min)
        self.ewma_alpha = float(ewma_alpha)
        self.stats = SpecStats()

    def k_for(self, ewma: float) -> int:
        """The adaptive dial: map a slot's accept-rate EWMA onto a
        draft depth in ``[k_min, k]`` (linear — the EWMA is already
        the realized fraction of drafts that paid off). Inert when
        ``adaptive=False``."""
        if not self.adaptive:
            return self.k
        r = min(1.0, max(0.0, float(ewma)))
        return self.k_min + int(round(r * (self.k - self.k_min)))

    def width_for(self, ewma: float) -> int:
        """Adaptive tree width: siblings only earn tokens when the
        trunk's FIRST position misses, so a hot slot (EWMA >= 0.9)
        drops back to a pure chain and stops paying the sibling
        verify rows."""
        if self.tree_width <= 1:
            return 1
        if self.adaptive and float(ewma) >= 0.9:
            return 1
        return self.tree_width


class OracleDraft:
    """Controlled-acceptance draft for the synthetic token plane: it
    KNOWS the target recurrence (synthetic_next_token) and corrupts
    each proposal with a deterministic hash of (token, position) so
    the per-position hit rate is ``accept_rate`` — the dial the bench
    and the equivalence tests turn. Pure function of (last, ctx):
    byte-identical streams across runs, loop shapes, and resumes."""

    def __init__(self, k: int, accept_rate: float = 0.7,
                 vocab: int = 64, target_seed: int = 0,
                 seed: int = 0, tree_width: int = 1,
                 sib_rate: float = 0.5):
        if not 0.0 <= accept_rate <= 1.0:
            raise ValueError(f"accept_rate must be in [0, 1], got "
                             f"{accept_rate}")
        if tree_width < 1:
            raise ValueError(f"tree_width must be >= 1, got "
                             f"{tree_width}")
        if not 0.0 <= sib_rate <= 1.0:
            raise ValueError(f"sib_rate must be in [0, 1], got "
                             f"{sib_rate}")
        self.k = int(k)
        self.accept_rate = float(accept_rate)
        self.vocab = int(vocab)
        self.target_seed = int(target_seed)
        self.seed = int(seed)
        self.tree_width = int(tree_width)
        self.sib_rate = float(sib_rate)  # P(some sibling recovers a
        #                                  trunk first-position miss)

    def _hit(self, tok: int, pos: int) -> bool:
        # LCG-style mix: deterministic, position- and token-sensitive,
        # cheap. The 23-bit hash compares against a threshold in the
        # SAME domain (no modulo fold — a `% 1e6` over 2^23 residues
        # would bias mid rates by ~1.4 points), so the per-position
        # rate is accept_rate to within 2^-23 and 0.0/1.0 are exact.
        h = (1103515245 * (tok * 131 + pos * 7919 + self.seed)
             + 12345) & 0x7FFFFFFF
        return (h >> 8) < int(round(self.accept_rate * (1 << 23)))

    def propose(self, last, ctx) -> np.ndarray:
        last = np.asarray(last, np.int64)
        ctx = np.asarray(ctx, np.int64)
        out = np.zeros((len(last), self.k), np.int32)
        for s in range(len(last)):
            t = int(last[s])
            for j in range(self.k):
                pos = int(ctx[s]) + j
                nxt = synthetic_next_token(t, pos, self.target_seed,
                                           self.vocab)
                if not self._hit(t, pos):
                    nxt = (nxt + 1) % self.vocab  # deliberate miss
                out[s, j] = nxt
                t = nxt  # chain on own proposal (dead past a miss)
        return out

    def _sib_hit(self, tok: int, pos: int) -> bool:
        # Second, independent mix (different multiplier/increment)
        # dialing the SIBLING recovery rate: given the trunk missed
        # at the first position, does some sibling carry the true
        # token? Independence from _hit keeps the two dials
        # orthogonal in the equivalence matrix.
        h = (1664525 * (tok * 131 + pos * 7919 + self.seed + 17)
             + 1013904223) & 0x7FFFFFFF
        return (h >> 8) < int(round(self.sib_rate * (1 << 23)))

    def propose_sibs(self, last, ctx) -> np.ndarray:
        """Alternative candidates for the FIRST draft position (the
        tree's branch point). Pure function of (last, ctx) like
        propose, so the plan-ahead / resume determinism arguments
        carry over. When the trunk's first proposal missed and the
        sib hash fires, sibling 0 carries the TRUE next token —
        the dial the tree-path tests and bench turn; the remaining
        siblings are deliberate distinct misses."""
        last = np.asarray(last, np.int64)
        ctx = np.asarray(ctx, np.int64)
        w = self.tree_width - 1
        out = np.zeros((len(last), max(w, 0)), np.int32)
        for s in range(len(last)):
            t = int(last[s])
            pos = int(ctx[s])
            true = synthetic_next_token(t, pos, self.target_seed,
                                        self.vocab)
            trunk_hit = self._hit(t, pos)
            recover = (not trunk_hit) and self._sib_hit(t, pos)
            for i in range(w):
                if i == 0 and recover:
                    out[s, i] = true
                else:
                    # distinct from the trunk's proposal AND the true
                    # token, so a non-recovering sibling never
                    # matches by accident
                    out[s, i] = (true + 2 + i) % self.vocab
        return out


class TruncatedDraft:
    """The jitted plane's cheap draft: a TRUNCATED-STAGE variant of
    the target PagedDecodeStep — the SAME embed/positional/output
    weights with the attention and MLP stages cut, so the draft is
    attention-free (no KV, no block tables, no gather) and one AOT
    executable proposes all k tokens for every slot in one dispatch:

        x_j = embed[t_j] + wpos[pos_j];  t_{j+1} = argmax(x_j @ wout)

    Acceptance against the full target is whatever the truncation
    earns — correctness never depends on it (a 0%-accept draft still
    yields byte-identical streams at one bonus token per step); the
    CONTROLLED-rate speedup measurements use OracleDraft on the
    synthetic plane instead."""

    def __init__(self, embed, wpos, wout, k: int, slots: int,
                 tree_width: int = 1):
        import jax
        import jax.numpy as jnp

        self.k = int(k)
        self.tree_width = int(tree_width)
        T = int(wpos.shape[0])

        def propose(last, ctx):
            t = last
            cols = []
            for j in range(self.k):
                pos = jnp.clip(ctx + j, 0, T - 1)
                x = embed[t] + wpos[pos]
                t = jnp.argmax(x @ wout, axis=-1).astype(jnp.int32)
                cols.append(t)
            return jnp.stack(cols, axis=1)

        z = jnp.zeros((int(slots),), jnp.int32)
        self._fn = jax.jit(propose).lower(z, z).compile()
        self._sib_fn = None
        if self.tree_width > 1:
            import jax.lax as lax
            W = self.tree_width

            def sibs(last, ctx):
                # ranks 2..W of the first-position logits: the trunk
                # already carries rank 1, so siblings are the next
                # most probable alternatives at the branch point
                pos = jnp.clip(ctx, 0, T - 1)
                x = embed[last] + wpos[pos]
                _, idx = lax.top_k(x @ wout, W)
                return idx[:, 1:W].astype(jnp.int32)

            self._sib_fn = jax.jit(sibs).lower(z, z).compile()

    @classmethod
    def from_paged(cls, paged_step, k: int,
                   tree_width: int = 1) -> "TruncatedDraft":
        """Build from a kvcache/paged.PagedDecodeStep — the weights
        are the ones its executable already closed over, so draft and
        target can never disagree on the token space."""
        embed, wpos, wout = paged_step.draft_params
        return cls(embed, wpos, wout, k, paged_step.slots,
                   tree_width=tree_width)

    def propose(self, last, ctx) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(self._fn(jnp.asarray(last, jnp.int32),
                                   jnp.asarray(ctx, jnp.int32)),
                          np.int32)

    def propose_sibs(self, last, ctx) -> np.ndarray:
        import jax.numpy as jnp

        if self._sib_fn is None:
            return np.zeros((len(np.asarray(last)), 0), np.int32)
        return np.asarray(self._sib_fn(jnp.asarray(last, jnp.int32),
                                       jnp.asarray(ctx, jnp.int32)),
                          np.int32)


def propose_full(draft, last, ctx) -> np.ndarray:
    """``[S, k+1]`` proposals: the draft's k-chain PLUS one more
    chained step — the draft's own prediction of the verify window's
    BONUS token. The pipelined planner needs it to seed window
    ``w+1`` before window ``w``'s true bonus exists: under full
    acceptance the window settles ``[d_1 .. d_k, t_k]`` and every
    token except ``t_k`` is host-known, so the plan-ahead drafts from
    the PREDICTED ``t_k`` (= column ``ks`` here) while the device row
    chains the true one. Two fixed-shape propose calls, so a jitted
    draft stays AOT: column j of propose(last, ctx) is the draft's
    prediction for the target's output at position ``ctx + j``, and
    re-seeding at ``(p_k, ctx + k)`` continues the SAME chain.

    A draft may fuse the two calls by exposing its own
    ``propose_full(last, ctx) -> [S, k+1]`` (one batched invocation —
    what a real draft model does; also what lets a cost-modelled
    draft charge ONE window latency instead of two)."""
    fused = getattr(draft, "propose_full", None)
    if fused is not None:
        out = np.asarray(fused(last, ctx), np.int32)
        if out.shape[1] != draft.k + 1:
            raise ValueError(
                f"draft.propose_full returned width {out.shape[1]}, "
                f"wanted k+1 = {draft.k + 1}")
        return out
    p = np.asarray(draft.propose(last, ctx), np.int32)
    ctx = np.asarray(ctx, np.int64)
    q = np.asarray(draft.propose(p[:, -1], ctx + draft.k), np.int32)
    return np.concatenate([p, q[:, :1]], axis=1)


def accept_tree(drafts, sibs, target_trunk, target_sibs):
    """Longest matching root-to-leaf path through the verify window's
    token tree — still exact greedy prefix match, per branch.

    ``drafts[ks]`` = trunk proposals, ``sibs[w]`` = first-position
    siblings, ``target_trunk[ks+1]`` = target outputs of the base +
    trunk rows (``t_0 .. t_ks``), ``target_sibs[w]`` = target outputs
    of the sibling rows. Returns ``(run, sib_idx)``: the settled
    token run and which sibling won (-1 = trunk path). The trunk
    wins ties — its tokens are already APPENDED at their positions,
    so equal-length paths prefer the one needing no repair. A sibling
    path only beats the trunk when the trunk's FIRST position missed
    (trunk path length 1) and a sibling carries the true ``t_0``:
    then the sibling row's output is the target's next token after
    it — 2 tokens instead of 1."""
    a = accept_length(drafts, target_trunk)
    tt = np.atleast_1d(np.asarray(target_trunk))
    if a == 0 and len(np.atleast_1d(np.asarray(sibs))):
        t0 = int(tt[0])
        ts = np.atleast_1d(np.asarray(target_sibs))
        for i, sb in enumerate(np.atleast_1d(np.asarray(sibs))):
            if int(sb) == t0:
                return [t0, int(ts[i])], int(i)
    return [int(t) for t in tt[:a + 1]], -1


def clamp_spec_k(k: int, ctx: int, max_total: int, chunk: int) -> int:
    """Per-slot draft depth under the page-reservation bound. With
    ``r = max_total - ctx - 1`` tokens still owed (``max_total =
    plen + max_tokens``), drafting beyond ``r - 1`` can only propose
    tokens past the request's budget — and, critically, would append
    KV past the worst-case pages reserved at admission (the plan's
    clipped table gather would silently scatter into table entry
    B-1's block — another slot era's data). Clamped, the maximum
    position a verify step writes equals the one-token loop's
    maximum, so ADMISSION MATH IS UNCHANGED: no extra slack pages,
    no new OOM class. Also bounded by the compiled chunk width
    (``k + 1 <= chunk``)."""
    owed = int(max_total) - int(ctx) - 1
    return max(0, min(int(k), owed - 1, int(chunk) - 1))


__all__ = [
    "NO_TOKEN",
    "OracleDraft",
    "SpecConfig",
    "SpecStats",
    "TruncatedDraft",
    "accept_length",
    "accept_tree",
    "clamp_spec_k",
    "propose_full",
    "synthetic_next_token",
    "token_run",
]
