"""Bounded admission queue — the backpressure point of the serving plane.

Overload policy (the Orca/vLLM-era contract): the queue has a hard
depth; past it, submission fails IMMEDIATELY with QueueFull and the
HTTP layer returns 503 + Retry-After. Latency for admitted requests
stays bounded because the excess is rejected at the door instead of
parked — queue depth, not queue time, is the knob. Requests whose
deadline expires while still queued are shed at pop time (they would
only waste batch slots on an answer nobody is waiting for).

begin_drain() flips the queue to refuse-new mode for SIGTERM drain:
already-queued work still pops and completes; submissions raise
Draining.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Tuple

from .. import faults
from ..obs import trace as obs_trace
from .api import (DEADLINE_QUEUED_ERROR, Draining, GenerateRequest,
                  QueueFull)


class AdmissionQueue:
    def __init__(self, max_depth: int = 64, retry_after_s: float = 1.0,
                 registry=None, tracer=None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self.retry_after_s = retry_after_s
        self._registry = registry
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._q: deque = deque()
        self._draining = False
        self._inflight = 0  # popped by a batcher, not yet in a slot
        self.rejected_full = 0
        self.rejected_draining = 0
        self.shed_expired = 0
        self.requeued = 0

    def _gauge(self) -> None:
        if self._registry is not None:
            self._registry.gauge_set(
                "serving_queue_depth", float(len(self._q)),
                help="requests waiting for a batch slot")

    def submit(self, req: GenerateRequest) -> None:
        faults.fire("queue.submit")
        with self._lock:
            if self._draining:
                self.rejected_draining += 1
                raise Draining("server is draining")
            if len(self._q) >= self.max_depth:
                self.rejected_full += 1
                raise QueueFull(len(self._q), self.retry_after_s)
            req.enqueued_at = time.monotonic()
            self._q.append(req)
            depth = len(self._q)
            self._gauge()
            self._nonempty.notify()
        self.tracer.event("queue.enqueue", request_id=req.request_id,
                          parent_id=req.trace_parent,
                          attrs={"depth": depth})

    def get_many(self, n: int, timeout: float = 0.0
                 ) -> List[GenerateRequest]:
        """Pop up to n requests; blocks up to `timeout` only while the
        queue is empty (a busy batcher polls with timeout=0 so decode
        steps never stall on admission). Expired entries settle here:
        a 503-mapped fail — or, when a requeued request already
        carries settled tokens, the truncated-200 mid-decode contract
        (same disposition as the supervisor's _requeue)."""
        out: List[GenerateRequest] = []
        shed: List[Tuple[GenerateRequest, str]] = []
        with self._lock:
            if not self._q and timeout > 0:
                self._nonempty.wait(timeout)
            now = time.monotonic()
            while self._q and len(out) < n:
                req = self._q.popleft()
                if req.done:
                    # Settled elsewhere while queued (e.g. the HTTP
                    # handler's wedge-timeout 500): drop. Settling
                    # again would mutate truncated/finished_at after
                    # the response was written — the same double-
                    # settle the supervisor's _requeue guards against.
                    continue
                if req.deadline <= now:
                    if req.tokens:
                        # A requeued resumable-lease request keeps its
                        # settled tokens (ISSUE 7): its deadline
                        # lapsing HERE is the same mid-decode
                        # truncation as lapsing mid-failure in the
                        # supervisor's _requeue — 200 with what was
                        # decoded, never a 503 that discards it.
                        # finish() releases the lease via the settle
                        # choke point.
                        req.truncated = True
                        req.finish()
                        shed.append((req, "deadline_truncated"))
                    else:
                        self.shed_expired += 1
                        req.fail(DEADLINE_QUEUED_ERROR)
                        shed.append((req, "deadline_queued"))
                    continue
                out.append(req)
            # Popped requests are invisible to depth() but not yet in a
            # slot (active). Counting them under the SAME lock as the
            # pop closes the quiesce race: at no instant can a request
            # be in none of depth/inflight/active — drain's "everything
            # finished" check must see it somewhere.
            self._inflight += len(out)
            self._gauge()
        # Trace OUTSIDE the lock: span recording is lock-light but the
        # queue lock is on the submit hot path.
        tr = self.tracer
        if tr.enabled:
            for req, reason in shed:
                tr.event("queue.shed", request_id=req.request_id,
                         parent_id=req.trace_parent,
                         attrs={"reason": reason})
                tr.decision("shed", request_id=req.request_id)
            for req in out:
                # The wait span covers (re-)enqueue → pop — the
                # "queue" leg of the request's timeline. enqueued_at,
                # not arrival: a requeued request's second wait must
                # not swallow its failed first decode attempt.
                tr.record_span("queue.wait", req.enqueued_at, now,
                               request_id=req.request_id,
                               parent_id=req.trace_parent)
        return out

    def requeue(self, req: GenerateRequest) -> None:
        """Supervisor re-admission of a request seized from a dead or
        wedged replica. Front of the line (it already waited its turn
        once) and EXEMPT from both the depth bound and the drain
        refusal: the request was admitted before the failure, so
        shedding it now would convert a replica fault into a
        client-visible overload answer even while capacity exists —
        and a drain must finish admitted work, re-admitted included."""
        with self._lock:
            req.enqueued_at = time.monotonic()
            self._q.appendleft(req)
            self.requeued += 1
            self._gauge()
            self._nonempty.notify()
        # kv_blocks records block-table ownership riding the queue
        # (ISSUE 7): a resumable lease means the next admit re-attaches
        # these pages instead of re-prefilling the prompt.
        lease = getattr(req, "kv_lease", None)
        self.tracer.event(
            "queue.requeue", request_id=req.request_id,
            parent_id=req.trace_parent,
            attrs={"attempts": req.attempts,
                   "kv_blocks": (len(lease.blocks)
                                 if lease is not None
                                 and lease.resumable else 0)})

    def mark_placed(self, n: int) -> None:
        """The batcher finished placing (or failing) n popped requests."""
        with self._lock:
            self._inflight -= n

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True
            self._nonempty.notify_all()

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def fail_all(self, error: str) -> int:
        """Empty the queue, failing every waiter (server stop path)."""
        with self._lock:
            n = len(self._q)
            while self._q:
                self._q.popleft().fail(error)
            self._gauge()
        return n
