"""Bounded admission queue — the backpressure point of the serving plane.

Overload policy (the Orca/vLLM-era contract): the queue has a hard
depth; past it, submission fails IMMEDIATELY with QueueFull and the
HTTP layer returns 503 + Retry-After. Latency for admitted requests
stays bounded because the excess is rejected at the door instead of
parked — queue depth, not queue time, is the knob. Requests whose
deadline expires while still queued are shed at pop time (they would
only waste batch slots on an answer nobody is waiting for).

Multi-tenant QoS (ISSUE 20): with a ``tenants=`` budget map installed,
admission and pop both become tenant-aware —

  * **token buckets** — each tenant's submissions spend a seeded
    bucket (``rate`` req/s refill up to ``burst``); an empty bucket
    raises TenantOverBudget (HTTP 429 + Retry-After) so one flooding
    tenant sheds against its OWN budget while everyone else admits
    normally. A tenant's queued depth is additionally capped at its
    weight's share of ``max_depth`` — the queue itself can't be
    monopolized between refills.
  * **priority classes** — two strict classes (api.PRIORITIES):
    every queued ``interactive`` request pops before any ``batch``
    request. Within a class, tenants are served weighted round-robin
    (``weight`` consecutive pops per visit), so equal-weight tenants
    interleave even when one keeps its deque full.

Without ``tenants=`` the queue is byte-for-byte the single-tenant
contract every earlier PR tested: one global depth bound, FIFO within
each priority class (and everything defaults to interactive).

begin_drain() flips the queue to refuse-new mode for SIGTERM drain:
already-queued work still pops and completes; submissions raise
Draining. ``requeue`` — the supervisor's seize path AND the batcher's
preemption park — stays exempt from depth, drain and budgets: the
request was admitted once already, and shedding it now would convert
a fault (or a policy decision) into a client-visible overload answer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import faults
from ..obs import trace as obs_trace
from .api import (DEADLINE_QUEUED_ERROR, PRIORITIES, Draining,
                  GenerateRequest, QueueFull, TenantOverBudget,
                  bounded_tenant_label)


class TenantBudget:
    """One tenant's admission contract: ``rate`` requests/second of
    token-bucket refill up to ``burst`` (None rate = unmetered), and a
    ``weight`` that sets both its round-robin quantum within its
    priority class and its share of the queue's depth bound."""

    __slots__ = ("rate", "burst", "weight")

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None, weight: float = 1.0):
        if rate is not None and rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self.rate = float(rate) if rate is not None else None
        self.burst = (float(burst) if burst is not None
                      else max(1.0, self.rate or 1.0))
        self.weight = float(weight)


class AdmissionQueue:
    def __init__(self, max_depth: int = 64, retry_after_s: float = 1.0,
                 registry=None, tracer=None,
                 tenants: Optional[Dict[str, TenantBudget]] = None,
                 default_budget: Optional[TenantBudget] = None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self.retry_after_s = retry_after_s
        self._registry = registry
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        # priority -> tenant -> deque. Deques are pruned when empty so
        # tenant-name cardinality can't grow the pop scan unboundedly.
        self._qs: Dict[str, Dict[str, deque]] = {p: {}
                                                 for p in PRIORITIES}
        # Per-priority weighted-RR pop state: (tenant, quantum_left).
        self._cursor: Dict[str, Optional[Tuple[str, float]]] = {
            p: None for p in PRIORITIES}
        self._n = 0
        self._n_by_prio: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._n_by_tenant: Dict[str, int] = {}
        self._tenants = dict(tenants) if tenants else {}
        self._default_budget = default_budget
        # tenant -> [tokens, last_refill] (monotonic clock).
        self._buckets: Dict[str, List[float]] = {}
        self._label_seen: set = set()
        self._draining = False
        self._inflight = 0  # popped by a batcher, not yet in a slot
        self.rejected_full = 0
        self.rejected_draining = 0
        self.rejected_over_budget = 0
        self.shed_expired = 0
        self.requeued = 0
        self.preempted_requeued = 0

    # -- tenant bookkeeping ---------------------------------------------------

    def _budget(self, tenant: str) -> Optional[TenantBudget]:
        got = self._tenants.get(tenant)
        return got if got is not None else self._default_budget

    def _weight(self, tenant: str) -> float:
        b = self._budget(tenant)
        return b.weight if b is not None else 1.0

    def _tenant_depth_cap(self, tenant: str) -> int:
        """This tenant's share of max_depth, by weight — only enforced
        when a tenant budget map is installed (the single-tenant plane
        keeps the one global bound)."""
        if not self._tenants:
            return self.max_depth
        total = sum(b.weight for b in self._tenants.values())
        if self._default_budget is not None:
            total += self._default_budget.weight
        share = self._weight(tenant) / max(1e-9, total)
        return max(1, int(self.max_depth * share))

    def _charge_bucket(self, tenant: str, now: float) -> bool:
        """Spend one token from the tenant's bucket; False = empty.
        Unmetered tenants (no budget / no rate) always pass."""
        b = self._budget(tenant)
        if b is None or b.rate is None:
            return True
        cell = self._buckets.get(tenant)
        if cell is None:
            cell = self._buckets[tenant] = [b.burst, now]
        tokens = min(b.burst, cell[0] + (now - cell[1]) * b.rate)
        cell[1] = now
        if tokens < 1.0:
            cell[0] = tokens
            return False
        cell[0] = tokens - 1.0
        return True

    def _count_shed(self, tenant: str, reason: str) -> None:
        if self._registry is not None:
            label = bounded_tenant_label(tenant, self._label_seen)
            self._registry.counter_inc(
                "serving_queue_shed_total",
                {"tenant": label, "reason": reason},
                help="admission-queue sheds by tenant and reason "
                     "(tenant label bounded at TENANT_LABEL_CAP)")

    def _gauge(self) -> None:
        if self._registry is not None:
            self._registry.gauge_set(
                "serving_queue_depth", float(self._n),
                help="requests waiting for a batch slot")

    # -- enqueue/dequeue core (callers hold self._lock) -----------------------

    def _push_locked(self, req: GenerateRequest, front: bool) -> None:
        prio = req.priority if req.priority in PRIORITIES else "interactive"
        dq = self._qs[prio].get(req.tenant)
        if dq is None:
            dq = self._qs[prio][req.tenant] = deque()
        (dq.appendleft if front else dq.append)(req)
        self._n += 1
        self._n_by_prio[prio] += 1
        self._n_by_tenant[req.tenant] = (
            self._n_by_tenant.get(req.tenant, 0) + 1)

    def _pop_locked(self) -> Optional[GenerateRequest]:
        """Next request by strict priority class, weighted round-robin
        across tenants within the class: the cursor tenant serves up
        to ``weight`` consecutive pops, then the next tenant (sorted
        name order — deterministic) takes over."""
        for prio in PRIORITIES:
            qs = self._qs[prio]
            if not self._n_by_prio[prio]:
                continue
            names = sorted(t for t in qs if qs[t])
            if not names:
                continue
            cur = self._cursor[prio]
            if (cur is None or cur[1] < 1.0 or not qs.get(cur[0])):
                prev = cur[0] if cur is not None else None
                later = [t for t in names
                         if prev is None or t > prev]
                name = (later or names)[0]
                cur = (name, self._weight(name))
            name, left = cur
            req = qs[name].popleft()
            if not qs[name]:
                del qs[name]
            self._cursor[prio] = (name, left - 1.0)
            self._n -= 1
            self._n_by_prio[prio] -= 1
            nt = self._n_by_tenant.get(name, 0) - 1
            if nt <= 0:
                self._n_by_tenant.pop(name, None)
            else:
                self._n_by_tenant[name] = nt
            return req
        return None

    # -- public API -----------------------------------------------------------

    def submit(self, req: GenerateRequest) -> None:
        faults.fire("queue.submit")
        shed_tenant: Optional[Tuple[str, str]] = None
        try:
            with self._lock:
                if self._draining:
                    self.rejected_draining += 1
                    raise Draining("server is draining")
                now = time.monotonic()
                if not self._charge_bucket(req.tenant, now):
                    self.rejected_over_budget += 1
                    shed_tenant = (req.tenant, "over_budget")
                    b = self._budget(req.tenant)
                    raise TenantOverBudget(
                        req.tenant,
                        max(self.retry_after_s,
                            1.0 / b.rate if b and b.rate else 0.0))
                if (self._n >= self.max_depth
                        or (self._n_by_tenant.get(req.tenant, 0)
                            >= self._tenant_depth_cap(req.tenant))):
                    self.rejected_full += 1
                    shed_tenant = (req.tenant, "full")
                    raise QueueFull(self._n, self.retry_after_s)
                req.enqueued_at = now
                self._push_locked(req, front=False)
                depth = self._n
                self._gauge()
                self._nonempty.notify()
        finally:
            # Counter AND trace outside the queue lock (both take
            # their own locks; this one is on the submit hot path).
            if shed_tenant is not None:
                self._count_shed(*shed_tenant)
        self.tracer.event("queue.enqueue", request_id=req.request_id,
                          parent_id=req.trace_parent,
                          attrs={"depth": depth,
                                 "tenant": req.tenant,
                                 "priority": req.priority})

    def get_many(self, n: int, timeout: float = 0.0
                 ) -> List[GenerateRequest]:
        """Pop up to n requests; blocks up to `timeout` only while the
        queue is empty (a busy batcher polls with timeout=0 so decode
        steps never stall on admission). Expired entries settle here:
        a 503-mapped fail — or, when a requeued request already
        carries settled tokens, the truncated-200 mid-decode contract
        (same disposition as the supervisor's _requeue)."""
        out: List[GenerateRequest] = []
        shed: List[Tuple[GenerateRequest, str]] = []
        with self._lock:
            if not self._n and timeout > 0:
                self._nonempty.wait(timeout)
            now = time.monotonic()
            while len(out) < n:
                req = self._pop_locked()
                if req is None:
                    break
                if req.done:
                    # Settled elsewhere while queued (e.g. the HTTP
                    # handler's wedge-timeout 500): drop. Settling
                    # again would mutate truncated/finished_at after
                    # the response was written — the same double-
                    # settle the supervisor's _requeue guards against.
                    continue
                if req.deadline <= now:
                    if req.tokens:
                        # A requeued resumable-lease request keeps its
                        # settled tokens (ISSUE 7): its deadline
                        # lapsing HERE is the same mid-decode
                        # truncation as lapsing mid-failure in the
                        # supervisor's _requeue — 200 with what was
                        # decoded, never a 503 that discards it.
                        # finish() releases the lease via the settle
                        # choke point (a preemption-parked lease's
                        # pinned tier pages check in the same way).
                        req.truncated = True
                        req.finish()
                        shed.append((req, "deadline_truncated"))
                    else:
                        self.shed_expired += 1
                        req.fail(DEADLINE_QUEUED_ERROR)
                        shed.append((req, "deadline_queued"))
                    continue
                out.append(req)
            # Popped requests are invisible to depth() but not yet in a
            # slot (active). Counting them under the SAME lock as the
            # pop closes the quiesce race: at no instant can a request
            # be in none of depth/inflight/active — drain's "everything
            # finished" check must see it somewhere.
            self._inflight += len(out)
            self._gauge()
        # Trace OUTSIDE the lock: span recording is lock-light but the
        # queue lock is on the submit hot path.
        for req, reason in shed:
            self._count_shed(req.tenant, reason)
        tr = self.tracer
        if tr.enabled:
            for req, reason in shed:
                tr.event("queue.shed", request_id=req.request_id,
                         parent_id=req.trace_parent,
                         attrs={"reason": reason,
                                "tenant": req.tenant})
                tr.decision("shed", request_id=req.request_id)
            for req in out:
                # The wait span covers (re-)enqueue → pop — the
                # "queue" leg of the request's timeline. enqueued_at,
                # not arrival: a requeued request's second wait must
                # not swallow its failed first decode attempt.
                tr.record_span("queue.wait", req.enqueued_at, now,
                               request_id=req.request_id,
                               parent_id=req.trace_parent)
        return out

    def requeue(self, req: GenerateRequest,
                preempted: bool = False) -> None:
        """Re-admission of an already-admitted request: the
        supervisor's seize path, and — with ``preempted=True`` — the
        batcher's KV-preemption park. Front of its OWN priority class
        (it already waited its turn once; a parked batch request must
        still never overtake queued interactive work) and EXEMPT from
        the depth bound, the drain refusal and the tenant budgets: the
        request was admitted before the failure/park, so shedding it
        now would convert a replica fault — or a scheduling decision —
        into a client-visible overload answer even while capacity
        exists. Never touches ``attempts``: that budget counts replica
        faults survived, and preemption is policy, not failure."""
        with self._lock:
            req.enqueued_at = time.monotonic()
            self._push_locked(req, front=True)
            self.requeued += 1
            if preempted:
                self.preempted_requeued += 1
            self._gauge()
            self._nonempty.notify()
        # kv_blocks records block-table ownership riding the queue
        # (ISSUE 7): a resumable lease means the next admit re-attaches
        # these pages instead of re-prefilling the prompt (a parked
        # ParkedKV resumes from pinned host-tier pages the same way).
        lease = getattr(req, "kv_lease", None)
        self.tracer.event(
            "queue.requeue", request_id=req.request_id,
            parent_id=req.trace_parent,
            attrs={"attempts": req.attempts,
                   "preempted": preempted,
                   "kv_blocks": (len(lease.blocks)
                                 if lease is not None
                                 and lease.resumable else 0)})

    def waiting(self, priority: Optional[str] = None) -> int:
        """Queued count, optionally for one priority class — the
        batcher's preemption trigger reads waiting("interactive")."""
        with self._lock:
            if priority is None:
                return self._n
            return self._n_by_prio.get(priority, 0)

    def mark_placed(self, n: int) -> None:
        """The batcher finished placing (or failing) n popped requests."""
        with self._lock:
            self._inflight -= n

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def depth(self) -> int:
        with self._lock:
            return self._n

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True
            self._nonempty.notify_all()

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def fail_all(self, error: str) -> int:
        """Empty the queue, failing every waiter (server stop path)."""
        with self._lock:
            n = self._n
            while True:
                req = self._pop_locked()
                if req is None:
                    break
                req.fail(error)
            self._gauge()
        return n
