"""Forward-only `infer_step` built from the train_step.py model.

The serving plane runs the SAME stage math the five-axis training step
trains — train_step._stage_fn's Megatron-paired dense block + Switch
MoE — stripped to a pure forward on a jax mesh: no loss, no VJP, no
optimizer, jitted ONCE for a fixed [slots, d] batch shape so the
continuous-batching scheduler never recompiles as requests come and go
(slot count is static; occupancy varies, shapes don't — the vLLM
fixed-slot discipline).

Mesh contract: the serving mesh keeps pp == sp == 1 (no microbatch
pipeline and no sequence axis in the decode state; every stage is
local), and shards the BATCH over ("dp", "ep") with weights over
tp/ep — the inference projection of train_step's token-sharded layout
(each ep device routes its own distinct batch rows, so the MoE
all_to_all carries no duplicates; tp replicates rows and shards the
matmul, the Megatron pairing).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from ..parallel.train_step import AXES, _stage_fn, param_specs


def serving_mesh(devices: Optional[Sequence] = None,
                 shape: Optional[Dict[str, int]] = None):
    """A 5-axis (dp, pp, sp, tp, ep) mesh for the forward-only step.
    Default: ONE device, every axis singleton — the per-replica shape;
    `shape` assigns sizes to dp/tp/ep (pp and sp must stay 1)."""
    import jax
    from jax.sharding import Mesh

    shape = dict(shape or {})
    if shape.get("pp", 1) != 1 or shape.get("sp", 1) != 1:
        raise ValueError(
            "serving mesh keeps pp == sp == 1: decode state has no "
            f"sequence axis and every stage is local, got {shape}")
    if devices is None:
        n = 1
        for a in ("dp", "tp", "ep"):
            n *= shape.get(a, 1)
        devices = jax.devices()[:n]
    sizes = tuple(shape.get(a, 1) for a in AXES)
    want = int(np.prod(sizes))
    if len(devices) != want:
        raise ValueError(
            f"mesh shape {dict(zip(AXES, sizes))} needs {want} devices, "
            f"got {len(devices)}")
    return Mesh(np.array(devices).reshape(sizes), AXES)


def make_infer_step(mesh, capacity_factor: float = 4.0):
    """infer_step(params, x[B, d]) -> y[B, d]: one decode step of the
    stage stack. Params are the stage-stacked train_step.init_params
    layout (leading dim S) in param_specs sharding; with pp == 1 the
    whole stack is local to every device and the stage loop unrolls at
    trace time. B must divide by dp·ep (batch rows shard over both)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel._compat import shard_map

    for axis in ("pp", "sp"):
        if mesh.shape[axis] != 1:
            raise ValueError(
                f"infer_step requires {axis}=1, got {mesh.shape[axis]}")
    E = mesh.shape["ep"]
    specs = param_specs()
    x_spec = P(("dp", "ep"), None)

    def per_device(params_local, x_loc):
        S = params_local["router"].shape[0]
        # Idle slots are EXACTLY zero-filled (the scheduler's contract)
        # and stay zero through every stage (relu/tanh/psum of zero).
        # They must also vanish from MoE routing: a zero row's uniform
        # softmax would win bucket slot 0 by stream priority and, on an
        # ep-sharded mesh under capacity pressure, silently drop a REAL
        # token's dispatch — making decode output occupancy-dependent.
        active = jnp.any(x_loc != 0, axis=1)
        x = x_loc
        for s in range(S):
            p = jax.tree.map(lambda a: a[s], params_local)
            x = _stage_fn(p, x, E=E, tp_axis="tp", ep_axis="ep",
                          capacity_factor=capacity_factor,
                          row_mask=active)
        return x

    @jax.jit
    def infer_step(params, x):
        return shard_map(
            per_device, mesh=mesh, in_specs=(specs, x_spec),
            out_specs=x_spec, check_vma=False)(params, x)

    return infer_step
