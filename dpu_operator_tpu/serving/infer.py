"""Forward-only `infer_step` built from the train_step.py model.

The serving plane runs the SAME stage math the five-axis training step
trains — train_step._stage_fn's Megatron-paired dense block + Switch
MoE — stripped to a pure forward on a jax mesh: no loss, no VJP, no
optimizer, jitted ONCE for a fixed [slots, d] batch shape so the
continuous-batching scheduler never recompiles as requests come and go
(slot count is static; occupancy varies, shapes don't — the vLLM
fixed-slot discipline).

Mesh contract: the serving mesh keeps pp == sp == 1 (no microbatch
pipeline and no sequence axis in the decode state; every stage is
local), and shards the BATCH over ("dp", "ep") with weights over
tp/ep — the inference projection of train_step's token-sharded layout
(each ep device routes its own distinct batch rows, so the MoE
all_to_all carries no duplicates; tp replicates rows and shards the
matmul, the Megatron pairing).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from ..parallel.train_step import AXES, _stage_fn, param_specs


def serving_mesh(devices: Optional[Sequence] = None,
                 shape: Optional[Dict[str, int]] = None):
    """A 5-axis (dp, pp, sp, tp, ep) mesh for the forward-only step.
    Default: ONE device, every axis singleton — the per-replica shape;
    `shape` assigns sizes to dp/tp/ep (pp and sp must stay 1)."""
    import jax
    from jax.sharding import Mesh

    shape = dict(shape or {})
    if shape.get("pp", 1) != 1 or shape.get("sp", 1) != 1:
        raise ValueError(
            "serving mesh keeps pp == sp == 1: decode state has no "
            f"sequence axis and every stage is local, got {shape}")
    if devices is None:
        n = 1
        for a in ("dp", "tp", "ep"):
            n *= shape.get(a, 1)
        devices = jax.devices()[:n]
    sizes = tuple(shape.get(a, 1) for a in AXES)
    want = int(np.prod(sizes))
    if len(devices) != want:
        raise ValueError(
            f"mesh shape {dict(zip(AXES, sizes))} needs {want} devices, "
            f"got {len(devices)}")
    return Mesh(np.array(devices).reshape(sizes), AXES)


def _check_serving_axes(mesh) -> None:
    for axis in ("pp", "sp"):
        if mesh.shape[axis] != 1:
            raise ValueError(
                f"infer_step requires {axis}=1, got {mesh.shape[axis]}")


def _make_per_device(E: int, capacity_factor: float):
    """The per-device stage stack shared by infer_step and DecodeStep."""
    import jax
    import jax.numpy as jnp

    def per_device(params_local, x_loc):
        S = params_local["router"].shape[0]
        # Idle slots are EXACTLY zero-filled (the scheduler's contract)
        # and stay zero through every stage (relu/tanh/psum of zero).
        # They must also vanish from MoE routing: a zero row's uniform
        # softmax would win bucket slot 0 by stream priority and, on an
        # ep-sharded mesh under capacity pressure, silently drop a REAL
        # token's dispatch — making decode output occupancy-dependent.
        active = jnp.any(x_loc != 0, axis=1)
        x = x_loc
        for s in range(S):
            p = jax.tree.map(lambda a: a[s], params_local)
            x = _stage_fn(p, x, E=E, tp_axis="tp", ep_axis="ep",
                          capacity_factor=capacity_factor,
                          row_mask=active)
        return x

    return per_device


def make_infer_step(mesh, capacity_factor: float = 4.0):
    """infer_step(params, x[B, d]) -> y[B, d]: one decode step of the
    stage stack. Params are the stage-stacked train_step.init_params
    layout (leading dim S) in param_specs sharding; with pp == 1 the
    whole stack is local to every device and the stage loop unrolls at
    trace time. B must divide by dp·ep (batch rows shard over both)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel._compat import shard_map

    _check_serving_axes(mesh)
    E = mesh.shape["ep"]
    specs = param_specs()
    x_spec = P(("dp", "ep"), None)
    per_device = _make_per_device(E, capacity_factor)

    @jax.jit
    def infer_step(params, x):
        return shard_map(
            per_device, mesh=mesh, in_specs=(specs, x_spec),
            out_specs=x_spec, check_vma=False)(params, x)

    return infer_step


class DecodeStep:
    """Device-resident decode step: the slot state never round-trips
    the host. One call applies the step's slot updates via an on-device
    scatter, runs the forward stack, and computes per-slot argmax on
    device — only the [slots] int32 token ids cross PCIe when the
    caller materializes them; the [slots, d] state stays put.

    Three deliberate dispatch-cost choices (each measured against the
    PR 2 `np.asarray(infer(params, x))` loop at serving model sizes):

      * params enter as a CLOSURE, not an argument — the executable
        binds the weights once, so per-step python dispatch never
        re-flattens the param pytree. (Weights are baked into the
        executable; a weight swap means building a new DecodeStep.)
      * the no-update step (the common case: admissions only happen
        when a slot frees) compiles as its own single-argument
        executable with no scatter in the graph.
      * the state argument is DONATED on accelerator backends: x_next
        reuses x's buffer, so a decode session allocates its state
        once. Callers must thread the returned state linearly and
        never touch a donated input. On CPU donation is OFF by
        default: the CPU runtime blocks the DISPATCH until the donated
        input's producer finishes (measured ~500us/step here, which
        serializes exactly the async pipeline this class exists for);
        TPU/GPU resolve input-output aliasing at compile time and
        dispatch stays async. `donate` overrides the platform default.

    Updates carry fixed [slots]/[slots, d] shapes (one compile, ever);
    padding entries use index == slots, out of range, dropped by the
    scatter (mode="drop")."""

    def __init__(self, mesh, params, slots: int,
                 capacity_factor: float = 4.0,
                 donate: Optional[bool] = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..parallel._compat import shard_map

        _check_serving_axes(mesh)
        E = mesh.shape["ep"]
        specs = param_specs()
        x_spec = P(("dp", "ep"), None)
        per_device = _make_per_device(E, capacity_factor)
        self.slots = int(slots)
        self.d = int(params["w1"].shape[1])

        def fwd(x):
            y = shard_map(
                per_device, mesh=mesh, in_specs=(specs, x_spec),
                out_specs=x_spec, check_vma=False)(params, x)
            return y, jnp.argmax(y, axis=1).astype(jnp.int32)

        def step_nop(x):
            return fwd(x)

        def step_upd(x, upd_idx, upd_val):
            return fwd(x.at[upd_idx].set(upd_val, mode="drop"))

        if donate is None:
            donate = mesh.devices.flat[0].platform != "cpu"
        self.donate = bool(donate)
        # Own call counter: the overflow ValueError below must name a
        # step even when the scheduler passes none (debug callers).
        self._calls = 0
        dn = (0,) if self.donate else ()
        x0 = jnp.zeros((self.slots, self.d), jnp.float32)
        i0 = jnp.zeros((self.slots,), jnp.int32)
        v0 = jnp.zeros((self.slots, self.d), jnp.float32)
        # AOT-compile both shapes up front: admission latency never
        # includes XLA, and the first request pays nothing the 1000th
        # doesn't.
        self._nop = jax.jit(step_nop, donate_argnums=dn).lower(
            x0).compile()
        self._upd = jax.jit(step_upd, donate_argnums=dn).lower(
            x0, i0, v0).compile()

    def init_state(self):
        """Fresh all-idle [slots, d] device state (exact zeros — the
        scheduler's idle-slot contract)."""
        import jax.numpy as jnp

        return jnp.zeros((self.slots, self.d), jnp.float32)

    def __call__(self, x, updates=(), step=None, request_ids=None):
        """(x_next, token_ids), both device arrays still in flight —
        jax async dispatch returns before the step executes, which is
        what the scheduler's pipelined loop overlaps against. `updates`
        is [(slot, row[d])]; x is consumed when donation is on.
        `step`/`request_ids` are DIAGNOSTIC context only: the batcher's
        seize path can legally race admissions close to the slot limit,
        and an overflow error that names neither the step nor the
        requests being admitted is undebuggable from a flight
        snapshot."""
        self._calls += 1
        if not updates:
            return self._nop(x)
        if len(updates) > self.slots:
            step_no = self._calls if step is None else step
            rids = (", ".join(str(r) for r in request_ids)
                    if request_ids else "unknown")
            raise ValueError(
                f"{len(updates)} updates for {self.slots} slots at "
                f"decode step {step_no} (admitting request_ids: "
                f"{rids}) — at most one update per slot per step")
        idx = np.full((self.slots,), self.slots, np.int32)
        val = np.zeros((self.slots, self.d), np.float32)
        for j, (i, row) in enumerate(updates):
            idx[j] = i
            val[j] = row
        return self._upd(x, idx, val)


def make_decode_step(mesh, params, slots: int,
                     capacity_factor: float = 4.0,
                     donate: Optional[bool] = None) -> DecodeStep:
    """DecodeStep factory, the device-resident sibling of
    make_infer_step (params are bound at build time — see DecodeStep)."""
    return DecodeStep(mesh, params, slots, capacity_factor,
                      donate=donate)
