"""The executor seam: what a batch slot's worth of model step IS.

The continuous-batching scheduler drives replicas through two
contracts, neither of which imports jax:

  * the synchronous seam — `step(x[slots, d]) -> y[slots, d]`, the
    PR 2 shape: the full batch round-trips host numpy every step.
  * the two-phase decode seam — `reset()` / `submit(updates) -> handle`
    / `collect(handle) -> token_ids[slots]`: slot state lives INSIDE
    the executor (on device for LocalExecutor), `submit` applies the
    step's slot updates ([(slot, row[d])] — admitted prompts and zeroed
    freed slots) and dispatches the step, `collect` blocks until the
    step's per-slot argmax token ids are available. When `pipelined`
    is True, submit returns while the step is still executing, so the
    scheduler can do retire/admit bookkeeping for neighbouring steps
    while the device runs — the overlap ISSUE 3 exists for. The base
    class adapts any step()-only executor to the two-phase contract
    (correct, eager, no overlap).

That seam is what lets replicas be swapped:

  * LocalExecutor — the in-process replica: a device-resident
    infer.DecodeStep (pipelined, the default) or infer.make_infer_step
    (mode="sync", the PR 2 loop kept as the measured baseline) on a
    jax mesh (CPU/TPU), params from train_step.init_params or a
    checkpoint. The bench and smoke tests run this one.
  * SyntheticExecutor — a jax-free replica with a CONTROLLED per-step
    cost: the scheduler/backpressure plane's test double (the
    RecordingDataplane idiom from bench.py), and the knob that makes
    overload AND overlap tests deterministic on shared CI boxes
    (pipelined=True runs steps on a worker thread — a "device" whose
    step cost is exactly step_time_s).
  * A fabric-worker-backed replica — the planned third implementation:
    `submit` ships the step's updates to a pool of
    parallel/fabric_worker.py-style processes inside operator-attached
    pod netns (same rendezvous, a forward-only program instead of the
    train slice) and `collect` reads token ids off the fabric — the
    two-phase contract is exactly the async boundary a remote replica
    needs. See docs/serving.md.

ReplicaPool owns one ContinuousBatcher per executor, all fed from one
AdmissionQueue — requests land on whichever replica frees a slot first.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

Update = Tuple[int, np.ndarray]  # (slot index, row[d]) applied at submit


class _Pending:
    """Handle for a step in flight on SyntheticExecutor's worker."""

    __slots__ = ("event", "tokens", "error")

    def __init__(self):
        self.event = threading.Event()
        self.tokens: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class Executor:
    """One model replica: a fixed number of batch slots over a fixed
    feature dim. All methods are called from the replica's single
    batcher thread; they need not be reentrant."""

    slots: int
    d: int
    #: True when submit() natively dispatches asynchronously (returns
    #: while the step executes). The scheduler picks its pipelined loop
    #: off this flag; the base adapter below is eager (no overlap) but
    #: contract-correct for any step()-only executor.
    pipelined: bool = False
    _resident: Optional[np.ndarray] = None

    def step(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- two-phase decode contract (base: eager adapter over step()) ----------

    def reset(self) -> None:
        """Zero the resident slot state (decode session start)."""
        self._resident = np.zeros((self.slots, self.d), np.float32)

    def submit(self, updates: Sequence[Update]):
        """Apply slot updates, dispatch one decode step; returns an
        opaque handle for collect(). Base implementation runs the step
        eagerly on the caller's thread."""
        if self._resident is None:
            self.reset()
        for i, row in updates:
            self._resident[i] = row
        y = np.asarray(self.step(self._resident), np.float32)
        self._resident = y
        # One batched argmax for every slot — the per-row python loop
        # the sync scheduler used to run is measurable at step rates.
        return y.argmax(axis=1).astype(np.int32)

    def collect(self, handle) -> np.ndarray:
        """Block until the submitted step finishes; returns the [slots]
        int32 per-slot argmax token ids."""
        return handle

    def close(self) -> None:
        pass


class LocalExecutor(Executor):
    """In-process replica: forward-only train_step model on a jax mesh.

    mode="pipelined" (default) builds a device-resident infer.DecodeStep:
    slot state lives on device across steps, submit() applies admitted
    rows by on-device scatter and returns while the step executes (jax
    async dispatch), collect() materializes only the [slots] token ids
    — the full batch never round-trips PCIe. mode="sync" keeps the PR 2
    shape (make_infer_step + np.asarray per step) as the comparison
    baseline bench_serving prices the pipeline win against.

    Builds tiny demo params when none are given (the bench/test shape);
    production hands in trained params in init_params layout. XLA
    compile cost is paid in the constructor either way (AOT for the
    decode path, `warmup=True` for the sync path) so admission latency
    never includes it."""

    def __init__(self, params=None, mesh=None, slots: int = 8,
                 capacity_factor: float = 4.0, S: int = 1, d: int = 16,
                 h: int = 32, E: int = 1, seed: int = 0,
                 warmup: bool = True, mode: str = "pipelined"):
        from ..parallel.train_step import init_params, shard_params
        from .infer import make_decode_step, make_infer_step, serving_mesh

        if mode not in ("pipelined", "sync"):
            raise ValueError(f"mode must be pipelined|sync, got {mode!r}")
        self.pipelined = mode == "pipelined"
        self.mesh = mesh if mesh is not None else serving_mesh()
        if params is None:
            if E != self.mesh.shape["ep"]:
                raise ValueError(
                    f"demo params need E == ep axis size "
                    f"{self.mesh.shape['ep']}, got {E}")
            params = init_params(S=S, d=d, h=h, E=E, seed=seed)
        shard = self.mesh.shape["dp"] * self.mesh.shape["ep"]
        if slots % shard:
            raise ValueError(
                f"slots={slots} must divide over dp*ep={shard} "
                f"(batch rows shard over both)")
        self.slots = slots
        self.d = int(params["w1"].shape[1])
        self.params = shard_params(params, self.mesh)
        if self.pipelined:
            self._decode = make_decode_step(self.mesh, self.params,
                                            slots, capacity_factor)
            self._xdev = self._decode.init_state()
            if warmup:
                # One dispatched step so the first request also skips
                # any first-execution lazy initialization.
                self.collect(self.submit([]))
                self.reset()
        else:
            self._infer = make_infer_step(self.mesh, capacity_factor)
            if warmup:
                self.step(np.zeros((self.slots, self.d), np.float32))

    def step(self, x: np.ndarray) -> np.ndarray:
        if not self.pipelined:
            return np.asarray(self._infer(self.params, x))
        # Compat adapter over the resident path: load x wholesale, run
        # one step, materialize the full next state — round-trips the
        # batch like PR 2 and exists for debugging, not the hot loop.
        rows = np.asarray(x, np.float32)
        self._xdev, _tokens = self._decode(
            self._xdev, list(enumerate(rows)))
        return np.asarray(self._xdev)

    def reset(self) -> None:
        if self.pipelined:
            self._xdev = self._decode.init_state()
        else:
            super().reset()

    def submit(self, updates: Sequence[Update]):
        if not self.pipelined:
            return super().submit(updates)
        # Async dispatch: both returned arrays are futures; the state
        # stays on device (the previous buffer was donated into it).
        self._xdev, tokens = self._decode(self._xdev, updates)
        return tokens

    def collect(self, handle) -> np.ndarray:
        if not self.pipelined:
            return handle
        return np.asarray(handle)


class SyntheticExecutor(Executor):
    """Deterministic jax-free replica with a dialable per-step cost.

    y = tanh(x @ W) for a fixed seeded W, after sleeping step_time_s —
    the model-cost knob that makes scheduler/backpressure tests assert
    timing properties instead of hoping the CI box is quiet. With
    pipelined=True, steps run FIFO on a worker thread: submit returns
    immediately and collect blocks on the step's completion, so
    scheduler-overlap assertions (wall ≈ max(host, device), not the
    sum) hold by construction on shared CI boxes."""

    def __init__(self, slots: int = 8, d: int = 16,
                 step_time_s: float = 0.0, seed: int = 0,
                 pipelined: bool = False):
        self.slots = slots
        self.d = d
        self.step_time_s = step_time_s
        self.pipelined = pipelined
        self._w = np.random.RandomState(seed).randn(d, d).astype(
            np.float32) / np.sqrt(d)
        self.steps = 0
        self._work: Optional[_queue.Queue] = None
        self._worker: Optional[threading.Thread] = None

    def step(self, x: np.ndarray) -> np.ndarray:
        if self.step_time_s:
            time.sleep(self.step_time_s)
        self.steps += 1
        return np.tanh(x @ self._w)

    # -- pipelined: the worker thread is the "device" -------------------------

    def _ensure_worker(self) -> None:
        if self._worker is None:
            self._work = _queue.Queue()
            self._worker = threading.Thread(
                target=self._worker_run, daemon=True,
                name="synthetic-step")
            self._worker.start()

    def _worker_run(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            if item[0] == "reset":
                self._resident = np.zeros((self.slots, self.d),
                                          np.float32)
                item[1].set()
                continue
            _, updates, pending = item
            try:
                # The base eager adapter IS one step of the contract
                # (apply updates, step, batched argmax); the worker
                # only moves it off the submitter's thread.
                pending.tokens = Executor.submit(self, updates)
            except BaseException as e:  # surfaced by collect()
                pending.error = e
            pending.event.set()

    def reset(self) -> None:
        if not self.pipelined or self._worker is None:
            super().reset()
            return
        # The worker owns the resident state between submit and
        # collect; a reset must serialize behind queued steps.
        done = threading.Event()
        self._work.put(("reset", done))
        done.wait()

    def submit(self, updates: Sequence[Update]):
        if not self.pipelined:
            return super().submit(updates)
        self._ensure_worker()
        if self._resident is None:
            self._resident = np.zeros((self.slots, self.d), np.float32)
        pending = _Pending()
        self._work.put(("step", list(updates), pending))
        return pending

    def collect(self, handle) -> np.ndarray:
        if not self.pipelined:
            return handle
        handle.event.wait()
        if handle.error is not None:
            raise handle.error
        return handle.tokens

    def close(self) -> None:
        if self._worker is not None:
            self._work.put(None)
            self._worker.join(timeout=5)
            self._worker = None


class ReplicaPool:
    """One ContinuousBatcher per executor over a shared AdmissionQueue."""

    def __init__(self, executors: Sequence[Executor], queue,
                 registry=None):
        from .scheduler import ContinuousBatcher

        if not executors:
            raise ValueError("a pool needs at least one executor")
        self.queue = queue
        self.executors = list(executors)
        self.batchers: List = [
            ContinuousBatcher(ex, queue, registry=registry,
                              replica=f"replica{i}")
            for i, ex in enumerate(self.executors)
        ]

    def start(self) -> None:
        for b in self.batchers:
            b.start()

    def stop(self) -> None:
        for b in self.batchers:
            b.stop()
        for ex in self.executors:
            ex.close()

    def active(self) -> int:
        return sum(b.active for b in self.batchers)

    def quiesce(self, timeout: float = 30.0,
                poll_s: float = 0.02) -> bool:
        """Wait until queue, pop-to-slot hand-off AND every batcher are
        empty (drain path: the queue has already stopped admitting, so
        empty is stable). inflight() covers the window where a request
        is popped but not yet in a slot — without it a drain stop()
        could land exactly there and fail an admitted request."""

        def idle() -> bool:
            return (self.queue.depth() == 0 and self.queue.inflight() == 0
                    and self.active() == 0)

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if idle():
                return True
            time.sleep(poll_s)
        return idle()
