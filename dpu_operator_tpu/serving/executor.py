"""The executor seam: what a batch slot's worth of model step IS.

The continuous-batching scheduler only ever calls
`step(x[slots, d]) -> y[slots, d]` — it neither imports jax nor knows
where the forward runs. That seam is what lets replicas be swapped:

  * LocalExecutor — the in-process replica: infer.make_infer_step on a
    jax mesh (CPU/TPU), params from train_step.init_params or a
    checkpoint. The bench and smoke tests run this one.
  * SyntheticExecutor — a jax-free replica with a CONTROLLED per-step
    cost: the scheduler/backpressure plane's test double (the
    RecordingDataplane idiom from bench.py), and the knob that makes
    overload tests deterministic on shared CI boxes.
  * A fabric-worker-backed replica — the planned third implementation:
    `step` ships the batch to a pool of parallel/fabric_worker.py-style
    processes inside operator-attached pod netns (same rendezvous, a
    forward-only program instead of the train slice) and collects the
    result off the fabric. It needs nothing from the scheduler beyond
    this interface; see docs/serving.md.

ReplicaPool owns one ContinuousBatcher per executor, all fed from one
AdmissionQueue — requests land on whichever replica frees a slot first.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np


class Executor:
    """One model replica: a fixed number of batch slots over a fixed
    feature dim. step() must be safe to call from the replica's single
    batcher thread; it need not be reentrant."""

    slots: int
    d: int

    def step(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalExecutor(Executor):
    """In-process replica: forward-only train_step model on a jax mesh.

    Builds tiny demo params when none are given (the bench/test shape);
    production hands in trained params in init_params layout. The first
    step() after construction pays the jit compile; `warmup=True` pays
    it here instead so admission latency never includes XLA."""

    def __init__(self, params=None, mesh=None, slots: int = 8,
                 capacity_factor: float = 4.0, S: int = 1, d: int = 16,
                 h: int = 32, E: int = 1, seed: int = 0,
                 warmup: bool = True):
        from ..parallel.train_step import init_params, shard_params
        from .infer import make_infer_step, serving_mesh

        self.mesh = mesh if mesh is not None else serving_mesh()
        if params is None:
            if E != self.mesh.shape["ep"]:
                raise ValueError(
                    f"demo params need E == ep axis size "
                    f"{self.mesh.shape['ep']}, got {E}")
            params = init_params(S=S, d=d, h=h, E=E, seed=seed)
        shard = self.mesh.shape["dp"] * self.mesh.shape["ep"]
        if slots % shard:
            raise ValueError(
                f"slots={slots} must divide over dp*ep={shard} "
                f"(batch rows shard over both)")
        self.slots = slots
        self.d = int(params["w1"].shape[1])
        self.params = shard_params(params, self.mesh)
        self._infer = make_infer_step(self.mesh, capacity_factor)
        if warmup:
            self.step(np.zeros((self.slots, self.d), np.float32))

    def step(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._infer(self.params, x))


class SyntheticExecutor(Executor):
    """Deterministic jax-free replica with a dialable per-step cost.

    y = tanh(x @ W) for a fixed seeded W, after sleeping step_time_s —
    the model-cost knob that makes scheduler/backpressure tests assert
    timing properties instead of hoping the CI box is quiet."""

    def __init__(self, slots: int = 8, d: int = 16,
                 step_time_s: float = 0.0, seed: int = 0):
        self.slots = slots
        self.d = d
        self.step_time_s = step_time_s
        self._w = np.random.RandomState(seed).randn(d, d).astype(
            np.float32) / np.sqrt(d)
        self.steps = 0

    def step(self, x: np.ndarray) -> np.ndarray:
        if self.step_time_s:
            time.sleep(self.step_time_s)
        self.steps += 1
        return np.tanh(x @ self._w)


class ReplicaPool:
    """One ContinuousBatcher per executor over a shared AdmissionQueue."""

    def __init__(self, executors: Sequence[Executor], queue,
                 registry=None):
        from .scheduler import ContinuousBatcher

        if not executors:
            raise ValueError("a pool needs at least one executor")
        self.queue = queue
        self.executors = list(executors)
        self.batchers: List = [
            ContinuousBatcher(ex, queue, registry=registry,
                              replica=f"replica{i}")
            for i, ex in enumerate(self.executors)
        ]

    def start(self) -> None:
        for b in self.batchers:
            b.start()

    def stop(self) -> None:
        for b in self.batchers:
            b.stop()
        for ex in self.executors:
            ex.close()

    def active(self) -> int:
        return sum(b.active for b in self.batchers)

    def quiesce(self, timeout: float = 30.0,
                poll_s: float = 0.02) -> bool:
        """Wait until queue, pop-to-slot hand-off AND every batcher are
        empty (drain path: the queue has already stopped admitting, so
        empty is stable). inflight() covers the window where a request
        is popped but not yet in a slot — without it a drain stop()
        could land exactly there and fail an admitted request."""

        def idle() -> bool:
            return (self.queue.depth() == 0 and self.queue.inflight() == 0
                    and self.active() == 0)

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if idle():
                return True
            time.sleep(poll_s)
        return idle()
