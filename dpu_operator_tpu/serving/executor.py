"""The executor seam: what a batch slot's worth of model step IS.

The continuous-batching scheduler drives replicas through two
contracts, neither of which imports jax:

  * the synchronous seam — `step(x[slots, d]) -> y[slots, d]`, the
    PR 2 shape: the full batch round-trips host numpy every step.
  * the two-phase decode seam — `reset()` / `submit(updates) -> handle`
    / `collect(handle) -> token_ids[slots]`: slot state lives INSIDE
    the executor (on device for LocalExecutor), `submit` applies the
    step's slot updates ([(slot, row[d])] — admitted prompts and zeroed
    freed slots) and dispatches the step, `collect` blocks until the
    step's per-slot argmax token ids are available. When `pipelined`
    is True, submit returns while the step is still executing, so the
    scheduler can do retire/admit bookkeeping for neighbouring steps
    while the device runs — the overlap ISSUE 3 exists for. The base
    class adapts any step()-only executor to the two-phase contract
    (correct, eager, no overlap).

That seam is what lets replicas be swapped:

  * LocalExecutor — the in-process replica: a device-resident
    infer.DecodeStep (pipelined, the default) or infer.make_infer_step
    (mode="sync", the PR 2 loop kept as the measured baseline) on a
    jax mesh (CPU/TPU), params from train_step.init_params or a
    checkpoint. The bench and smoke tests run this one.
  * SyntheticExecutor — a jax-free replica with a CONTROLLED per-step
    cost: the scheduler/backpressure plane's test double (the
    RecordingDataplane idiom from bench.py), and the knob that makes
    overload AND overlap tests deterministic on shared CI boxes
    (pipelined=True runs steps on a worker thread — a "device" whose
    step cost is exactly step_time_s).
  * A fabric-worker-backed replica — the planned third implementation:
    `submit` ships the step's updates to a pool of
    parallel/fabric_worker.py-style processes inside operator-attached
    pod netns (same rendezvous, a forward-only program instead of the
    train slice) and `collect` reads token ids off the fabric — the
    two-phase contract is exactly the async boundary a remote replica
    needs. See docs/serving.md.

ReplicaPool owns one ContinuousBatcher per executor, all fed from one
AdmissionQueue — requests land on whichever replica frees a slot first.
"""

from __future__ import annotations

import logging
import queue as _queue
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults
from ..obs import trace as obs_trace
from .api import (DEADLINE_QUEUED_ERROR, RETRIES_EXHAUSTED_ERROR,
                  GenerateRequest)

log = logging.getLogger(__name__)

Update = Tuple[int, np.ndarray]  # (slot index, row[d]) applied at submit


class _Pending:
    """Handle for a step in flight on a synthetic executor's worker."""

    __slots__ = ("event", "tokens", "error")

    def __init__(self):
        self.event = threading.Event()
        self.tokens: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class _GuardedWorker:
    """Single-thread FIFO "device" shared by the synthetic executors
    (row plane here, token plane in kvcache/executor.py). EVERY
    failure path must land in the owning handle and the thread must
    survive — an exception escaping the loop used to kill it silently,
    so collect() on any outstanding (or future) handle blocked forever
    and the replica wedged with no error anywhere. That discipline
    (the PR 5 lesson) lives HERE, once, parameterized by the per-item
    step and reset callables."""

    def __init__(self, name: str, step_fn, reset_fn):
        self._name = name
        self._step_fn = step_fn        # payload -> tokens
        self._reset_fn = reset_fn      # () -> None
        self._work: Optional[_queue.Queue] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def started(self) -> bool:
        return self._thread is not None

    def _ensure(self) -> None:
        if self._thread is None:
            self._work = _queue.Queue()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=self._name)
            self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            pending = None
            try:
                if item[0] == "reset":
                    pending = item[1]
                    self._reset_fn()
                else:
                    _, payload, pending = item
                    pending.tokens = self._step_fn(payload)
            except BaseException as e:  # surfaced by collect()/reset()
                if pending is not None:
                    pending.error = e
                else:
                    log.exception(
                        "%s: malformed work item %r (dropped; worker "
                        "survives)", self._name, item)
            finally:
                if pending is not None:
                    pending.event.set()

    def submit(self, payload) -> _Pending:
        self._ensure()
        pending = _Pending()
        self._work.put(("step", payload, pending))
        return pending

    def reset(self) -> None:
        """Serialize behind queued steps and RE-RAISE a worker-side
        failure instead of reporting a clean session over poisoned
        state."""
        self._ensure()
        pending = _Pending()
        self._work.put(("reset", pending))
        pending.event.wait()
        if pending.error is not None:
            raise pending.error

    def close(self, timeout: float = 5.0) -> None:
        if self._thread is not None:
            self._work.put(None)
            self._thread.join(timeout=timeout)
            self._thread = None


class Executor:
    """One model replica: a fixed number of batch slots over a fixed
    feature dim. All methods are called from the replica's single
    batcher thread; they need not be reentrant."""

    slots: int
    d: int
    #: True when submit() natively dispatches asynchronously (returns
    #: while the step executes). The scheduler picks its pipelined loop
    #: off this flag; the base adapter below is eager (no overlap) but
    #: contract-correct for any step()-only executor.
    pipelined: bool = False
    #: True for paged-KV executors (serving/kvcache): the scheduler
    #: runs its token-level KV loop (attach leases, chunked prefill,
    #: NO_TOKEN-aware retire) instead of the [slots, d] row plane.
    kv: bool = False
    #: True when the executor runs the draft/verify speculative mode
    #: (ISSUE 15, KV plane only): collect() returns [slots, chunk]
    #: accepted-token RUNS instead of [slots] single tokens, and the
    #: executor presents pipelined=False — the next plan drafts from
    #: the previous step's accepted tokens, so the collect-before-
    #: plan (sync) loop shape is structural. The batcher needs no
    #: branch on this: retire normalizes both collect shapes.
    speculative: bool = False
    #: True when this replica's step spans multiple fabric shard
    #: workers (serving/sharded FabricExecutor): the pool publishes it
    #: as the `sharded` dimension on serving_pool_replicas so a
    #: dashboard separates single-host from fabric-sharded capacity.
    sharded: bool = False
    _resident: Optional[np.ndarray] = None

    def step(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- two-phase decode contract (base: eager adapter over step()) ----------

    def reset(self) -> None:
        """Zero the resident slot state (decode session start)."""
        self._resident = np.zeros((self.slots, self.d), np.float32)

    def submit(self, updates: Sequence[Update], step=None,
               request_ids=None, occupants=None):
        """Apply slot updates, dispatch one decode step; returns an
        opaque handle for collect(). Base implementation runs the step
        eagerly on the caller's thread. `step`/`request_ids` are
        diagnostic context for overflow errors (see
        DecodeStep.__call__); `occupants` is the full occupant
        request-id list, trace-only context (the sharded coordinator
        stamps it on its per-step shard.step span so worker spans
        link into each occupant's tree); the eager path has no
        fixed-shape limit and ignores them."""
        if self._resident is None:
            self.reset()
        for i, row in updates:
            self._resident[i] = row
        y = np.asarray(self.step(self._resident), np.float32)
        self._resident = y
        # One batched argmax for every slot — the per-row python loop
        # the sync scheduler used to run is measurable at step rates.
        return y.argmax(axis=1).astype(np.int32)

    def collect(self, handle) -> np.ndarray:
        """Block until the submitted step finishes; returns the [slots]
        int32 per-slot argmax token ids."""
        return handle

    def close(self) -> None:
        pass


class LocalExecutor(Executor):
    """In-process replica: forward-only train_step model on a jax mesh.

    mode="pipelined" (default) builds a device-resident infer.DecodeStep:
    slot state lives on device across steps, submit() applies admitted
    rows by on-device scatter and returns while the step executes (jax
    async dispatch), collect() materializes only the [slots] token ids
    — the full batch never round-trips PCIe. mode="sync" keeps the PR 2
    shape (make_infer_step + np.asarray per step) as the comparison
    baseline bench_serving prices the pipeline win against.

    Builds tiny demo params when none are given (the bench/test shape);
    production hands in trained params in init_params layout. XLA
    compile cost is paid in the constructor either way (AOT for the
    decode path, `warmup=True` for the sync path) so admission latency
    never includes it."""

    def __init__(self, params=None, mesh=None, slots: int = 8,
                 capacity_factor: float = 4.0, S: int = 1, d: int = 16,
                 h: int = 32, E: int = 1, seed: int = 0,
                 warmup: bool = True, mode: str = "pipelined"):
        from ..parallel.train_step import init_params, shard_params
        from .infer import make_decode_step, make_infer_step, serving_mesh

        if mode not in ("pipelined", "sync"):
            raise ValueError(f"mode must be pipelined|sync, got {mode!r}")
        self.pipelined = mode == "pipelined"
        self.mesh = mesh if mesh is not None else serving_mesh()
        if params is None:
            if E != self.mesh.shape["ep"]:
                raise ValueError(
                    f"demo params need E == ep axis size "
                    f"{self.mesh.shape['ep']}, got {E}")
            params = init_params(S=S, d=d, h=h, E=E, seed=seed)
        shard = self.mesh.shape["dp"] * self.mesh.shape["ep"]
        if slots % shard:
            raise ValueError(
                f"slots={slots} must divide over dp*ep={shard} "
                f"(batch rows shard over both)")
        self.slots = slots
        self.d = int(params["w1"].shape[1])
        self.params = shard_params(params, self.mesh)
        if self.pipelined:
            self._decode = make_decode_step(self.mesh, self.params,
                                            slots, capacity_factor)
            self._xdev = self._decode.init_state()
            if warmup:
                # One dispatched step so the first request also skips
                # any first-execution lazy initialization.
                self.collect(self.submit([]))
                self.reset()
        else:
            self._infer = make_infer_step(self.mesh, capacity_factor)
            if warmup:
                self.step(np.zeros((self.slots, self.d), np.float32))

    def step(self, x: np.ndarray) -> np.ndarray:
        if not self.pipelined:
            return np.asarray(self._infer(self.params, x))
        # Compat adapter over the resident path: load x wholesale, run
        # one step, materialize the full next state — round-trips the
        # batch like PR 2 and exists for debugging, not the hot loop.
        rows = np.asarray(x, np.float32)
        self._xdev, _tokens = self._decode(
            self._xdev, list(enumerate(rows)))
        return np.asarray(self._xdev)

    def reset(self) -> None:
        if self.pipelined:
            self._xdev = self._decode.init_state()
        else:
            super().reset()

    def submit(self, updates: Sequence[Update], step=None,
               request_ids=None, occupants=None):
        if not self.pipelined:
            return super().submit(updates)
        # Async dispatch: both returned arrays are futures; the state
        # stays on device (the previous buffer was donated into it).
        self._xdev, tokens = self._decode(self._xdev, updates,
                                          step=step,
                                          request_ids=request_ids)
        return tokens

    def collect(self, handle) -> np.ndarray:
        if not self.pipelined:
            return handle
        return np.asarray(handle)


class SyntheticExecutor(Executor):
    """Deterministic jax-free replica with a dialable per-step cost.

    y = tanh(x @ W) for a fixed seeded W, after sleeping step_time_s —
    the model-cost knob that makes scheduler/backpressure tests assert
    timing properties instead of hoping the CI box is quiet. With
    pipelined=True, steps run FIFO on a worker thread: submit returns
    immediately and collect blocks on the step's completion, so
    scheduler-overlap assertions (wall ≈ max(host, device), not the
    sum) hold by construction on shared CI boxes."""

    def __init__(self, slots: int = 8, d: int = 16,
                 step_time_s: float = 0.0, seed: int = 0,
                 pipelined: bool = False,
                 fault_site: Optional[str] = None):
        self.slots = slots
        self.d = d
        self.step_time_s = step_time_s
        self.pipelined = pipelined
        # Fault seam INSIDE the device: with pipelined=True the step
        # runs on the worker thread, where a FaultyExecutor wrapper
        # (which intercepts the submit/collect seam on the scheduler
        # thread) can't reach — naming a site here is how chaos tests
        # break the "device" itself.
        self.fault_site = fault_site
        self._w = np.random.RandomState(seed).randn(d, d).astype(
            np.float32) / np.sqrt(d)
        self.steps = 0
        # The base eager adapter IS one step of the contract (apply
        # updates, step, batched argmax); the worker only moves it off
        # the submitter's thread.
        self._worker = _GuardedWorker(
            "synthetic-step",
            step_fn=lambda updates: Executor.submit(self, updates),
            reset_fn=self._zero_resident)

    def _zero_resident(self) -> None:
        self._resident = np.zeros((self.slots, self.d), np.float32)

    def step(self, x: np.ndarray) -> np.ndarray:
        if self.fault_site is not None:
            faults.fire(f"{self.fault_site}.step")
        if self.step_time_s:
            time.sleep(self.step_time_s)
        self.steps += 1
        return np.tanh(x @ self._w)

    # -- pipelined: the worker thread is the "device" -------------------------

    def reset(self) -> None:
        if not self.pipelined or not self._worker.started:
            super().reset()
            return
        # The worker owns the resident state between submit and
        # collect; a reset must serialize behind queued steps.
        self._worker.reset()

    def submit(self, updates: Sequence[Update], step=None,
               request_ids=None, occupants=None):
        if not self.pipelined:
            return super().submit(updates)
        if self._resident is None:
            self._resident = np.zeros((self.slots, self.d), np.float32)
        return self._worker.submit(list(updates))

    def collect(self, handle) -> np.ndarray:
        if not self.pipelined:
            return handle
        handle.event.wait()
        if handle.error is not None:
            raise handle.error
        return handle.tokens

    def close(self) -> None:
        self._worker.close()


REPLICA_LIVE = "live"
REPLICA_BACKOFF = "backoff"
REPLICA_PARKED = "parked"


class ReplicaPool:
    """One ContinuousBatcher per executor over a shared AdmissionQueue
    — and, when `supervise` (the default), the SUPERVISOR that keeps
    them converged on "every replica live":

      * detection — a monitor thread polls every `poll_s` for replica
        DEATH (batcher thread exited with a recorded failure) and
        WEDGE (the batcher has been blocked on the device — step() or
        collect() — longer than `watchdog_s`; a hung device step can
        never time itself out, so the deadline lives out here);
      * requeue — the dead replica's in-flight requests are seized
        (under the batcher's settle lock: no double-settle) and
        re-admitted at the FRONT of the shared queue with a
        per-request attempts budget — past `max_attempts` replica
        failures a request 500s with RETRIES_EXHAUSTED_ERROR; a
        request whose deadline lapsed mid-failure settles exactly once
        (truncated 200 if it already has tokens, 503 deadline-shed
        otherwise) and never re-enters the queue;
      * restart — a fresh ContinuousBatcher over the same executor
        (which `reset()`s at loop start) under exponential backoff +
        jitter (SRE retry discipline: backoff bounds the flap rate,
        jitter de-synchronizes a fleet of restarts);
      * circuit breaker — `breaker_threshold` failures inside
        `breaker_window_s` PARK the replica: no more restarts, the
        pool serves degraded, and the operator sees
        serving_breaker_state=1 instead of an infinite crash loop.

    `watchdog_s` bounds the time a batcher may sit blocked on the
    device (step/collect/reset); executors must therefore pay their
    compile cost in the CONSTRUCTOR (the LocalExecutor contract since
    PR 2 — warmup=True) or hand the pool a watchdog_s above their
    worst first step, or a cold compile reads as a wedge.

    Readiness contract consumed by the HTTP front-end: live replicas <
    `quorum` (default: all of them) → /readyz 503 "degraded"; zero
    live replicas → /healthz goes red too. Recovery metrics:
    serving_replica_restarts_total, serving_requeue_total{outcome},
    serving_breaker_state, serving_pool_replicas{state}."""

    def __init__(self, executors: Sequence[Executor], queue,
                 registry=None, *, supervise: bool = True,
                 watchdog_s: float = 5.0, max_attempts: int = 3,
                 restart_backoff_s: float = 0.05,
                 restart_backoff_cap_s: float = 2.0,
                 breaker_window_s: float = 30.0,
                 breaker_threshold: int = 5,
                 quorum: Optional[int] = None,
                 poll_s: float = 0.02, seed: int = 0,
                 tracer=None, flight_recorder=None,
                 role: str = "unified",
                 name_prefix: str = "replica",
                 batcher_kwargs: Optional[dict] = None):
        from .scheduler import ContinuousBatcher

        if not executors:
            raise ValueError("a pool needs at least one executor")
        # Role-typed pools (serving/disagg): `role` is the
        # serving_pool_replicas label (prefill|decode|unified) and
        # `name_prefix` namespaces replica names so a prefill pool's
        # replica0 and a decode pool's replica0 never collide in
        # per-replica series. `batcher_kwargs` rides every batcher
        # construction INCLUDING supervisor restarts — a restarted
        # prefill replica must keep its handoff hook.
        self.role = str(role)
        self.name_prefix = str(name_prefix)
        self.batcher_kwargs = dict(batcher_kwargs or {})
        self.queue = queue
        self.registry = registry
        if registry is not None:
            # Executors that keep their own step-internal series (the
            # FabricExecutor's shard collective/skew histograms) adopt
            # the pool's registry so a ServingServer-built pool
            # exposes them on /metrics with no extra wiring.
            for ex in executors:
                bind = getattr(ex, "bind_registry", None)
                if bind is not None:
                    bind(registry)
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        # Armed by the serving front-end (obs.FlightRecorder): the
        # supervisor snapshots the trace ring on wedge/death/breaker —
        # the moment the evidence exists, not when someone reproduces.
        self.flight_recorder = flight_recorder
        self.executors = list(executors)
        self.supervised = bool(supervise)
        self.watchdog_s = watchdog_s
        self.max_attempts = max_attempts
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_cap_s = restart_backoff_cap_s
        self.breaker_window_s = breaker_window_s
        self.breaker_threshold = breaker_threshold
        self.quorum = (len(self.executors) if quorum is None
                       else max(1, int(quorum)))
        self.poll_s = poll_s
        self._rng = random.Random(seed)
        self._Batcher = ContinuousBatcher
        # _plock guards the state arrays and batcher swaps (monitor
        # thread vs readers like live_count); the per-batcher settle
        # lock guards request ownership.
        self._plock = threading.Lock()
        # Replica names are STABLE across attach/detach splices (the
        # autoscaler's role flips): index-derived names would rename
        # every later replica's metric series on each flip.
        self._names: List[str] = [f"{self.name_prefix}{i}"
                                  for i in range(len(self.executors))]
        self._name_seq = len(self.executors)
        self.batchers: List = [
            self._make_batcher(i, ex)
            for i, ex in enumerate(self.executors)
        ]
        n = len(self.executors)
        self._state = [REPLICA_LIVE] * n
        self._restart_at: List[Optional[float]] = [None] * n
        self._fail_times: List[deque] = [deque() for _ in range(n)]
        # Nonzero while a seize→requeue hand-off is in flight: in that
        # window the seized requests are in no batcher's slots and not
        # yet back in the queue, and quiesce() must not read the pool
        # as drained around them.
        self._seizing = 0
        self.restarts = [0] * n
        self._sup_stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None

    def _rname(self, i: int) -> str:
        if i < len(self._names):
            return self._names[i]
        return f"{self.name_prefix}{i}"

    def _make_batcher(self, i: int, ex: Executor):
        return self._Batcher(ex, self.queue, registry=self.registry,
                             replica=self._rname(i),
                             crash_only=self.supervised,
                             tracer=self.tracer,
                             **self.batcher_kwargs)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        for b in self.batchers:
            b.start()
        if self.supervised:
            self._publish_state()
            self._sup_thread = threading.Thread(
                target=self._supervise, daemon=True,
                name="replica-supervisor")
            self._sup_thread.start()

    def stop(self) -> None:
        # Supervisor first: a replica dying DURING teardown must not be
        # requeued into a queue the server is about to fail_all().
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=5)
        for b in self.batchers:
            b.stop()
        for ex in self.executors:
            ex.close()

    def active(self) -> int:
        return sum(b.active for b in self.batchers)

    # -- observability --------------------------------------------------------

    def live_count(self) -> int:
        """Replicas currently serving. Supervised: state LIVE (the
        monitor flips it within ~poll_s of a death/wedge).
        Unsupervised: batcher threads actually running."""
        with self._plock:
            if self.supervised:
                return sum(1 for s in self._state if s == REPLICA_LIVE)
            return sum(1 for b in self.batchers if b.thread_alive)

    def states(self) -> Dict[str, str]:
        with self._plock:
            return {self._rname(i): s
                    for i, s in enumerate(self._state)}

    def all_parked(self) -> bool:
        """True when every replica's breaker is open — no restart will
        ever be scheduled again, so the pool is dead, not degraded."""
        with self._plock:
            return all(s == REPLICA_PARKED for s in self._state)

    def _publish_state(self) -> None:
        if self.registry is None:
            return
        with self._plock:
            shard_dim = ["true" if getattr(ex, "sharded", False)
                         else "false" for ex in self.executors]
            counts = {(st, sh): 0.0
                      for st in (REPLICA_LIVE, REPLICA_BACKOFF,
                                 REPLICA_PARKED)
                      for sh in ("true", "false")}
            for i, s in enumerate(self._state):
                counts[(s, shard_dim[i])] += 1
        for (st, sh), n in counts.items():
            self.registry.gauge_set(
                "serving_pool_replicas", float(n),
                {"state": st, "sharded": sh, "role": self.role},
                help="replicas by supervision state, fabric-sharding, "
                     "and serving role (prefill|decode|unified)")

    def _count(self, name: str, labels: dict, help: str = "") -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, labels, help=help)

    # -- the supervisor -------------------------------------------------------

    def _supervise(self) -> None:
        while not self._sup_stop.is_set():
            now = time.monotonic()
            for i in range(len(self.executors)):
                # Per-replica guard: the monitor IS the self-healing
                # plane — one throw here (thread exhaustion during a
                # fault storm, a poisoned executor attribute) must cost
                # at most this replica this cycle, never the thread.
                try:
                    with self._plock:
                        if i >= len(self.batchers):
                            # A detach_replica spliced the arrays
                            # mid-cycle; the next cycle re-ranges.
                            break
                        st = self._state[i]
                        b = self.batchers[i]
                        restart_at = self._restart_at[i]
                    if st == REPLICA_LIVE:
                        bs = b.blocked_since
                        wedged = (bs is not None
                                  and now - bs > self.watchdog_s)
                        dead = (not b.thread_alive and not b.stopping
                                and b._thread is not None)
                        if dead or wedged:
                            self._replica_down(
                                i, b, "wedged" if wedged else "died")
                    elif st == REPLICA_BACKOFF and restart_at is not None \
                            and now >= restart_at:
                        self._restart(i)
                except Exception:
                    log.exception("supervisor: %s cycle failed",
                                  self._rname(i))
            self._sup_stop.wait(self.poll_s)

    def _replica_down(self, i: int, batcher, why: str) -> None:
        err = batcher.failure
        self.tracer.event(
            "supervisor.detect",
            attrs={"replica": self._rname(i), "why": why,
                   "error": str(err)[:200] if err else None})
        # _seizing flips BEFORE seize(): at no instant is a seized
        # request in none of {batcher slots, this hand-off, the queue}
        # — the same closed-accounting contract the queue's inflight
        # counter keeps for pop→place (quiesce checks all three).
        with self._plock:
            self._seizing += 1
        try:
            t0 = time.monotonic()
            seized = batcher.seize()
            rids = [r.request_id for r in seized]
            self.tracer.record_span(
                "supervisor.seize", t0, time.monotonic(),
                attrs={"replica": self._rname(i), "why": why,
                       "request_ids": rids})
            self.tracer.decision("seize", replica=self._rname(i),
                                 why=why, request_ids=rids)
            log.warning("%s %s (%s); requeueing %d in-flight "
                        "request(s): %s", self._rname(i), why, err,
                        len(seized),
                        rids)
            self._requeue(i, seized)
        finally:
            with self._plock:
                self._seizing -= 1
        self._record_failure(i)
        self._flight_snapshot(why, replica=i)

    def _record_failure(self, i: int) -> None:
        """Window bookkeeping shared by the death/wedge path and a
        failed restart: park past the breaker threshold, otherwise
        schedule the next restart under exponential backoff + jitter."""
        now = time.monotonic()
        window = self._fail_times[i]
        window.append(now)
        while window and window[0] < now - self.breaker_window_s:
            window.popleft()
        if len(window) >= self.breaker_threshold:
            with self._plock:
                self._state[i] = REPLICA_PARKED
                self._restart_at[i] = None
            if self.registry is not None:
                self.registry.gauge_set(
                    "serving_breaker_state", 1.0,
                    {"replica": self._rname(i)},
                    help="1 when the replica's restart breaker is "
                         "open (replica parked)")
            self.tracer.event(
                "supervisor.breaker_open",
                attrs={"replica": self._rname(i),
                       "failures_in_window": len(window),
                       "window_s": self.breaker_window_s})
            self.tracer.decision("breaker_open",
                                 replica=self._rname(i))
            log.error("%s: breaker OPEN (%d failures in %.0fs) "
                      "— parked, pool degraded", self._rname(i),
                      len(window), self.breaker_window_s)
            # Publish BEFORE the flight snapshot: the snapshot is
            # disk I/O that can take >100 ms on a loaded box, and a
            # scraper reading serving_pool_replicas inside that
            # window must not see the replica parked in states() but
            # not in the gauge (observed as a full-suite flake).
            self._publish_state()
            self._flight_snapshot("breaker_open", replica=i)
        else:
            delay = min(self.restart_backoff_cap_s,
                        self.restart_backoff_s
                        * (2 ** (len(window) - 1)))
            delay *= 1.0 + 0.25 * self._rng.random()  # de-sync restarts
            with self._plock:
                self._state[i] = REPLICA_BACKOFF
                self._restart_at[i] = now + delay
        self._publish_state()

    def _requeue(self, i: int, reqs: List[GenerateRequest]) -> None:
        now = time.monotonic()
        replica = self._rname(i)
        for req in reqs:
            if req.done:
                # Settled before (or while) the replica fell over —
                # nothing to do, and settling again is the double-
                # settle this path exists to prevent.
                outcome = "already_done"
            elif req.deadline <= now:
                # Deadline lapsed mid-failure: settle ONCE, never
                # re-enter the queue (the pop-side shed would settle it
                # a second time). With tokens already decoded this is
                # the mid-decode truncation contract; with none it is
                # the queued-deadline shed.
                if req.tokens:
                    req.truncated = True
                    req.finish()
                    outcome = "deadline_truncated"
                else:
                    req.fail(DEADLINE_QUEUED_ERROR)
                    outcome = "deadline_lapsed"
            else:
                req.attempts += 1
                if req.attempts >= self.max_attempts:
                    req.fail(RETRIES_EXHAUSTED_ERROR)
                    outcome = "retries_exhausted"
                else:
                    lease = getattr(req, "kv_lease", None)
                    if lease is not None and lease.resumable:
                        # Paged-KV retry (ISSUE 7): the lease — the
                        # request's block-table ownership — rides the
                        # queue with it, so the restarted replica
                        # RE-ATTACHES the surviving pages and resumes
                        # from the last settled token. Tokens are
                        # KEPT: the deterministic recurrence makes the
                        # resumed stream identical to an unfailed
                        # run's, at a replay cost of in-flight steps
                        # instead of prompt-length re-decode.
                        outcome = "requeued_kv"
                    else:
                        # Fresh decode from the prompt: the recurrence
                        # is deterministic, so the retried stream is
                        # identical to an unfailed run's —
                        # half-decoded state must not leak into the
                        # retry.
                        req.tokens.clear()
                        req.truncated = False
                        outcome = "requeued"
                    self.queue.requeue(req)
            self._count("serving_requeue_total",
                        {"replica": replica, "outcome": outcome},
                        help="in-flight requests seized from failed "
                             "replicas, by disposition")
            # Parented to the request's root span: the recovery chain
            # (seize → requeue → re-decode) shows up in ITS trace, not
            # only in replica-level series.
            self.tracer.event(
                "supervisor.requeue", request_id=req.request_id,
                parent_id=req.trace_parent,
                attrs={"replica": replica, "outcome": outcome,
                       "attempts": req.attempts})
            self.tracer.decision("requeue", request_id=req.request_id,
                                 replica=replica, outcome=outcome)

    # -- autoscaler surface (ISSUE 20) ----------------------------------------

    def _requeue_policy(self, name: str, reqs: List[GenerateRequest],
                        why: str) -> None:
        """Requeue requests displaced by POLICY (role flip, park-to-
        zero) rather than failure. Same exactly-once dispositions as
        the supervisor's `_requeue`, with one deliberate difference:
        `attempts` is NOT burned — the replica did nothing wrong and
        neither did the request, so a flip must never push a request
        toward RETRIES_EXHAUSTED_ERROR."""
        now = time.monotonic()
        for req in reqs:
            if req.done:
                outcome = "already_done"
            elif req.deadline <= now:
                if req.tokens:
                    req.truncated = True
                    req.finish()
                    outcome = "deadline_truncated"
                else:
                    req.fail(DEADLINE_QUEUED_ERROR)
                    outcome = "deadline_lapsed"
            else:
                lease = getattr(req, "kv_lease", None)
                if lease is not None and lease.resumable:
                    # The executor object survives the flip, so the
                    # lease's pages do too: tokens are KEPT and the
                    # next attach either resumes (same executor) or
                    # releases-and-reprefills (foreign) — byte-
                    # identical either way.
                    outcome = f"{why}_kv"
                else:
                    req.tokens.clear()
                    req.truncated = False
                    outcome = why
                self.queue.requeue(req)
            self._count("serving_requeue_total",
                        {"replica": name, "outcome": outcome},
                        help="in-flight requests seized from failed "
                             "replicas, by disposition")
            self.tracer.event(
                "supervisor.requeue", request_id=req.request_id,
                parent_id=req.trace_parent,
                attrs={"replica": name, "outcome": outcome,
                       "attempts": req.attempts})

    def detach_replica(self, min_live: int = 1):
        """Remove one LIVE replica from the pool (the autoscaler's
        role-flip donor side). Seizes the batcher under its settle
        lock, requeues its in-flight occupants exactly once WITHOUT
        burning `attempts`, splices every parallel array, and returns
        the executor — still warm, pages intact — for
        `attach_replica` on the destination pool. Returns None rather
        than dropping the pool below `min_live` live replicas."""
        with self._plock:
            live = [j for j, s in enumerate(self._state)
                    if s == REPLICA_LIVE]
            if len(live) <= max(1, int(min_live)):
                return None
            i = live[-1]
            b = self.batchers[i]
            name = self._rname(i)
            self._seizing += 1
        try:
            seized = b.seize()
            b.stop(timeout=5.0)  # slots already empty: fails nothing
            self._requeue_policy(name, seized, "requeued_flip")
            with self._plock:
                ex = self.executors[i]
                for arr in (self.executors, self.batchers, self._state,
                            self._restart_at, self._fail_times,
                            self.restarts, self._names):
                    del arr[i]
                # A shrunk pool must not read as permanently degraded.
                self.quorum = max(1, min(self.quorum,
                                         len(self.executors)))
        finally:
            with self._plock:
                self._seizing -= 1
        self.tracer.event("pool.detach_replica",
                          attrs={"role": self.role, "replica": name,
                                 "seized": len(seized)})
        self._publish_state()
        return ex

    def attach_replica(self, ex: Executor) -> str:
        """Adopt an executor (the role-flip recipient side): build a
        batcher with THIS pool's `batcher_kwargs` — that is what makes
        the replica's new role real (a prefill pool's kwargs carry the
        handoff hook; a decode pool's do not) — and start serving from
        this pool's queue. Returns the replica's stable name."""
        if self.registry is not None:
            bind = getattr(ex, "bind_registry", None)
            if bind is not None:
                bind(self.registry)
        with self._plock:
            self.executors.append(ex)
            i = len(self.executors) - 1
            name = f"{self.name_prefix}{self._name_seq}"
            self._name_seq += 1
            self._names.append(name)
            b = self._make_batcher(i, ex)
            self.batchers.append(b)
            self._state.append(REPLICA_LIVE)
            self._restart_at.append(None)
            self._fail_times.append(deque())
            self.restarts.append(0)
        b.start()
        self.tracer.event("pool.attach_replica",
                          attrs={"role": self.role, "replica": name})
        self._publish_state()
        return name

    def park_replica(self, i: Optional[int] = None,
                     min_live: int = 0) -> Optional[str]:
        """Scale-to-zero: stop a LIVE replica and PARK it — the same
        terminal state the restart breaker uses, so the supervisor
        leaves it alone and states()/serving_pool_replicas read it as
        parked capacity. In-flight occupants requeue exactly once via
        the policy path (no `attempts` burn). Returns the replica
        name, or None when parking would drop live below
        `min_live` (or nothing is live)."""
        with self._plock:
            live = [j for j, s in enumerate(self._state)
                    if s == REPLICA_LIVE]
            if not live or len(live) - 1 < max(0, int(min_live)):
                return None
            if i is None:
                i = live[-1]
            elif self._state[i] != REPLICA_LIVE:
                return None
            b = self.batchers[i]
            name = self._rname(i)
            # State flips BEFORE the seize so the monitor never reads
            # the stopping batcher as a death to requeue+restart.
            self._state[i] = REPLICA_PARKED
            self._restart_at[i] = None
            self._seizing += 1
        try:
            seized = b.seize()
            b.stop(timeout=5.0)
            self._requeue_policy(name, seized, "requeued_park")
        finally:
            with self._plock:
                self._seizing -= 1
        self.tracer.event("pool.park_replica",
                          attrs={"role": self.role, "replica": name,
                                 "seized": len(seized)})
        self._publish_state()
        return name

    def unpark_replica(self, i: Optional[int] = None) -> Optional[str]:
        """Wake a PARKED replica (scale-from-zero). Builds a fresh
        batcher over the same executor — distinct from `_restart` so
        autoscale wakes never count as failure-recovery restarts and
        never touch the breaker window."""
        with self._plock:
            parked = [j for j, s in enumerate(self._state)
                      if s == REPLICA_PARKED]
            if i is None:
                if not parked:
                    return None
                i = parked[0]
            elif self._state[i] != REPLICA_PARKED:
                return None
            ex = self.executors[i]
            name = self._rname(i)
        try:
            b = self._make_batcher(i, ex)
        except Exception:
            log.exception("%s: unpark construction failed", name)
            return None
        with self._plock:
            if self._state[i] != REPLICA_PARKED:
                return None  # raced a concurrent unpark
            self.batchers[i] = b
            self._state[i] = REPLICA_LIVE
            self._restart_at[i] = None
            # Fresh start, fresh breaker window: the park that put it
            # here may have been policy, and even a breaker park's
            # stale failures should not instantly re-park the wake.
            self._fail_times[i].clear()
        b.start()
        if self.registry is not None:
            self.registry.gauge_set(
                "serving_breaker_state", 0.0, {"replica": name},
                help="1 when the replica's restart breaker is "
                     "open (replica parked)")
        self.tracer.event("pool.unpark_replica",
                          attrs={"role": self.role, "replica": name})
        self._publish_state()
        return name

    def _restart(self, i: int) -> None:
        ex = self.executors[i]
        t0 = time.monotonic()
        try:
            b = self._make_batcher(i, ex)
        except Exception:
            # Construction failure counts as another replica failure:
            # same window bookkeeping, so backoff escalates and the
            # breaker eventually parks a replica that cannot even be
            # rebuilt. (Executor-level failures surface later, in the
            # new batcher thread's reset/step, and come back through
            # the normal death path.)
            log.exception("%s: restart construction failed",
                          self._rname(i))
            self._record_failure(i)
            return
        with self._plock:
            self.batchers[i] = b
            # restarts increments under the same lock and BEFORE the
            # state flips LIVE: an observer seeing the pool at full
            # strength must also see every restart that got it there.
            self.restarts[i] += 1
            self._state[i] = REPLICA_LIVE
            self._restart_at[i] = None
        b.start()
        self._count("serving_replica_restarts_total",
                    {"replica": self._rname(i)},
                    help="supervisor-initiated replica restarts")
        self.tracer.record_span(
            "supervisor.restart", t0, time.monotonic(),
            attrs={"replica": self._rname(i),
                   "restarts": self.restarts[i]})
        self.tracer.decision("restart", replica=self._rname(i))
        self._publish_state()
        log.info("%s: restarted (attempt %d)", self._rname(i),
                 self.restarts[i])
        # The recovery snapshot: by restart time the ring holds the
        # WHOLE chain (fault → detect → seize → requeue → restart) —
        # the wedge-time snapshot necessarily ends at the seize.
        self._flight_snapshot("restart", replica=i)

    def _flight_snapshot(self, reason: str, replica: int) -> None:
        rec = self.flight_recorder
        if rec is None:
            return
        try:
            rec.snapshot(reason,
                         extra={"replica": self._rname(replica),
                                "states": self.states()})
        except Exception:
            # The recorder is evidence, not a dependency: a snapshot
            # failure must never take down the healing plane.
            log.exception("flight recorder snapshot (%s) failed",
                          reason)

    def quiesce(self, timeout: float = 30.0,
                poll_s: float = 0.02) -> bool:
        """Wait until queue, pop-to-slot hand-off, supervisor
        seize-to-requeue hand-off AND every batcher are empty (drain
        path: the queue has already stopped admitting, so empty is
        stable). inflight() covers the window where a request is
        popped but not yet in a slot; _seizing covers the one where a
        failed replica's requests are seized but not yet re-admitted —
        without either, a drain stop() could land exactly there and
        fail an admitted request."""

        def idle() -> bool:
            with self._plock:
                seizing = self._seizing
            return (seizing == 0 and self.queue.depth() == 0
                    and self.queue.inflight() == 0
                    and self.active() == 0)

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if idle():
                return True
            time.sleep(poll_s)
        return idle()
