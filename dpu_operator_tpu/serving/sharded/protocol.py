"""Coordinator↔shard control protocol: framed JSON + raw payload.

One frame = ``!II`` header (json length, payload length) + UTF-8 JSON
object + optional raw array bytes. The control plane is deliberately
tiny (reset/step/tokens/close); the BULK bytes of a sharded step are
the collective's, and those ride parallel/fabric_collectives between
the shards directly — the coordinator only ever moves scatter updates
in and token ids out.

Cross-process tracing (ISSUE 11) rides these SAME frames — the JSON
object is free-form, so every field below is ignored by a worker (or
coordinator) that predates it, and none adds a round trip:

  * step msg → worker: ``trace_parent`` — the coordinator's reserved
    ``shard.step`` span id; the worker's ``shard.compute`` parents on
    it (as ``attrs["xparent"]`` — coordinator ids must never ride a
    worker span's local ``parent_id``, the id spaces collide).
  * every reply ← worker: ``t_rx``/``t_tx`` — the worker's monotonic
    receive/reply stamps, completing the NTP four-timestamp exchange
    the coordinator's ClockSync estimates clock offsets from.
  * tokens reply ← worker: ``spans`` (obs.xproc wire lists from the
    bounded SpanShip buffer), ``spans_dropped`` (its cumulative
    overflow counter), and — every ``--metrics-interval`` steps —
    ``metrics`` (a Registry.federated_snapshot() the coordinator
    re-exports rank/codec-labelled).

Every receive here takes a mandatory ``timeout`` and arms it on the
socket before reading (the GL010 discipline: a dead or wedged peer
surfaces as ``socket.timeout``/``ProtocolError`` in bounded time,
never an unbounded block the watchdog cannot attribute)."""

from __future__ import annotations

import json
import socket
import struct
import time
from typing import Optional, Tuple

_HDR = struct.Struct("!II")
_MAX_JSON = 1 << 20
_MAX_PAYLOAD = 1 << 28


class ProtocolError(RuntimeError):
    """Framing violation or peer gone mid-frame."""


def send_msg(sock: socket.socket, obj: dict, *parts) -> None:
    """One frame. ``parts`` are bytes-like payload pieces — bytes, a
    memoryview, or any buffer-protocol object (a contiguous numpy
    array passes as-is). Each part is written straight from its own
    memory, never concatenated into a fresh buffer: the bulk payload
    of a step/tokens frame must not pay a ``tobytes()`` copy in the
    per-step hot loop (the GL011 contract). Callers that interleave
    small frames on a long-lived control socket arm TCP_NODELAY at
    connect so the header write and a small payload part never sit
    out a Nagle/delayed-ACK exchange."""
    views = []
    total = 0
    for p in parts:
        v = p if isinstance(p, memoryview) else memoryview(p)
        if v.nbytes == 0:
            continue  # empty parts frame as zero bytes (cast chokes)
        if v.format != "B":
            v = v.cast("B")
        views.append(v)
        total += len(v)
    body = json.dumps(obj).encode()
    sock.sendall(_HDR.pack(len(body), total) + body)
    for v in views:
        if len(v):
            sock.sendall(v)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float]) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    while len(view):
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("frame deadline expired mid-read")
            sock.settimeout(remaining)
        got = sock.recv_into(view)
        if got == 0:
            raise ProtocolError("peer closed mid-frame")
        view = view[got:]
    return bytes(buf)


def recv_msg(sock: socket.socket,
             timeout: Optional[float]) -> Tuple[dict, bytes]:
    """One frame, or raise inside `timeout` seconds (socket.timeout on
    silence, ProtocolError on a torn frame). The timeout is a deadline
    over the WHOLE frame, re-armed before every recv — a sick peer
    dripping one byte per near-timeout interval cannot stretch one
    receive to timeout x bytes. `timeout=None` is an explicit caller
    decision, not a default."""
    deadline = (None if timeout is None
                else time.monotonic() + timeout)
    if timeout is None:
        sock.settimeout(None)
    hdr = _recv_exact(sock, _HDR.size, deadline)
    jlen, plen = _HDR.unpack(hdr)
    if jlen > _MAX_JSON or plen > _MAX_PAYLOAD:
        raise ProtocolError(f"oversized frame (json={jlen} "
                            f"payload={plen})")
    obj = json.loads(_recv_exact(sock, jlen, deadline).decode())
    payload = _recv_exact(sock, plen, deadline) if plen else b""
    return obj, payload
