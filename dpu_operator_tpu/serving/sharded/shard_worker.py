"""One shard worker of a fabric-sharded serving replica.

The forward-only sibling of parallel/fabric_worker.py: where that
program proves the fabric with a training slice, this one IS the
serving dataplane — it holds rank r's tensor-parallel slice of the
decode params (shard_math.TpShardSlice, or the seeded double) plus a
replica of the [slots, d] decode state, and runs the per-step tp
collective through parallel/fabric_collectives.RingTransport over the
fabric addresses the coordinator wired into a ring (ring order chosen
by parallel/topology.ring_order — every participant derives the SAME
ring from the same address set).

Control plane: the worker dials the coordinator, says hello, then
serves framed step/reset messages (protocol.py). Per step it applies
the scatter updates, computes its stage partials (jitted via jax when
``--jit`` and jax imports; numpy otherwise — same shard_math either
way), allreduces each stage over the ring, and replies with its OWNED
token segment plus compute/collective timings (the coordinator's
skew/collective metrics) as zero-copy buffer parts.

ISSUE 9 knobs: ``--codec int8|bf16`` runs the ring collective
quantized (every ring member must agree — the hello handshake
refuses a mixed ring typed); ``--overlap`` restructures each stage
through shard_math.forward_overlapped — block reduces run on a
dedicated collective thread in (stage, block) order (identical on
every rank, so the sequential ring allreduces pair up) while this
thread computes the next block's partial, and the reported
collective_s becomes the time compute actually BLOCKED (the
non-hidden remainder).

Protocol: prints exactly ONE JSON object on stdout at exit
(fabric_worker.protocol_stdout guards the stream — all logging and
diagnostics go to stderr); rc 0 iff the session ended cleanly.
"""

from __future__ import annotations

import argparse
import json
import logging
import select
import socket
import sys
import time

import numpy as np

from ...obs import logging as obs_logging
from ...obs import trace as obs_trace
from ...obs.xproc import SpanShip
from ...parallel.fabric_collectives import RingError, RingTransport
from ...parallel.fabric_worker import protocol_stdout
from ...utils.metrics import Registry
from .protocol import ProtocolError, recv_msg, send_msg
from .shard_math import (DoubleShardSlice, TpShardSlice,
                         segment_bounds)
from .synthetic import GuardedReducer

log = logging.getLogger("shard_worker")

# Worker-local step-scale histogram bounds (the coordinator re-exports
# these series verbatim, so they must match the serving plane's
# decode-step resolution).
_WORKER_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 1.0)


def _ring_reducer(ring) -> GuardedReducer:
    """The worker's collective thread (overlap mode): block reduces
    queue in (stage, block) order — identical on every rank, so the
    sequential ring allreduces pair up — while the compute thread
    runs the NEXT block's partial. One GuardedReducer (shared with
    the synthetic shard plane: every failure lands in the owning
    ticket) over a ring-allreduce fn with per-size scratch reuse; the
    OUT buffer stays fresh each call — it escapes through the ticket
    and the compute thread may not have consumed block b when block
    b+1 reduces."""
    scratch = {}

    def reduce_fn(part):
        if ring is None:
            return part
        s = scratch.get(part.size)
        if s is None:
            s = scratch[part.size] = np.empty(part.size, np.float32)
        return ring.allreduce(part, scratch=s)

    return GuardedReducer(reduce_fn, name="ring-reducer")


def _load_slice(args):
    if args.params_npz:
        with np.load(args.params_npz) as z:
            params = {k: z[k] for k in z.files}
        return TpShardSlice(params, args.rank, args.world)
    return DoubleShardSlice(args.d, args.seed, args.rank, args.world)


def _maybe_jit(sl, want_jit: bool, slots: int):
    """(partial_fn, finish_fn, jitted?) — jax.jit over the SAME
    shard_math methods when requested and importable (the numpy
    params bind as executable constants); numpy fallback otherwise so
    the worker runs in images without jax."""
    if not want_jit:
        return None, None, False
    try:
        import jax
        import jax.numpy as jnp

        jax.config.update("jax_platforms", "cpu")
        # The stage math must TRACE: swap the slice's array module to
        # jax.numpy before jitting (numpy ufuncs over tracers raise).
        sl.xp = jnp
        partial = jax.jit(sl.partial, static_argnums=1)
        finish = jax.jit(sl.finish, static_argnums=2)
        # Compile EVERY stage up front (the stage index is a static
        # arg — each value is its own executable): step latency never
        # includes XLA (the LocalExecutor constructor contract).
        x0 = np.zeros((slots, sl.d), np.float32)
        for s in range(sl.stages):
            d0 = np.asarray(partial(x0, s))
            np.asarray(finish(x0, d0, s))
        # finish's output becomes the next decode state, which the
        # step loop SCATTERS updates into — np.asarray over a jax
        # array is a read-only view, so copy to a writable buffer
        # ([slots, d]: negligible next to the collective).
        return ((lambda x, s: np.asarray(partial(x, s))),
                (lambda x, dense, s: np.array(finish(x, dense, s),
                                              np.float32)),
                True)
    except Exception as e:  # fall back loudly, not silently
        sl.xp = np  # the numpy path must not trip over a half-swap
        log.warning("jit unavailable (%r); numpy math", e)
        return None, None, False


def _kv_main(argv) -> int:
    """``--kv`` mode (ISSUE 16): this process serves ONE rank's slice
    of a context-parallel paged KV pool instead of a row-state shard —
    it dials the coordinator's per-rank listener, rebuilds the shared
    ``KVSpec`` from ``--kv-spec`` and derives its OWN head/block slice
    bounds from it (the GL018 discipline holds across the process
    boundary), then serves framed step/reset messages until the
    coordinator closes the stream. Same one-JSON-line stdout protocol
    as the row worker."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--kv", action="store_true")
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--connect", required=True,
                    help="ip:port of the KVShardProcessSet's per-rank "
                         "listener")
    ap.add_argument("--slots", type=int, required=True)
    ap.add_argument("--num-blocks", type=int, required=True)
    ap.add_argument("--chunk", type=int, required=True)
    ap.add_argument("--kv-spec", required=True,
                    help="k=v CSV of KVSpec.fingerprint() — the ONE "
                         "layout declaration both ends derive from")
    args = ap.parse_args(argv)
    proto_out = protocol_stdout()
    obs_logging.setup("shard_worker", stream=sys.stderr)
    with obs_logging.context(rank=args.rank):
        from ..kvcache.sharded import serve_kv_rank, spec_from_argv

        spec = spec_from_argv(args.kv_spec)
        host, port = args.connect.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=30.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        rc, err = 0, None
        try:
            serve_kv_rank(sock, args.rank, spec, slots=args.slots,
                          num_blocks=args.num_blocks,
                          chunk=args.chunk)
        except (OSError, ProtocolError) as e:
            # A dead coordinator closes the socket: bounded, loud.
            rc, err = 1, str(e)
            log.warning("kv rank %d: coordinator stream died: %s",
                        args.rank, e)
        finally:
            sock.close()
        print(json.dumps({"ok": rc == 0, "mode": "kv",
                          "rank": args.rank, "error": err}),
              file=proto_out, flush=True)
    return rc


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--kv" in argv:
        return _kv_main(argv)
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, required=True,
                    help="ring rank (the coordinator applies "
                         "topology.ring_order before spawning)")
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--slots", type=int, required=True)
    ap.add_argument("--d", type=int, default=16)
    ap.add_argument("--coordinator", required=True,
                    help="ip:port of the FabricExecutor's control "
                         "listener")
    ap.add_argument("--bind-ip", default="127.0.0.1",
                    help="this shard's fabric address (ring listener)")
    ap.add_argument("--peers", required=True,
                    help="comma-separated ip:port ring addresses of "
                         "ALL shards, indexed by ring rank")
    ap.add_argument("--params-npz", default="",
                    help="train_step params (E=1) for the real model "
                         "slice; empty = the seeded double")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--jit", action="store_true",
                    help="jit the local stage math via jax (numpy "
                         "fallback when jax is unavailable)")
    ap.add_argument("--codec", choices=["fp32", "bf16", "int8"],
                    default="fp32",
                    help="wire codec for the ring collective "
                         "(quantized collectives — every rank of a "
                         "ring must agree; a mismatch fails typed at "
                         "connect)")
    ap.add_argument("--overlap", action="store_true",
                    help="overlap the stage-k collective with "
                         "stage-k+1 compute: block reduces run on a "
                         "dedicated collective thread while this "
                         "thread computes the next block's partial "
                         "(shard_math.forward_overlapped)")
    ap.add_argument("--overlap-blocks", type=int, default=2,
                    help="row blocks per stage in overlap mode (2 = "
                         "double buffering)")
    ap.add_argument("--trace-parent", type=int, default=0,
                    help="coordinator span id this worker session "
                         "parents its rendezvous spans on (ISSUE 11; "
                         "0 = unparented). Rides the fabric _HELLO "
                         "too, so ring peers agree on the session "
                         "root.")
    ap.add_argument("--span-buffer", type=int, default=512,
                    help="bounded outbound span buffer (obs.xproc."
                         "SpanShip): finished spans piggyback onto "
                         "reply frames; overflow is dropped AND "
                         "counted (shipped as spans_dropped). 0 "
                         "disables shipping entirely.")
    ap.add_argument("--metrics-interval", type=int, default=16,
                    help="ship a federated metrics snapshot every N "
                         "steps (piggybacked on the reply — never an "
                         "extra round trip)")
    ap.add_argument("--connect-timeout", type=float, default=30.0)
    ap.add_argument("--idle-timeout", type=float, default=300.0,
                    help="control-socket wait interval: idle is NOT "
                         "death (a quiet serving replica submits "
                         "nothing between requests), so silence just "
                         "re-arms the wait — a DEAD coordinator "
                         "closes the socket (the kernel does, even "
                         "on a crash) and TCP keepalive surfaces a "
                         "half-open partition, either ending the "
                         "worker in bounded time")
    args = ap.parse_args(argv)

    proto_out = protocol_stdout()  # stdout carries ONLY the summary
    # JSON-lines logging on stderr (satellite of ISSUE 11): the
    # protocol_stdout guard above already repointed every stream
    # handler, so setup() landing on stderr cannot touch the one-line
    # stdout protocol. Rank binds once via context() — every record
    # this process emits carries it.
    obs_logging.setup("shard_worker", stream=sys.stderr)
    with obs_logging.context(rank=args.rank):
        return _serve(args, proto_out)


def _serve(args, proto_out) -> int:
    trace = log.info
    sl = _load_slice(args)
    partial_fn, finish_fn, jitted = _maybe_jit(sl, args.jit,
                                               args.slots)
    lo, hi = segment_bounds(args.slots, args.world)[args.rank]
    result = {"rank": args.rank, "world": args.world,
              "jitted": jitted, "steps": 0, "resets": 0, "ok": False}

    # Cross-process tracing (ISSUE 11): this process's spans (the
    # per-step shard.compute/reduce segments, the ring's
    # fabric.connect, quantized shard.encode chunks) accumulate in the
    # worker-global tracer and PIGGYBACK onto the reply frames the
    # step loop already sends — zero extra round trips. The ship
    # buffer is bounded and its losses counted (shipped too, so the
    # coordinator re-exports them).
    tracer = obs_trace.get_tracer()
    ship = (SpanShip(cap=args.span_buffer)
            if args.span_buffer > 0 else None)
    # Worker-local metrics, federated to the coordinator every
    # --metrics-interval steps as a snapshot on the same piggyback.
    reg = Registry()
    # Per-step span context the reduce closures read: the compute
    # span's id is reserved at step start (reduce segments parent on
    # it) and the span itself is recorded when the step closes.
    cur = {"sid": None, "step": 0, "traced": False}

    peers = [p for p in args.peers.split(",") if p]
    ring = None
    reducer = None
    csock = socket.socket()
    try:
        if args.world > 1:
            bind_port = int(peers[args.rank].rpartition(":")[2])
            ring = RingTransport(args.rank, args.world, args.bind_ip,
                                 peers, port=bind_port,
                                 codec=args.codec,
                                 trace_parent=args.trace_parent
                                 or None)
            trace(f"connecting ring ({args.world} ranks, "
                  f"codec={args.codec})")
            ring.connect(timeout=args.connect_timeout)
        trace(f"dialing coordinator {args.coordinator}")
        chost, _, cport = args.coordinator.rpartition(":")
        csock.settimeout(args.connect_timeout)
        csock.connect((chost, int(cport)))
        # Half-open partition coverage for the idle loop below: with
        # keepalive armed, a coordinator host that vanished without a
        # FIN surfaces as an OSError instead of eternal silence.
        csock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        # The reply frame is a small header write followed by the
        # zero-copy token/state parts: NODELAY so the parts never sit
        # out a Nagle/delayed-ACK round trip between sendalls.
        csock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_msg(csock, {"op": "hello", "rank": args.rank})

        x = np.zeros((args.slots, sl.d), np.float32)
        out = np.empty((args.slots, sl.d), np.float32)
        scratch = np.empty(args.slots * sl.d, np.float32)

        def reduce_fn(part, stage):
            t0 = time.monotonic()
            try:
                if ring is None:
                    total = part
                else:
                    total = ring.allreduce(part, out, scratch)
            except BaseException as e:
                # Peer-side evidence of a sick ring: how long this
                # rank blocked before the failure surfaced — shipped
                # like every other span, so the coordinator's flight
                # snapshot shows the stall on the victim's peers.
                if cur["traced"]:
                    tracer.record_span(
                        "shard.reduce_stall", t0, time.monotonic(),
                        parent_id=cur["sid"],
                        attrs={"rank": args.rank, "step": cur["step"],
                               "stage": stage,
                               "error": type(e).__name__})
                raise
            if cur["traced"]:
                tracer.record_span(
                    "shard.reduce_blocked", t0, time.monotonic(),
                    parent_id=cur["sid"],
                    attrs={"rank": args.rank, "step": cur["step"],
                           "stage": stage})
            reduce_fn.collective_s += time.monotonic() - t0
            return total

        reduce_fn.collective_s = 0.0

        # Overlap mode: the collective rides its own thread; the
        # per-step collective_s is the time the COMPUTE thread
        # actually blocked waiting for a reduce — the non-hidden
        # remainder, which is the number overlap exists to shrink.
        coll_box = [0.0]
        if args.overlap:
            reducer = _ring_reducer(ring)

            def reduce_submit(part, stage, block):
                return reducer.submit(part)

            def reduce_wait(tkt):
                # No AGGREGATE ceiling: a chunked allreduce's total
                # time is only bounded per socket op (io_timeout) and
                # per chunk dependency (the 60 s event waits), so a
                # fixed wall here could spuriously fail a healthy-but-
                # slow ring the serialized path would have finished.
                # The wait re-arms in slices; a genuine hang still
                # surfaces in bounded time because every ring op is
                # deadline-armed and the guarded reducer ALWAYS sets
                # the event — the liveness check below covers only a
                # dead reducer thread (can't set anything again).
                t0 = time.monotonic()
                while not tkt.event.wait(60.0):
                    if not reducer.thread.is_alive():
                        coll_box[0] += time.monotonic() - t0
                        if cur["traced"]:
                            tracer.record_span(
                                "shard.reduce_stall", t0,
                                time.monotonic(),
                                parent_id=cur["sid"],
                                attrs={"rank": args.rank,
                                       "step": cur["step"],
                                       "error": "RingError"})
                        raise RingError(
                            "ring reducer thread died with the "
                            "reduce outstanding")
                coll_box[0] += time.monotonic() - t0
                if tkt.error is not None:
                    if cur["traced"]:
                        tracer.record_span(
                            "shard.reduce_stall", t0,
                            time.monotonic(), parent_id=cur["sid"],
                            attrs={"rank": args.rank,
                                   "step": cur["step"],
                                   "error": type(tkt.error).__name__})
                    raise tkt.error
                if cur["traced"]:
                    tracer.record_span(
                        "shard.reduce_blocked", t0, time.monotonic(),
                        parent_id=cur["sid"],
                        attrs={"rank": args.rank,
                               "step": cur["step"]})
                return tkt.value

        while True:
            # Idle is not death: a drained serving replica submits
            # nothing between requests, and a worker that exited on
            # silence would make every lull cost a spurious replica
            # failure + re-rendezvous. So the IDLE wait (select, no
            # bytes consumed) re-arms freely — but once the frame's
            # first byte is on the wire, the whole frame must land
            # under a FRESH deadline and a mid-frame timeout is
            # FATAL: catching it would desync the positional stream
            # (the next "header" would be this frame's json body).
            # Coordinator death still ends the worker via the closed
            # socket (ProtocolError/OSError).
            readable, _, _ = select.select([csock], [], [],
                                           args.idle_timeout)
            if not readable:
                continue
            msg, payload = recv_msg(csock, timeout=args.idle_timeout)
            # Clock-sync receive stamp (ISSUE 11): the coordinator
            # pairs this with its own send/receive stamps to estimate
            # this worker's monotonic offset (NTP midpoint) — the
            # stamps ride frames that exist anyway.
            t_rx = time.monotonic()
            op = msg["op"]
            if op == "close":
                break
            if op == "reset":
                x = np.zeros((args.slots, sl.d), np.float32)
                result["resets"] += 1
                send_msg(csock, {"op": "ack", "reset": True,
                                 "t_rx": round(t_rx, 6),
                                 "t_tx": round(time.monotonic(), 6)})
                continue
            if op != "step":
                raise ProtocolError(f"unknown op {op!r}")
            traced = tracer.enabled
            sid = tracer.reserve_id() if traced else None
            cur["sid"], cur["step"] = sid, msg["step"]
            cur["traced"] = traced
            t0 = time.monotonic()
            idx = msg["slots"]
            rows = np.frombuffer(payload, np.float32).reshape(
                len(idx), sl.d) if idx else None
            for j, i in enumerate(idx):
                x[i] = rows[j]
            if args.overlap:
                coll_box[0] = 0.0
                x, tokens = sl.forward_overlapped(
                    x, reduce_submit, reduce_wait,
                    blocks=args.overlap_blocks,
                    partial_fn=partial_fn, finish_fn=finish_fn)
                coll = coll_box[0]
            else:
                reduce_fn.collective_s = 0.0
                x, tokens = sl.forward(x, reduce_fn,
                                       partial_fn=partial_fn,
                                       finish_fn=finish_fn)
                coll = reduce_fn.collective_s
            total = time.monotonic() - t0
            if traced:
                attrs = {"rank": args.rank, "step": msg["step"],
                         "compute_s": round(max(0.0, total - coll),
                                            6),
                         "collective_s": round(coll, 6)}
                tp = msg.get("trace_parent")
                if tp:
                    # A COORDINATOR-space parent id: it must not ride
                    # parent_id (that space collides with this
                    # process's ids) — the wire format carries it as
                    # attrs["xparent"] and ingest resolves it.
                    attrs["xparent"] = tp
                tracer.record_span("shard.compute", t0,
                                   time.monotonic(), span_id=sid,
                                   attrs=attrs)
            reg.observe("shard_step_compute_seconds",
                        max(0.0, total - coll),
                        buckets=_WORKER_BUCKETS,
                        help="worker-local per-step compute time "
                             "(federated to the coordinator)")
            reg.observe("shard_step_collective_seconds", coll,
                        buckets=_WORKER_BUCKETS,
                        help="worker-local time blocked in the ring "
                             "collective per step (federated)")
            reg.counter_inc("shard_steps_total",
                            help="steps served by this shard worker")
            reply = {"op": "tokens", "step": msg["step"],
                     "compute_s": round(max(0.0, total - coll), 6),
                     "collective_s": round(coll, 6),
                     "t_rx": round(t_rx, 6)}
            # Span shipping: everything the worker traced since the
            # last reply piggybacks here — on a frame that exists
            # anyway, never an extra round trip. Losses to the
            # bounded buffer ship as a counter next to the spans.
            if ship is not None:
                ship.harvest(tracer)
                wire = ship.flush()
                if wire:
                    reply["spans"] = wire
                reply["spans_dropped"] = ship.dropped_total
            if result["steps"] % args.metrics_interval == 0:
                reply["metrics"] = reg.federated_snapshot()
            # Zero-copy reply: the token segment and the state ship as
            # buffer-protocol parts straight out of their arrays — no
            # tobytes() copies in the per-step loop (GL011).
            parts = [np.ascontiguousarray(tokens[lo:hi], np.int32)]
            if msg.get("want_state") and args.rank == 0:
                reply["state"] = True
                parts.append(np.ascontiguousarray(x, np.float32))
            reply["t_tx"] = round(time.monotonic(), 6)
            send_msg(csock, reply, *parts)
            result["steps"] += 1
        result["ok"] = True
    except Exception as e:
        result["error"] = repr(e)[:300]
        log.error("failed: %r", e)
    finally:
        if reducer is not None:
            reducer.stop()
        if ring is not None:
            ring.close()
        csock.close()
    print(json.dumps(result), file=proto_out, flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
