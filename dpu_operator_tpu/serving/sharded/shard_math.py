"""The per-shard slice of a serving replica's decode step.

One FabricExecutor replica spans `world` shard workers; each worker
holds ONE tensor-parallel slice of the params and the (replicated)
[slots, d] decode state, computes its partial contribution per stage,
and closes the contraction with an allreduce over whatever collective
plane the backend provides — the in-process reduce board of the
SyntheticShardSet, or parallel/fabric_collectives.RingTransport in the
real shard worker. This module is that slice's MATH, in plain numpy
(optionally another array module via ``xp`` — the real worker jits the
same functions), shared by every backend so the token-equivalence
contract ("a sharded replica decodes the same streams as a local one")
has exactly one definition to hold against.

Two slice families:

  * ``TpShardSlice`` — the Megatron pairing over the REAL
    train_step.init_params layout: w1 column-sharded, w2 row-sharded,
    so ``relu(x @ w1_r) @ w2_r`` summed over ranks equals
    ``relu(x @ w1) @ w2`` exactly (relu is elementwise on DISJOINT
    column slices — the decomposition is exact in real arithmetic;
    only the final sum's fp order differs, which argmax tolerates).
    After the reduce every rank computes the identical tanh + MoE
    residual, so the replicated states stay bit-identical across
    shards. E must be 1: the MoE all_to_all is not carried across
    shards (the expert block replicates; tp shards only the dense
    contraction — the serving projection of the Megatron pairing).
  * ``DoubleShardSlice`` — the SyntheticExecutor double
    (``tanh(x @ W)``) with W row-sharded over the input dim: partials
    ``x[:, lo:hi] @ W[lo:hi]`` sum to the full product. The jax-free
    slice for scheduler/chaos tests with dialable costs.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

# Token ownership (shard r reports slots seg[r]) and weight slicing use
# the SAME even-contiguous split the fabric ring uses for its
# collective segments — imported, not re-implemented, so the two can
# never silently diverge. fabric_collectives is jax-free.
from ...parallel.fabric_collectives import (
    _segment_bounds as segment_bounds)


class ShardSlice:
    """One rank's compute: per-stage ``partial`` (pre-reduce) and
    ``finish`` (post-reduce), plus the stage loop. ``reduce_fn(partial,
    stage)`` is the collective seam the backend injects. ``xp`` is the
    array module the stage math runs on — numpy by default; the real
    worker swaps in jax.numpy before jitting the SAME methods (the
    ufunc calls must trace, so they go through ``self.xp``)."""

    stages: int = 1
    d: int = 0
    xp = np

    def partial(self, x: np.ndarray, stage: int) -> np.ndarray:
        raise NotImplementedError

    def finish(self, x: np.ndarray, dense: np.ndarray,
               stage: int) -> np.ndarray:
        raise NotImplementedError

    def forward(self, x: np.ndarray,
                reduce_fn: Callable[[np.ndarray, int], np.ndarray],
                partial_fn: Optional[Callable] = None,
                finish_fn: Optional[Callable] = None,
                ) -> Tuple[np.ndarray, np.ndarray]:
        """One decode step on the replicated state: per stage, local
        partial → allreduce → local finish. Returns (x_next, tokens);
        tokens are the FULL [slots] argmax (identical on every rank —
        callers report only their owned segment). ``partial_fn``/
        ``finish_fn`` override the local math (the real worker passes
        jitted wrappers over the same methods)."""
        pf = partial_fn if partial_fn is not None else self.partial
        ff = finish_fn if finish_fn is not None else self.finish
        for s in range(self.stages):
            dense = reduce_fn(np.asarray(pf(x, s), np.float32), s)
            x = np.asarray(ff(x, dense, s), np.float32)
        return x, np.argmax(x, axis=1).astype(np.int32)

    def forward_overlapped(self, x: np.ndarray,
                           reduce_submit: Callable,
                           reduce_wait: Callable,
                           blocks: int = 2,
                           partial_fn: Optional[Callable] = None,
                           finish_fn: Optional[Callable] = None,
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """The same step with the per-stage partial→allreduce→finish
        sequence RESTRUCTURED so the collective overlaps compute: slots
        split into ``blocks`` row blocks (legal because every piece of
        the stage math is row-independent — each slot's matmuls and the
        MoE residual touch only that slot's row), and the collective
        seam split into ``reduce_submit(partial, stage, block) →
        ticket`` / ``reduce_wait(ticket) → dense`` so a block's reduce
        runs on the backend's collective plane while this thread
        computes the NEXT block's partial — and, across the stage
        boundary, stage k's still-in-flight reduces overlap stage k+1's
        partials (the double-buffered schedule: at steady state one
        block is always on the wire while another is on the ALU).

        Every rank MUST issue submits in the identical (stage, block)
        order — the loop below is deterministic, and backends key their
        collective cells/allreduces on (stage, block), so the schedule
        is the ordering contract.

        Numerics contract: on the synthetic board (rank-ordered cell
        sum) block-splitting changes nothing — per element the same
        contributions add in the same order, so streams are
        token-identical to the unoverlapped path. On the REAL ring
        the block-wise allreduces re-segment the payload, so an
        element's ring addition ORDER can differ from the whole-array
        reduce — exact in real arithmetic, last-ulp fp deltas
        possible, which argmax tolerates (the same caveat as
        TpShardSlice's cross-rank sum): equivalence there is
        token-level, not bit-level."""
        pf = partial_fn if partial_fn is not None else self.partial
        ff = finish_fn if finish_fn is not None else self.finish
        x = np.array(x, np.float32)  # mutated per block below
        bounds = [b for b in segment_bounds(x.shape[0],
                                            max(1, blocks))
                  if b[1] > b[0]]
        pending: list = []  # (ticket, lo, hi) in (stage, block) order
        for s in range(self.stages):
            for bi, (lo, hi) in enumerate(bounds):
                if s > 0:
                    t, plo, phi = pending.pop(0)
                    x[plo:phi] = np.asarray(
                        ff(x[plo:phi], reduce_wait(t), s - 1),
                        np.float32)
                part = np.asarray(pf(x[lo:hi], s), np.float32)
                pending.append((reduce_submit(part, s, bi), lo, hi))
        for t, lo, hi in pending:
            x[lo:hi] = np.asarray(
                ff(x[lo:hi], reduce_wait(t), self.stages - 1),
                np.float32)
        return x, np.argmax(x, axis=1).astype(np.int32)


def make_mesh_stage_fn(mesh, params: dict, axis: str = "tp",
                       overlap: bool = True):
    """The jax-shard form of the overlapped stage: when the
    tensor-parallel slices live as shards ON A JAX MESH (one process,
    the virtual-device or real-TPU case) the collective doesn't need a
    reducer thread at all — ``collective_matmul.make_allgather_matmul``
    DECOMPOSES the slot-gather into ring steps inside the w1 matmul,
    so each block's transfer hides behind the previous block's dot
    (pallas RDMA on real multi-chip meshes, XLA async collective-
    permute elsewhere), and the w2 contraction closes with an explicit
    psum. This is the same partial→reduce→finish sequence
    ``forward_overlapped`` pipelines by hand for process shards,
    expressed in the compiler's overlap vocabulary; ``overlap=False``
    keeps the naive gather-then-matmul for A/B comparison.

    Returns ``step(x[slots, d]) -> (x_next, tokens)``; slots must
    divide the axis size (the shard_map even-shard contract).
    Token-equivalent to ``TpShardSlice`` at any world (verified in
    tests/test_sharded.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ...parallel._compat import shard_map
    from ...parallel.collective_matmul import make_allgather_matmul

    p = {k: np.asarray(v, np.float32) for k, v in params.items()}
    if p["router"].shape[2] != 1 or p["moe_w1"].shape[1] != 1:
        raise ValueError(
            "mesh-stage serving shards require E == 1 (tp shards the "
            "dense contraction; experts replicate)")
    S = p["w1"].shape[0]
    n = mesh.shape[axis]
    ag_mm = make_allgather_matmul(mesh, axis, overlap=overlap)
    close = jax.jit(shard_map(
        lambda h_loc, w2_loc: jax.lax.psum(
            jnp.maximum(h_loc, 0.0) @ w2_loc, axis),
        mesh=mesh, in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, None), check_vma=False))
    finish = jax.jit(
        lambda dense, m1, m2: (lambda y: y + jnp.maximum(
            y @ m1, 0.0) @ m2)(jnp.tanh(dense)))

    def step(x: np.ndarray):
        x = np.ascontiguousarray(x, np.float32)
        if x.shape[0] % n:
            raise ValueError(
                f"slots {x.shape[0]} must divide the {axis!r} axis "
                f"size {n} (shard_map even-shard contract)")
        for s in range(S):
            h_col = ag_mm(x, p["w1"][s])          # gather ∥ matmul
            dense = close(h_col, p["w2"][s])      # psum closes w2
            x = finish(dense, p["moe_w1"][s, 0], p["moe_w2"][s, 0])
        x = np.asarray(x, np.float32)
        return x, np.argmax(x, axis=1).astype(np.int32)

    return step


class TpShardSlice(ShardSlice):
    """Rank r's Megatron slice of the stage-stacked train_step params
    (the LocalExecutor model): w1 [S, d, h] column slice, w2 [S, h, d]
    row slice, router/MoE weights replicated. The idle-slot contract
    holds by arithmetic: a zero row stays zero through relu/matmul/
    tanh and contributes zero MoE residual, so no row mask is needed
    at E == 1 (capacity ≥ rows means no token ever drops)."""

    def __init__(self, params: dict, rank: int, world: int):
        if not (0 <= rank < world):
            raise ValueError(f"bad shard shape rank={rank} "
                             f"world={world}")
        p = {k: np.asarray(v, np.float32) for k, v in params.items()}
        S, d, h = p["w1"].shape
        if p["router"].shape[2] != 1 or p["moe_w1"].shape[1] != 1:
            raise ValueError(
                "tensor-parallel serving shards require E == 1: the "
                "MoE all_to_all is not carried across the shard "
                "fabric (tp shards the dense contraction; experts "
                "replicate)")
        if "wq" in p:
            raise ValueError("attention params are not supported by "
                             "the serving shard slice (decode state "
                             "has no sequence axis)")
        self.rank, self.world = rank, world
        self.stages, self.d, self.h = S, d, h
        lo, hi = segment_bounds(h, world)[rank]
        # Empty slices are legal (world > h): the rank contributes a
        # zero partial and still participates in every collective.
        self.w1 = p["w1"][:, :, lo:hi]            # [S, d, h_r]
        self.w2 = p["w2"][:, lo:hi, :]            # [S, h_r, d]
        self.moe_w1 = p["moe_w1"][:, 0]           # [S, d, h]
        self.moe_w2 = p["moe_w2"][:, 0]           # [S, h, d]

    def partial(self, x: np.ndarray, stage: int) -> np.ndarray:
        xp = self.xp
        if self.w1.shape[2] == 0:
            return xp.zeros((x.shape[0], self.d), np.float32)
        return xp.maximum(x @ self.w1[stage], 0.0) @ self.w2[stage]

    def finish(self, x: np.ndarray, dense: np.ndarray,
               stage: int) -> np.ndarray:
        xp = self.xp
        y = xp.tanh(dense)
        # Switch MoE at E == 1: softmax over one expert is exactly 1.0
        # and capacity (ceil(rows · cf) ≥ rows) never drops a token,
        # so the block reduces to the expert body as a residual —
        # verified token-equivalent against the jitted
        # switch_moe_local path in tests/test_sharded.py.
        moe = xp.maximum(y @ self.moe_w1[stage], 0.0) \
            @ self.moe_w2[stage]
        return y + moe


class DoubleShardSlice(ShardSlice):
    """Rank r's row slice of the SyntheticExecutor double: partials
    ``x[:, lo:hi] @ W[lo:hi]`` allreduce to ``x @ W``; finish is the
    elementwise tanh. Same seeded W construction as SyntheticExecutor
    so token streams compare 1:1."""

    stages = 1

    def __init__(self, d: int, seed: int, rank: int, world: int):
        if not (0 <= rank < world):
            raise ValueError(f"bad shard shape rank={rank} "
                             f"world={world}")
        self.rank, self.world, self.d = rank, world, d
        w = np.random.RandomState(seed).randn(d, d).astype(
            np.float32) / np.sqrt(d)
        lo, hi = segment_bounds(d, world)[rank]
        self._lo, self._hi = lo, hi
        self.w = w[lo:hi, :]                      # [d_r, d]

    def partial(self, x: np.ndarray, stage: int) -> np.ndarray:
        if self._hi == self._lo:
            return self.xp.zeros((x.shape[0], self.d), np.float32)
        return x[:, self._lo:self._hi] @ self.w

    def finish(self, x: np.ndarray, dense: np.ndarray,
               stage: int) -> np.ndarray:
        return self.xp.tanh(dense)
