"""Fabric-sharded serving replicas (ISSUE 8).

One replica's decode step spans many shard workers: the
``FabricExecutor`` coordinator speaks the serving plane's two-phase
``submit/collect`` contract upward (scheduler, supervisor, bench all
unchanged) and a tiny shard-set contract downward, with two backends —
``SyntheticShardSet`` (in-process shard threads with controlled step
and collective cost: tier-1's deterministic double) and
``ShardProcessSet`` (real ``shard_worker`` processes reducing over
parallel/fabric_collectives, ring order from
parallel/topology.ring_order: the multiworker lane). The shard-side
math lives once in ``shard_math`` so every backend decodes the same
token streams.

Importing this package stays jax-free (the real worker jits only
inside its own process)."""

from .executor import FabricExecutor
from .procset import ShardProcessSet
from .synthetic import (ShardAborted, ShardCollectiveStall, ShardError,
                        ShardStepError, ShardTimeout, StepOutput,
                        SyntheticShardSet)

__all__ = [
    "FabricExecutor",
    "ShardAborted",
    "ShardCollectiveStall",
    "ShardError",
    "ShardProcessSet",
    "ShardStepError",
    "ShardTimeout",
    "StepOutput",
    "SyntheticShardSet",
]
