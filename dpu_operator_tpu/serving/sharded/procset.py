"""ShardProcessSet — real shard workers behind the ShardSet contract.

Spawns ``world`` shard_worker processes, wires their collective ring
(ring order from parallel/topology.ring_order over the allocated
rendezvous addresses — the coordinator and any restarted incarnation
derive the SAME ring from the same address set), accepts their control
dials, and speaks the framed protocol. Byte-for-byte the same
contract the SyntheticShardSet serves in-process, so a FabricExecutor
cannot tell thread shards from fabric workers — tier-1 proves the
scheduling/chaos contracts on threads, the multiworker lane proves the
rendezvous and the real collective with THIS class.

Failure surfaces in bounded time everywhere: worker spawn/hello under
``spawn_timeout_s``, every control receive under the caller's collect
deadline, and recovery is always the full kill + respawn (a real
re-rendezvous) — the control stream is positional, so any failed or
abandoned step leaves unread frames behind and no polite path exists.

Supervision safety mirrors SyntheticShardSet's generation discipline:
every handle carries the generation it was submitted under, a collect
against a torn-down generation fails fast with ``ShardAborted``, a
blocked collect snapshots its generation's sockets (a restarted
incarnation's fresh sockets are invisible to it), and the
failure-path teardown only acts when the failing handle still IS the
current generation — an abandoned wedged collect waking after the
supervisor restarted the replica can never kill the respawned set."""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...obs import trace as obs_trace
from ...obs.xproc import ClockSync
from ...parallel.topology import ring_order
from .protocol import ProtocolError, recv_msg, send_msg
from .shard_math import segment_bounds
from .synthetic import (ShardAborted, ShardError, ShardStepError,
                        ShardTimeout, StepOutput)


def _distinct_ports(n: int) -> List[int]:
    """n distinct loopback ports, all bound SIMULTANEOUSLY before any
    is released — sequential bind-then-close can hand the same port
    out twice. The close→worker-bind window remains (inherent to
    pre-agreed ring addresses on one host); a stolen port surfaces as
    a bounded spawn timeout, never a hang."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _reap(procs: Sequence[subprocess.Popen],
          socks: Dict[int, socket.socket],
          listener: Optional[socket.socket], kill: bool) -> None:
    """Close an incarnation's control sockets and reap its worker
    processes (polite close op unless `kill`)."""
    for s in socks.values():
        try:
            if not kill:
                send_msg(s, {"op": "close"})
        except OSError:
            pass
        s.close()
    if listener is not None:
        listener.close()
    for p in procs:
        if kill:
            p.kill()
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=5)


class _ProcHandle:
    """One submitted step's ledger token: just the generation it
    belongs to and its step identity — the replies live on the
    sockets, not here (unlike the synthetic set's per-rank reply
    board, which this deliberately is NOT)."""

    __slots__ = ("gen", "step_no", "want_state", "tx")

    def __init__(self, gen: int, step_no: int, want_state: bool):
        self.gen = gen
        self.step_no = step_no
        self.want_state = want_state
        # Per-rank monotonic send stamps (clock sync, ISSUE 11): the
        # coordinator half of the NTP four-timestamp exchange the
        # worker's reply completes.
        self.tx: Dict[int, float] = {}


class ShardProcessSet:
    """``world`` shard_worker subprocesses on loopback (the same
    program runs unchanged inside operator-attached pod netns — only
    the addresses differ; see docs/serving.md)."""

    def __init__(self, world: int, slots: int, d: int = 16, *,
                 params: Optional[dict] = None, seed: int = 0,
                 jit: bool = True, spawn_timeout_s: float = 60.0,
                 python: str = sys.executable,
                 codec: str = "fp32", overlap: bool = False,
                 overlap_blocks: int = 2, span_buffer: int = 512,
                 metrics_interval: int = 16):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = world
        self.slots = slots
        self.params = params
        self.d = (int(np.asarray(params["w1"]).shape[1])
                  if params is not None else d)
        self.seed = seed
        self.jit = jit
        self.spawn_timeout_s = spawn_timeout_s
        self.python = python
        # Quantized-collective + overlap knobs, handed verbatim to
        # every shard_worker (a ring must agree on its codec — the
        # hello handshake refuses a mixed ring typed).
        self.codec_name = str(codec or "fp32")
        self.overlap = bool(overlap)
        self.overlap_blocks = int(overlap_blocks)
        # ISSUE 11 shipping knobs, handed to every worker: bounded
        # span piggyback buffer (0 disables shipping) and the
        # federated-metrics snapshot cadence.
        self.span_buffer = int(span_buffer)
        self.metrics_interval = max(1, int(metrics_interval))
        self.segments = segment_bounds(slots, world)
        self._procs: List[subprocess.Popen] = []
        self._socks: Dict[int, socket.socket] = {}
        self._listener: Optional[socket.socket] = None
        self._params_path: Optional[str] = None
        self._up = False
        # Generation discipline: bumped on every teardown; handles
        # are stamped at submit and checked at collect, so a stale
        # (pre-restart) caller can neither read a fresh socket nor
        # tear the fresh generation down. TWO locks, two jobs:
        # `_lock` guards the gen/socks/outstanding bookkeeping and is
        # NEVER held across a blocking call, so collect's fast
        # gen-check exit and the leak-ledger read stay fail-fast even
        # while a 60 s respawn is in flight; `_life` serializes the
        # lifecycle operations themselves (spawn/teardown/reset/
        # close/submit) whose socket work legitimately blocks.
        self._gen = 0
        self._lock = threading.Lock()
        self._life = threading.RLock()
        self._outstanding: set = set()
        self.respawns = 0
        # Per-rank monotonic clock offset estimators (ISSUE 11), fed
        # by the send/receive stamps the step frames already carry.
        # Reset on teardown: a respawned worker is a NEW process with
        # a new clock.
        self._clocks: Dict[int, ClockSync] = {}

    # -- rendezvous -----------------------------------------------------------

    def _spawn(self) -> None:
        """Caller holds ``_life``. All blocking socket work happens on
        locals; the new incarnation commits under ``_lock`` at the
        end, so bookkeeping readers never wait on a rendezvous."""
        if self.params is not None and self._params_path is None:
            fd, self._params_path = tempfile.mkstemp(
                prefix="shard-params-", suffix=".npz")
            os.close(fd)
            np.savez(self._params_path,
                     **{k: np.asarray(v, np.float32)
                        for k, v in self.params.items()})
        listener = socket.socket()
        listener.setsockopt(socket.SOL_SOCKET,
                            socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", 0))
        listener.listen(self.world + 2)
        listener.settimeout(self.spawn_timeout_s)
        cport = listener.getsockname()[1]
        # Session root span (ISSUE 11): reserved now so the workers
        # can parent their rendezvous spans (fabric.connect via the
        # --trace-parent arg and the ring _HELLO) on it; recorded
        # once the rendezvous completes.
        tr = obs_trace.get_tracer()
        spawn_sid = tr.reserve_id() if tr.enabled else None
        t_spawn = time.monotonic()
        # The ring the shards reduce over: allocate one fabric address
        # per shard, then let topology.ring_order pick the canonical
        # order — rank r of the spawned set IS ring position r.
        addrs = [f"127.0.0.1:{p}"
                 for p in _distinct_ports(self.world)]
        ring = ring_order(addrs)
        procs: List[subprocess.Popen] = []
        socks: Dict[int, socket.socket] = {}
        for rank in range(self.world):
            cmd = [self.python, "-m",
                   "dpu_operator_tpu.serving.sharded.shard_worker",
                   "--rank", str(rank), "--world", str(self.world),
                   "--slots", str(self.slots), "--d", str(self.d),
                   "--coordinator", f"127.0.0.1:{cport}",
                   "--bind-ip", "127.0.0.1",
                   "--peers", ",".join(ring),
                   "--seed", str(self.seed),
                   "--connect-timeout", str(self.spawn_timeout_s)]
            if spawn_sid is not None:
                cmd += ["--trace-parent", str(spawn_sid)]
            cmd += ["--span-buffer", str(self.span_buffer),
                    "--metrics-interval", str(self.metrics_interval)]
            if self._params_path:
                cmd += ["--params-npz", self._params_path]
            if self.jit:
                cmd.append("--jit")
            if self.codec_name != "fp32":
                cmd += ["--codec", self.codec_name]
            if self.overlap:
                cmd += ["--overlap", "--overlap-blocks",
                        str(self.overlap_blocks)]
            procs.append(subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        deadline = time.monotonic() + self.spawn_timeout_s
        try:
            while len(socks) < self.world:
                if time.monotonic() > deadline:
                    raise ShardTimeout(
                        f"only {len(socks)}/{self.world} shards "
                        f"dialed in within {self.spawn_timeout_s}s")
                c, _ = listener.accept()
                # Control frames are a small header write + zero-copy
                # payload parts: NODELAY so the parts never wait out a
                # delayed-ACK exchange between the two sendalls.
                c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                msg, _ = recv_msg(c, timeout=self.spawn_timeout_s)
                if msg.get("op") != "hello":
                    c.close()
                    continue
                socks[int(msg["rank"])] = c
        except (OSError, ProtocolError, ShardError):
            _reap(procs, socks, listener, kill=True)
            raise
        if spawn_sid is not None:
            tr.record_span(
                "shard.spawn", t_spawn, time.monotonic(),
                span_id=spawn_sid,
                attrs={"world": self.world, "respawn": self.respawns,
                       "codec": self.codec_name})
        with self._lock:
            self._listener = listener
            self._procs = procs
            self._socks = socks
            self._up = True

    def _teardown(self, kill: bool) -> None:
        """Caller holds ``_life``. Bumps the generation and detaches
        the incarnation's resources under ``_lock`` FIRST — handles
        submitted against the old incarnation fail fast at collect()
        and a stale blocked reader (its per-recv deadline bounds the
        wake-up) finds its snapshot sockets dead, never the
        successor's — then does the blocking close/kill/reap work on
        the detached locals."""
        with self._lock:
            self._gen += 1
            socks = self._socks
            self._socks = {}
            listener = self._listener
            self._listener = None
            procs = self._procs
            self._procs = []
            # A respawned worker is a new process with a new
            # monotonic clock: stale offsets must not align the fresh
            # incarnation's spans.
            self._clocks = {}
            self._up = False
        _reap(procs, socks, listener, kill=kill)

    # -- the ShardSet contract ------------------------------------------------

    def reset(self) -> None:
        """Zero every shard's decode state. Any outstanding step (or
        any miss on the reset ack) forces kill + respawn — the real
        re-rendezvous: a submitted-never-collected step left unread
        frames on the positional control stream, so the polite path
        would desync even if every worker were healthy."""
        with self._life:
            with self._lock:
                stale = list(self._outstanding)
                # Generation-orphaned handles are settled (collect
                # raises ShardAborted on the gen mismatch), so
                # exactly these leave the ledger.
                self._outstanding.difference_update(stale)
                up = self._up
                socks = dict(self._socks)
            if not up:
                self._spawn()
                return
            if stale:
                self._teardown(kill=True)
                self.respawns += 1
                self._spawn()
                return
            try:
                tx = {}
                for rank, s in socks.items():
                    tx[rank] = time.monotonic()
                    send_msg(s, {"op": "reset"})
                for rank, s in socks.items():
                    msg, _ = recv_msg(s, timeout=self.spawn_timeout_s)
                    t_now = time.monotonic()
                    if msg.get("op") != "ack":
                        raise ProtocolError(
                            f"shard {rank}: expected reset ack, got "
                            f"{msg.get('op')!r}")
                    # The reset ack carries worker clock stamps too:
                    # a first offset estimate exists before the first
                    # step's spans need aligning.
                    if "t_rx" in msg and "t_tx" in msg:
                        self._clocks.setdefault(
                            rank, ClockSync()).observe(
                            tx[rank], float(msg["t_rx"]),
                            float(msg["t_tx"]), t_now)
            except (OSError, ProtocolError, ShardError):
                self._teardown(kill=True)
                self.respawns += 1
                self._spawn()

    def submit(self, step_no: int, updates: Sequence,
               want_state: bool = False,
               trace_parent=None) -> _ProcHandle:
        idx = [int(i) for i, _row in updates]
        rows = (np.stack([np.asarray(r, np.float32)
                          for _i, r in updates])
                if updates else np.empty((0, self.d), np.float32))
        msg = {"op": "step", "step": step_no, "slots": idx,
               "want_state": bool(want_state)}
        if trace_parent is not None:
            # Context propagation (ISSUE 11): the coordinator's
            # shard.step span id rides the frame; a worker that
            # predates the field simply never reads it.
            msg["trace_parent"] = int(trace_parent)
        payload = rows  # buffer-protocol part: sent without a copy
        with self._life:
            with self._lock:
                up = self._up
            if not up:
                self._spawn()
            with self._lock:
                handle = _ProcHandle(self._gen, step_no, want_state)
                # On the ledger BEFORE the broadcast: a partial
                # broadcast leaves a poisoned positional stream, and
                # the ledger entry is what routes the next reset() to
                # kill+respawn.
                self._outstanding.add(handle)
                socks = dict(self._socks)
            try:
                for rank, s in socks.items():
                    # The clock-sync send stamp, per rank: taken
                    # immediately before the write so queuing inside
                    # this loop lands in the estimator's uncertainty,
                    # not its bias.
                    handle.tx[rank] = time.monotonic()
                    send_msg(s, msg, payload)
            except OSError as e:
                raise ShardStepError(f"broadcast failed: {e!r}")
            return handle

    def collect(self, handle: _ProcHandle,
                timeout: float) -> StepOutput:
        with self._lock:
            if handle.gen != self._gen:
                self._outstanding.discard(handle)
                raise ShardAborted(
                    "shard set re-rendezvoused mid-step; this handle "
                    "belongs to a torn-down generation")
            # Snapshot THIS generation's sockets: if the set restarts
            # while we block below, the fresh sockets are invisible
            # to us — we fail on our own closed snapshot.
            socks = dict(self._socks)
        deadline = time.monotonic() + timeout
        tokens = np.empty((self.slots,), np.int32)
        state = None
        compute, coll = [0.0] * self.world, [0.0] * self.world
        spans_by_rank: Dict[int, list] = {}
        clock_by_rank: Dict[int, tuple] = {}
        metrics_by_rank: Dict[int, dict] = {}
        span_dropped_by_rank: Dict[int, int] = {}
        try:
            for rank in range(self.world):
                lo, hi = self.segments[rank]
                s = socks.get(rank)
                if s is None:
                    raise ShardAborted(
                        f"shard {rank} gone (set torn down mid-step)",
                        rank=rank)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ShardTimeout(
                        f"shard {rank} never replied to step "
                        f"{handle.step_no} within {timeout}s",
                        rank=rank)
                try:
                    msg, payload = recv_msg(s, timeout=remaining)
                except socket.timeout:
                    raise ShardTimeout(
                        f"shard {rank} silent past the step deadline "
                        f"({timeout}s)", rank=rank)
                except (OSError, ProtocolError) as e:
                    raise ShardStepError(
                        f"shard {rank} control channel failed: "
                        f"{e!r}", rank=rank)
                if msg.get("op") != "tokens" or \
                        msg.get("step") != handle.step_no:
                    raise ShardStepError(
                        f"shard {rank}: unexpected reply "
                        f"{msg.get('op')!r} (step "
                        f"{msg.get('step')} != {handle.step_no})",
                        rank=rank)
                t_reply = time.monotonic()
                seg = np.frombuffer(payload[:4 * (hi - lo)], np.int32)
                tokens[lo:hi] = seg
                compute[rank] = float(msg.get("compute_s", 0.0))
                coll[rank] = float(msg.get("collective_s", 0.0))
                # Clock sync (ISSUE 11): the reply completes the NTP
                # four-timestamp exchange the submit stamps started.
                # The worker's processing time sits BETWEEN its two
                # stamps, so only genuine wire/queue time widens the
                # uncertainty.
                t_tx = handle.tx.get(rank)
                if (t_tx is not None and "t_rx" in msg
                        and "t_tx" in msg):
                    sync = self._clocks.setdefault(rank, ClockSync())
                    sync.observe(t_tx, float(msg["t_rx"]),
                                 float(msg["t_tx"]), t_reply)
                    clock_by_rank[rank] = sync.estimate
                # Piggybacked spans + federated metrics: already paid
                # for by the reply frame — never an extra round trip.
                if msg.get("spans"):
                    spans_by_rank[rank] = msg["spans"]
                if "spans_dropped" in msg:
                    span_dropped_by_rank[rank] = int(
                        msg["spans_dropped"])
                if msg.get("metrics"):
                    metrics_by_rank[rank] = msg["metrics"]
                if msg.get("state"):
                    state = np.frombuffer(
                        payload[4 * (hi - lo):],
                        np.float32).reshape(self.slots, self.d).copy()
            return StepOutput(tokens, state, compute, coll,
                              spans_by_rank=spans_by_rank or None,
                              clock_by_rank=clock_by_rank or None,
                              metrics_by_rank=metrics_by_rank or None,
                              span_dropped_by_rank=(
                                  span_dropped_by_rank or None))
        except ShardError:
            # A failed step leaves unread frames on the positional
            # control stream, so the only safe recovery is the
            # respawn path — but ONLY for our own generation: an
            # abandoned pre-restart collect waking here must not kill
            # the supervisor's freshly restarted incarnation (the
            # gen check runs under _lock AFTER _life is held, so a
            # concurrent lifecycle op cannot slip a new incarnation
            # in between the check and the teardown).
            with self._life:
                with self._lock:
                    current = handle.gen == self._gen
                if current:
                    self._teardown(kill=True)
            raise
        finally:
            with self._lock:
                self._outstanding.discard(handle)

    def outstanding(self) -> int:
        with self._lock:
            return len(self._outstanding)

    def close(self) -> None:
        with self._life:
            with self._lock:
                stale = list(self._outstanding)
                self._outstanding.difference_update(stale)
                up = self._up or self._procs
            if up:
                # An uncollected step means a possibly-blocked reader
                # and unread frames: kill, don't wait on a polite
                # close of a desynced stream.
                self._teardown(kill=bool(stale))
            if self._params_path:
                try:
                    os.unlink(self._params_path)
                except OSError:
                    pass
                self._params_path = None
