"""FabricExecutor — one serving replica sharded across many workers.

The third Executor implementation serving/executor.py documented in
PR 2: the replica's decode step spans ``world`` shard workers, each
holding one tensor-parallel slice of the params (shard_math) and a
replica of the [slots, d] decode state. The coordinator implements
the existing async two-phase contract UNCHANGED — the PR 3 pipelined
batcher loop and the PR 5 supervisor drive it exactly as they drive a
LocalExecutor:

  * ``submit(updates)`` broadcasts the step's scatter updates to every
    shard and returns while the shards compute (the broadcast is a
    queue put / small socket write — the step itself runs on the
    shard plane, which is what the pipelined loop overlaps against);
  * ``collect(handle)`` gathers the per-slot token ids off the shard
    plane under a hard ``step_timeout_s`` deadline (the GL010
    contract: a hung shard surfaces in bounded time; the batcher's
    ``blocked_since`` keeps it watchdog-visible well before that);
  * ``step(x)`` (mode="sync") is the PR 2 full-state round trip: load
    every row, run one step, materialize the next state from shard 0
    — the measured baseline the bench prices the sharded pipeline
    against.

Shard backends speak one duck contract (``reset`` / ``submit(step,
updates, want_state)→handle`` / ``collect(handle, timeout)→
StepOutput`` / ``close``): SyntheticShardSet (thread shards, tier-1)
and ShardProcessSet (real shard_worker processes over the fabric
transport, multiworker lane).

Per-step observability (the executor sees what the scheduler cannot):
``serving_shard_collective_seconds`` (slowest shard's time inside the
allreduce — the step pays the slowest; under overlap, only the
NON-HIDDEN wait) and ``serving_shard_step_skew_seconds``
(fastest-vs-slowest shard local compute: imbalance that manifests as
collective wait), both labelled ``{replica, codec}`` so a quantized
replica's latencies never aggregate with an fp32 one's. The ReplicaPool
binds its registry via ``bind_registry`` so a ServingServer-built pool
exposes both on /metrics without extra wiring.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ...obs import trace as obs_trace
from ...obs.xproc import federate_labels
from ..executor import Executor

# Collective/skew distributions live at decode-step scale, same as the
# scheduler's step histograms.
_SHARD_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                  0.05, 0.1, 0.25, 1.0)


class _TracedStep:
    """One in-flight step's coordinator-side trace context: the
    reserved shard.step span id the workers parent on, the submit
    stamp, and the occupant request ids the recorded span will carry
    (what links the whole shard subtree into each request's
    /debug/traces tree)."""

    __slots__ = ("sid", "t0", "rids", "step_no", "handle")

    def __init__(self, sid: Optional[int], t0: float, rids,
                 step_no: int):
        self.sid = sid
        self.t0 = t0
        self.rids = list(rids) if rids else None
        self.step_no = step_no
        self.handle = None


class FabricExecutor(Executor):
    """Coordinator for one sharded replica. ``shards`` is any shard
    set speaking the duck contract above; ``mode`` picks the scheduler
    loop exactly as LocalExecutor's does."""

    sharded = True

    def __init__(self, shards, mode: str = "pipelined",
                 step_timeout_s: float = 60.0, registry=None,
                 name: str = "sharded0"):
        if mode not in ("pipelined", "sync"):
            raise ValueError(f"mode must be pipelined|sync, got "
                             f"{mode!r}")
        self.shards = shards
        self.slots = int(shards.slots)
        self.d = int(shards.d)
        # The wire codec the shard plane reduces over, stamped on the
        # shard metrics: a quantized and an fp32 replica must never
        # aggregate into one latency series (they are different
        # physical collectives).
        self.codec_name = str(getattr(shards, "codec_name", "fp32"))
        self.pipelined = mode == "pipelined"
        self.step_timeout_s = step_timeout_s
        self.name = name
        self._registry = registry
        self._step_no = 0
        # Cross-process ingest bookkeeping (ISSUE 11): last published
        # per-rank ship-loss total (the counter re-exports deltas so
        # the series stays monotonic per coordinator).
        self._ship_dropped_pub: Dict[int, int] = {}

    # -- wiring ---------------------------------------------------------------

    def bind_registry(self, registry) -> None:
        """ReplicaPool hook: adopt the pool's registry unless the
        constructor already bound one (explicit wins)."""
        if self._registry is None:
            self._registry = registry

    # -- the two-phase decode contract ----------------------------------------

    def reset(self) -> None:
        self._step_no = 0
        # Reset may respawn the worker set (fresh processes, fresh
        # cumulative counters): stale ship-loss cursors would misread
        # the first post-respawn totals.
        self._ship_dropped_pub.clear()
        self.shards.reset()

    def submit(self, updates: Sequence, step=None, request_ids=None,
               occupants=None):
        self._step_no += 1
        tstep = self._begin_step(occupants or request_ids)
        tstep.handle = self.shards.submit(self._step_no,
                                          list(updates),
                                          want_state=False,
                                          trace_parent=tstep.sid)
        if self.pipelined:
            return tstep
        # Sync-shape two-phase callers (the base adapter contract):
        # eager — the step completes before submit returns.
        return self._gather(tstep)

    def collect(self, handle):
        if not self.pipelined:
            return handle  # already token ids (eager submit)
        return self._gather(handle)

    def step(self, x: np.ndarray) -> np.ndarray:
        """The sync loop's full-state round trip: every row loads as
        an update, the next state materializes from shard 0."""
        rows = np.asarray(x, np.float32)
        self._step_no += 1
        tstep = self._begin_step(None)
        tstep.handle = self.shards.submit(self._step_no,
                                          list(enumerate(rows)),
                                          want_state=True,
                                          trace_parent=tstep.sid)
        out = self.shards.collect(tstep.handle,
                                  timeout=self.step_timeout_s)
        self._finish_step(tstep, out)
        if out.state is None:
            raise RuntimeError("shard plane returned no state for a "
                               "sync step")
        return out.state

    def close(self) -> None:
        self.shards.close()

    # -- internals ------------------------------------------------------------

    def _begin_step(self, rids) -> "_TracedStep":
        """Reserve the step's coordinator span id (ISSUE 11): workers
        parent their shard.compute spans on it BEFORE it is recorded
        — the span itself closes at collect, when its submit→gather
        wall exists."""
        tr = obs_trace.get_tracer()
        sid = tr.reserve_id() if tr.enabled else None
        return _TracedStep(sid, time.monotonic(), rids, self._step_no)

    def _gather(self, tstep: "_TracedStep") -> np.ndarray:
        try:
            out = self.shards.collect(tstep.handle,
                                      timeout=self.step_timeout_s)
        except BaseException as e:
            # The reserved id was already shipped: record the failed
            # step against it so the workers' spans (and the chaos
            # timeline) keep their parent instead of dangling.
            tr = obs_trace.get_tracer()
            if tstep.sid is not None and tr.enabled:
                tr.record_span(
                    "shard.step", tstep.t0, time.monotonic(),
                    span_id=tstep.sid,
                    attrs={"replica": self.name,
                           "step": tstep.step_no,
                           "world": int(self.shards.world),
                           "codec": self.codec_name,
                           "request_ids": tstep.rids,
                           "error": type(e).__name__})
            raise
        self._finish_step(tstep, out)
        return out.tokens

    def _finish_step(self, tstep: "_TracedStep", out) -> None:
        tr = obs_trace.get_tracer()
        if tstep.sid is not None and tr.enabled:
            tr.record_span(
                "shard.step", tstep.t0, time.monotonic(),
                span_id=tstep.sid,
                attrs={"replica": self.name, "step": tstep.step_no,
                       "world": int(self.shards.world),
                       "codec": self.codec_name,
                       "request_ids": tstep.rids})
        self._ingest(out, tr)
        self._observe(out)

    def _ingest(self, out, tr) -> None:
        """Drain the shard plane's piggyback into the coordinator:
        foreign spans onto the process tracer (clock-shifted, offset
        and uncertainty stamped), federated metrics re-exported with
        rank/codec labels, ship losses published as a counter."""
        if out.spans_by_rank:
            for rank, wires in out.spans_by_rank.items():
                off, unc = (out.clock_by_rank or {}).get(
                    rank, (0.0, float("inf")))
                attrs = {"clock_offset_s": round(off, 6)}
                if math.isfinite(unc):
                    attrs["clock_unc_s"] = round(unc, 6)
                else:
                    # No round-trip estimate yet: spans land
                    # unshifted and SAY SO — an unaligned foreign
                    # span must not masquerade as an aligned one.
                    off = 0.0
                    attrs["clock_unaligned"] = True
                tr.ingest(wires, offset=off, attrs=attrs)
        reg = self._registry
        if reg is None:
            return
        if out.span_dropped_by_rank:
            for rank, total in out.span_dropped_by_rank.items():
                last = self._ship_dropped_pub.get(rank, 0)
                # A total BELOW the high-water mark means the worker
                # respawned (fresh process, counter restarted from 0):
                # everything it reports is new loss — resyncing the
                # cursor without publishing would swallow it.
                delta = total - last if total >= last else total
                if delta > 0:
                    reg.counter_inc(
                        "serving_shard_trace_dropped_total",
                        {"replica": self.name, "rank": str(rank)},
                        by=float(delta),
                        help="worker spans lost to the bounded "
                             "piggyback ship buffer")
                self._ship_dropped_pub[rank] = total
        if out.metrics_by_rank:
            for rank, snap in out.metrics_by_rank.items():
                reg.apply_federated(
                    snap, extra_labels=federate_labels(
                        rank, self.codec_name, self.name))

    def _observe(self, out) -> None:
        reg = self._registry
        if reg is None or not out.compute_s:
            return
        labels = {"replica": self.name, "codec": self.codec_name}
        reg.observe(
            "serving_shard_collective_seconds",
            max(out.collective_s), labels,
            help="slowest shard's time inside the per-step collective "
                 "(the step pays the slowest ring member)",
            buckets=_SHARD_BUCKETS)
        reg.observe(
            "serving_shard_step_skew_seconds",
            max(out.compute_s) - min(out.compute_s), labels,
            help="fastest-vs-slowest shard local compute per step — "
                 "imbalance that surfaces as collective wait",
            buckets=_SHARD_BUCKETS)
