"""SyntheticShardSet — the FabricExecutor's jax-free shard backend.

N shard threads (the `_GuardedWorker` discipline from
serving/executor.py, extended to a SET: every failure path lands in
the owning step handle and a thread must never die silently) stand in
for N fabric worker processes. The collective plane is an in-process
reduce board with a CONTROLLED cost and a deadline, so overlap, chaos
and scheduling tests are deterministic on shared CI boxes without a
real multi-process rendezvous:

  * ``step_time_s`` — per-rank (scalar or per-shard sequence) local
    compute cost: the skew knob (`serving_shard_step_skew_seconds`
    must move when one shard is slower).
  * ``collective_time_s`` — added wire cost per reduce: the
    collective-fraction knob.
  * ``collective_timeout_s`` — every shard's wait at the reduce board
    carries this deadline (the GL010 contract: a hung peer surfaces
    as ``ShardCollectiveStall`` in bounded time, never an unbounded
    block — and the coordinator's ``collect`` is watchdog-visible in
    the meantime).
  * ``fault_site`` — rank r fires ``{fault_site}{r}.step`` inside its
    shard thread before computing, so a chaos plan can kill or hang
    ONE shard of the replica (the new failure domain) exactly as
    `faults` kills whole replicas.

Failure propagation is eager: a shard that raises poisons its
GENERATION on the board, so peers blocked in the reduce raise
``ShardStepError`` immediately instead of waiting out the stall
deadline. ``reset()`` bumps the generation, aborts every outstanding
handle, abandons busy (possibly hung) shard threads and spawns fresh
ones with zeroed state — the in-process model of the restarted
replica's re-rendezvous.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ... import faults
from ...obs import trace as obs_trace
from ...parallel import quantize
from .shard_math import (DoubleShardSlice, ShardSlice, TpShardSlice,
                         segment_bounds)


class ShardError(RuntimeError):
    """Base of the shard plane's failures; carries the origin rank."""

    def __init__(self, msg: str, rank: Optional[int] = None):
        super().__init__(msg)
        self.rank = rank


class ShardStepError(ShardError):
    """One shard's step raised; the whole replica step is poisoned
    (every peer needs the missing partial)."""


class ShardCollectiveStall(ShardError):
    """A peer never deposited its partial inside the collective
    deadline — the bounded-time spelling of 'one shard is hung'."""


class ShardAborted(ShardError):
    """The step's generation was torn down (reset/close) before the
    result landed — the owner must not retry against this handle."""


class ShardTimeout(ShardError):
    """collect() deadline expired before every shard replied."""


class StepOutput:
    """What one replica step produced, assembled across shards.

    The cross-process extras (ISSUE 11) are None on the in-process
    backend — synthetic shard threads record straight into the
    process tracer, so there is nothing to ship or clock-align:

      * ``spans_by_rank`` — piggybacked wire spans per rank
        (obs.xproc format), for ``Tracer.ingest``;
      * ``clock_by_rank`` — per-rank (offset, uncertainty) monotonic
        clock estimate at collect time;
      * ``metrics_by_rank`` — federated Registry snapshots;
      * ``span_dropped_by_rank`` — each worker's cumulative
        bounded-ship-buffer loss counter."""

    __slots__ = ("tokens", "state", "compute_s", "collective_s",
                 "spans_by_rank", "clock_by_rank", "metrics_by_rank",
                 "span_dropped_by_rank")

    def __init__(self, tokens: np.ndarray,
                 state: Optional[np.ndarray],
                 compute_s: List[float], collective_s: List[float],
                 spans_by_rank=None, clock_by_rank=None,
                 metrics_by_rank=None, span_dropped_by_rank=None):
        self.tokens = tokens
        self.state = state
        self.compute_s = compute_s
        self.collective_s = collective_s
        self.spans_by_rank = spans_by_rank
        self.clock_by_rank = clock_by_rank
        self.metrics_by_rank = metrics_by_rank
        self.span_dropped_by_rank = span_dropped_by_rank


class _StepHandle:
    """Per-step reply board: one slot per rank, an event per rank.
    Every shard failure path deposits SOMETHING here — the owner's
    collect() must never block past its own deadline on silence."""

    __slots__ = ("gen", "step_no", "want_state", "events", "tokens",
                 "errors", "compute_s", "collective_s", "state",
                 "trace_parent", "_updates")

    def __init__(self, gen: int, step_no: int, world: int,
                 want_state: bool, trace_parent=None):
        self.gen = gen
        self.step_no = step_no
        self.want_state = want_state
        # The coordinator's shard.step span id: shard threads parent
        # their per-step spans on it (ISSUE 11 — the same hand-off the
        # real protocol ships in the step frame's trace_parent field).
        self.trace_parent = trace_parent
        self.events = [threading.Event() for _ in range(world)]
        self.tokens: List[Optional[np.ndarray]] = [None] * world
        self.errors: List[Optional[BaseException]] = [None] * world
        self.compute_s = [0.0] * world
        self.collective_s = [0.0] * world
        self.state: Optional[np.ndarray] = None

    def deliver(self, rank: int, tokens: np.ndarray, compute_s: float,
                collective_s: float,
                state: Optional[np.ndarray]) -> None:
        self.tokens[rank] = tokens
        self.compute_s[rank] = compute_s
        self.collective_s[rank] = collective_s
        if state is not None:
            self.state = state
        self.events[rank].set()

    def deliver_error(self, rank: int, exc: BaseException) -> None:
        self.errors[rank] = exc
        self.events[rank].set()


class _ReduceBoard:
    """The in-process allreduce: rank-ordered deterministic sum with a
    modelled wire cost and a hard deadline. One board per set; cells
    are keyed by (generation, step, stage) so stale deposits from an
    abandoned shard thread can never reach a restarted session."""

    def __init__(self, world: int, cost_s: float, timeout_s: float,
                 codec=None):
        self.world = world
        self.cost_s = cost_s
        self.timeout_s = timeout_s
        # Codec model: the transport's quantized allreduce quantizes
        # each rank's CONTRIBUTION once and reduces decoded fp32 —
        # the board mirrors that as a roundtrip on deposit, so token
        # equivalence under int8/bf16 is testable without sockets and
        # the rounding the serving plane sees is the codec's real one.
        self.codec = codec
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._cells: Dict[tuple, dict] = {}
        self._poisoned: Dict[int, BaseException] = {}
        # Per-thread wire busy-clock for the modelled cost: see
        # _charge_wire.
        self._wire_clock = threading.local()

    def poison(self, gen: int, exc: BaseException) -> None:
        """Fail every current and future wait of this generation —
        eager error propagation (a peer must not wait out the stall
        deadline for a partial that provably never comes) AND the
        reset/close abort path. Poison is PERMANENT for its
        generation: a hung shard thread waking long after a reset
        must fail fast against its stale generation, never squat a
        fresh cell for the full stall deadline."""
        with self._lock:
            self._poisoned.setdefault(gen, exc)
            for key in [k for k in self._cells if k[0] == gen]:
                del self._cells[key]
            self._ready.notify_all()

    def reduce(self, gen: int, step_no: int, stage: int, rank: int,
               part: np.ndarray, block: int = 0,
               cost_frac: float = 1.0) -> np.ndarray:
        # The same fault site the REAL transport fires per chunk
        # (fabric_collectives sender loops): a chaos plan targeting
        # fabric.send breaks the synthetic collective identically, so
        # the collective failure domain is testable without sockets.
        faults.fire("fabric.send")
        if self.codec is not None:
            # The codec roundtrip models the wire encode+decode; the
            # per-block shard.encode span is the same segment the real
            # transport records around its quantized chunk encodes.
            tr = obs_trace.get_tracer()
            te = time.monotonic() if tr.enabled else 0.0
            part = self.codec.roundtrip(np.asarray(part, np.float32))
            if tr.enabled:
                tr.record_span(
                    "shard.encode", te, time.monotonic(),
                    attrs={"rank": rank, "step": step_no,
                           "stage": stage, "block": block,
                           "codec": self.codec.name})
        # Cells key on the BLOCK too: the overlapped schedule runs one
        # collective per (stage, block) and every rank issues them in
        # the same order, so block-keyed cells are what keeps a rank's
        # block-1 deposit from polluting a peer's block-0 reduce.
        key = (gen, step_no, stage, block)
        deadline = time.monotonic() + self.timeout_s
        with self._lock:
            if gen in self._poisoned:
                raise self._poisoned[gen]
            cell = self._cells.setdefault(key,
                                          {"parts": {}, "left": 0})
            cell["parts"][rank] = part
            cell["left"] += 1
            self._ready.notify_all()
            while len(cell["parts"]) < self.world:
                if gen in self._poisoned:
                    raise self._poisoned[gen]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    missing = [r for r in range(self.world)
                               if r not in cell["parts"]]
                    raise ShardCollectiveStall(
                        f"rank {rank}: peers {missing} never "
                        f"deposited for step {step_no} stage {stage} "
                        f"within {self.timeout_s}s", rank=rank)
                self._ready.wait(remaining)
            # Rank-ordered sum: every shard computes the IDENTICAL
            # float result, so the replicated states stay equal.
            parts = cell["parts"]
            total = parts[0].astype(np.float32, copy=True)
            for r in range(1, self.world):
                total = total + parts[r]
            cell["left"] -= 1
            if cell["left"] == 0 and len(parts) == self.world:
                # Last leaver only: an early leaver deleting the cell
                # would strand slower ranks re-creating it half-full.
                self._cells.pop(key, None)
        if self.cost_s:
            self._charge_wire(self.cost_s * cost_frac)
        return total

    def _charge_wire(self, cost: float) -> None:
        """Modelled wire time as BUSY-TIME accounting, not independent
        sleeps: each charge extends a per-thread deadline from the
        previous charge's scheduled end (or now, after an idle gap)
        and sleeps to it. Back-to-back block reduces therefore cost
        their SUM plus one sleep quantum — with independent sleeps,
        the ~0.5 ms kernel overshoot per sleep() multiplies by the
        block count and the overlapped schedule would be billed fake
        wire time the real transport never pays."""
        clock = self._wire_clock
        now = time.monotonic()
        deadline = max(getattr(clock, "deadline", 0.0), now) + cost
        clock.deadline = deadline
        if deadline > now:
            time.sleep(deadline - now)


class ReduceTicket:
    """One in-flight overlapped block reduce: the compute thread's
    wait handle against its shard's reducer thread."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class GuardedReducer:
    """The overlap schedule's collective thread, ONE copy for every
    backend (the synthetic shard plane here, the real shard worker's
    ring): a FIFO of (ticket, payload) drained by ``fn(payload)``,
    with the _GuardedWorker discipline — every failure lands in the
    owning ticket's ``error`` and the thread never dies silently;
    ``stop()`` is the None sentinel; ``thread`` is exposed so a
    waiter can bound on liveness (a dead reducer can never set
    another event)."""

    def __init__(self, fn, name: str = "reducer"):
        self.fn = fn
        self.q: _queue.Queue = _queue.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=name)
        self.thread.start()

    def _run(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                return
            ticket, payload = item
            try:
                ticket.value = self.fn(payload)
            except BaseException as e:
                ticket.error = e
            ticket.event.set()

    def submit(self, payload) -> ReduceTicket:
        ticket = ReduceTicket()
        self.q.put((ticket, payload))
        return ticket

    def stop(self) -> None:
        self.q.put(None)


class _Shard:
    """One shard worker thread: FIFO over its own queue, guarded like
    _GuardedWorker — an exception lands in the step handle (and
    poisons the board generation), never kills the thread. In overlap
    mode a SECOND thread per shard (the reducer) drains block reduces
    off a FIFO so the compute thread's next-block partial runs while
    the previous block sits at the board — the in-process model of
    the shard worker's collective thread."""

    def __init__(self, owner: "SyntheticShardSet", rank: int,
                 gen: int):
        self.owner = owner
        self.rank = rank
        self.gen = gen
        self.slice: ShardSlice = owner._make_slice(rank)
        self.x = np.zeros((owner.slots, owner.d), np.float32)
        self.q: _queue.Queue = _queue.Queue()
        self._reducer: Optional[GuardedReducer] = None
        if owner.overlap:
            self._reducer = GuardedReducer(
                self._board_reduce, name=f"shard{rank}-red-g{gen}")
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"shard{rank}-g{gen}")
        self.thread.start()

    def _board_reduce(self, payload):
        step_no, stage, block, part, frac = payload
        return self.owner.board.reduce(
            self.gen, step_no, stage, self.rank, part,
            block=block, cost_frac=frac)

    def _run(self) -> None:
        owner, rank = self.owner, self.rank
        lo, hi = owner.segments[rank]
        while True:
            item = self.q.get()
            if item is None:
                return
            handle: _StepHandle = item
            if handle.gen != self.gen:
                # A stale item from before a reset raced onto this
                # queue: the handle was already aborted — ignore.
                continue
            # Per-step shard spans (ISSUE 11): the compute span's id
            # is RESERVED up front so the reduce segments can parent
            # on it before it is recorded (it closes at step end) —
            # the same reserve-then-record pattern the coordinator
            # uses for shard.step. Same taxonomy as the real shard
            # worker, so synthetic-vs-subprocess traces compare.
            tr = obs_trace.get_tracer()
            traced = tr.enabled
            sid = tr.reserve_id() if traced else None
            # t0 binds BEFORE the try: the except handler records the
            # failed step's span from it (the GL003 discipline).
            t0 = time.monotonic()
            try:
                if owner.fault_site is not None:
                    faults.fire(f"{owner.fault_site}{rank}.step",
                                attrs={"rank": rank,
                                       "step": handle.step_no})
                for i, row in handle._updates:  # type: ignore[attr-defined]
                    self.x[i] = row
                coll = [0.0]
                if owner.overlap:
                    self.x, tokens = self._step_overlapped(
                        handle, coll, tr, sid)
                else:
                    if owner.step_time_s[rank]:
                        time.sleep(owner.step_time_s[rank])

                    def reduce_fn(part, stage,
                                  _h=handle, _c=coll):
                        t = time.monotonic()
                        try:
                            out = owner.board.reduce(
                                self.gen, _h.step_no, stage, rank,
                                part)
                        except BaseException as e:
                            # The peer-side evidence of a sick ring
                            # member: how long THIS rank sat in the
                            # reduce before the poison/stall surfaced.
                            if traced:
                                tr.record_span(
                                    "shard.reduce_stall", t,
                                    time.monotonic(), parent_id=sid,
                                    attrs={"rank": rank,
                                           "step": _h.step_no,
                                           "stage": stage,
                                           "error": type(e).__name__})
                            raise
                        if traced:
                            tr.record_span(
                                "shard.reduce_blocked", t,
                                time.monotonic(), parent_id=sid,
                                attrs={"rank": rank,
                                       "step": _h.step_no,
                                       "stage": stage})
                        _c[0] += time.monotonic() - t
                        return out

                    self.x, tokens = self.slice.forward(self.x,
                                                        reduce_fn)
                total = time.monotonic() - t0
                if traced:
                    tr.record_span(
                        "shard.compute", t0, time.monotonic(),
                        span_id=sid, parent_id=handle.trace_parent,
                        attrs={"rank": rank, "step": handle.step_no,
                               "compute_s": round(
                                   max(0.0, total - coll[0]), 6),
                               "collective_s": round(coll[0], 6)})
                handle.deliver(
                    rank, tokens[lo:hi],
                    compute_s=max(0.0, total - coll[0]),
                    collective_s=coll[0],
                    state=(self.x.copy()
                           if handle.want_state and rank == 0
                           else None))
            except BaseException as e:
                if traced:
                    tr.record_span(
                        "shard.compute", t0, time.monotonic(),
                        span_id=sid, parent_id=handle.trace_parent,
                        attrs={"rank": rank, "step": handle.step_no,
                               "error": type(e).__name__})
                if isinstance(e, ShardError):
                    typed = e
                else:
                    # Wrap: the owner's collect() must raise the
                    # shard plane's typed error naming the origin
                    # rank, with the real failure chained.
                    typed = ShardStepError(
                        f"shard {rank} step failed: {e!r}", rank=rank)
                    typed.__cause__ = e
                # Poison FIRST: peers blocked in the reduce must fail
                # fast with the origin error, not a generic stall.
                owner.board.poison(self.gen, typed)
                handle.deliver_error(rank, typed)

    def _step_overlapped(self, handle: "_StepHandle", coll, tr, sid):
        """One step through forward_overlapped: block reduces queue to
        the reducer thread (submit returns immediately), the modelled
        compute cost rides INSIDE each block partial, and collective_s
        counts only the time the compute thread actually BLOCKED in
        wait — the non-hidden remainder, which is the number overlap
        exists to shrink."""
        owner, rank = self.owner, self.rank
        n_blocks = max(1, min(owner.overlap_blocks, owner.slots))
        stages = max(1, self.slice.stages)
        per_partial = owner.step_time_s[rank] / (stages * n_blocks)
        full = float(owner.slots * owner.d)
        wait_ceiling = owner.board.timeout_s + 5.0

        def submit(part, stage, block, _h=handle):
            return self._reducer.submit(
                (_h.step_no, stage, block, part,
                 part.size / full if full else 1.0))

        traced = tr.enabled

        def wait(t, _c=coll):
            t0 = time.monotonic()
            if not t.event.wait(wait_ceiling):
                if traced:
                    tr.record_span(
                        "shard.reduce_stall", t0, time.monotonic(),
                        parent_id=sid,
                        attrs={"rank": rank, "step": handle.step_no,
                               "error": "ShardCollectiveStall"})
                raise ShardCollectiveStall(
                    f"rank {rank}: overlapped reduce never settled "
                    f"within {wait_ceiling}s", rank=rank)
            _c[0] += time.monotonic() - t0
            if t.error is not None:
                if traced:
                    tr.record_span(
                        "shard.reduce_stall", t0, time.monotonic(),
                        parent_id=sid,
                        attrs={"rank": rank, "step": handle.step_no,
                               "error": type(t.error).__name__})
                raise t.error
            if traced:
                tr.record_span(
                    "shard.reduce_blocked", t0, time.monotonic(),
                    parent_id=sid,
                    attrs={"rank": rank, "step": handle.step_no})
            return t.value

        # Compute cost as busy-time accounting too (same reasoning as
        # _charge_wire: per-block sleeps must cost their sum, not
        # sum + a kernel overshoot per block).
        comp_clock = [0.0]

        def pf(xb, stage):
            if per_partial:
                now = time.monotonic()
                deadline = max(comp_clock[0], now) + per_partial
                comp_clock[0] = deadline
                if deadline > now:
                    time.sleep(deadline - now)
            return self.slice.partial(xb, stage)

        return self.slice.forward_overlapped(
            self.x, submit, wait, blocks=n_blocks, partial_fn=pf)

    def stop(self) -> None:
        self.q.put(None)
        if self._reducer is not None:
            self._reducer.stop()


def _per_rank(value: Union[float, Sequence[float]],
              world: int) -> List[float]:
    if isinstance(value, (int, float)):
        return [float(value)] * world
    vals = [float(v) for v in value]
    if len(vals) != world:
        raise ValueError(f"need {world} per-rank values, got "
                         f"{len(vals)}")
    return vals


class SyntheticShardSet:
    """N in-process shard threads behind the ShardSet contract the
    FabricExecutor drives (``reset`` / ``submit(step, updates,
    want_state)→handle`` / ``collect(handle, timeout)→StepOutput`` /
    ``close``). With ``params`` (train_step.init_params layout, E=1)
    the shards run the REAL model math tensor-parallel — the tier-1
    stand-in for jitted fabric workers; without, the SyntheticExecutor
    double with dialable costs."""

    def __init__(self, world: int, slots: int, d: int = 16, *,
                 params: Optional[dict] = None, seed: int = 0,
                 step_time_s: Union[float, Sequence[float]] = 0.0,
                 collective_time_s: float = 0.0,
                 collective_timeout_s: float = 5.0,
                 fault_site: Optional[str] = None,
                 overlap: bool = False, overlap_blocks: int = 2,
                 codec: Optional[str] = None):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = world
        self.slots = slots
        self.params = params
        self.seed = seed
        self.d = (int(np.asarray(params["w1"]).shape[1])
                  if params is not None else d)
        self.step_time_s = _per_rank(step_time_s, world)
        self.collective_time_s = collective_time_s
        self.fault_site = fault_site
        # Overlap (ISSUE 9): forward_overlapped's double-buffered
        # block schedule with a reducer thread per shard. Codec: the
        # transport's quantized-collective rounding, modelled at the
        # board (opt-in, exactly like the RingTransport knob).
        self.overlap = bool(overlap)
        self.overlap_blocks = max(1, int(overlap_blocks))
        self.codec = quantize.get_codec(codec)
        self.codec_name = self.codec.name if self.codec else "fp32"
        self.segments = segment_bounds(slots, world)
        self.board = _ReduceBoard(world, collective_time_s,
                                  collective_timeout_s,
                                  codec=self.codec)
        self._gen = 0
        self._lock = threading.Lock()
        self._shards: List[_Shard] = []
        self._outstanding: set = set()
        self.resets = 0

    # -- slice construction ---------------------------------------------------

    def _make_slice(self, rank: int) -> ShardSlice:
        if self.params is not None:
            return TpShardSlice(self.params, rank, self.world)
        return DoubleShardSlice(self.d, self.seed, rank, self.world)

    # -- lifecycle ------------------------------------------------------------

    def _ensure(self) -> None:
        if not self._shards:
            self._shards = [_Shard(self, r, self._gen)
                            for r in range(self.world)]

    def reset(self) -> None:
        """Tear down this decode session and re-rendezvous: bump the
        generation (stale deposits and late-waking hung threads can
        never touch the new session), abort every outstanding handle,
        abandon the old shard threads (a HUNG shard cannot be joined
        — it is left to die on its poison pill) and spawn fresh ones
        with zeroed state."""
        with self._lock:
            old_gen = self._gen
            self._gen += 1
            old = self._shards
            self._shards = []
            outstanding = list(self._outstanding)
        abort = ShardAborted(
            f"shard set reset (generation {old_gen} torn down)")
        self.board.poison(old_gen, abort)
        for h in outstanding:
            for r, ev in enumerate(h.events):
                if not ev.is_set():
                    h.deliver_error(r, abort)
        for sh in old:
            sh.stop()
        with self._lock:
            # Aborted handles are SETTLED, not leaked: discard exactly
            # the snapshot (never clear() — a handle submitted
            # concurrently with this reset must stay on the ledger
            # until collected or aborted, or outstanding() could hide
            # a real leak).
            self._outstanding.difference_update(outstanding)
            self._ensure()
            self.resets += 1

    def close(self) -> None:
        with self._lock:
            old = self._shards
            self._shards = []
            gen = self._gen
            outstanding = list(self._outstanding)
        abort = ShardAborted("shard set closed")
        self.board.poison(gen, abort)
        for h in outstanding:
            for r, ev in enumerate(h.events):
                if not ev.is_set():
                    h.deliver_error(r, abort)
        for sh in old:
            sh.stop()
        with self._lock:
            # Same discipline as reset(): only the handles this close
            # actually aborted leave the ledger, so the chaos
            # teardowns' outstanding() == 0 assertion stays a REAL
            # invariant (an un-aborted in-flight step survives it).
            self._outstanding.difference_update(outstanding)

    def live_shards(self) -> int:
        with self._lock:
            return sum(1 for sh in self._shards
                       if sh.thread.is_alive())

    def outstanding(self) -> int:
        """Submitted steps not yet collected — the shard plane's leak
        ledger (chaos teardowns assert 0 after close)."""
        with self._lock:
            return len(self._outstanding)

    # -- the step plane -------------------------------------------------------

    def submit(self, step_no: int, updates: Sequence,
               want_state: bool = False,
               trace_parent=None) -> _StepHandle:
        with self._lock:
            self._ensure()
            handle = _StepHandle(self._gen, step_no, self.world,
                                 want_state,
                                 trace_parent=trace_parent)
            # Rows are copied at apply time; the handle only carries
            # the references across the queue hop.
            handle._updates = [(int(i), np.asarray(row, np.float32))
                               for i, row in updates]
            self._outstanding.add(handle)
            shards = list(self._shards)
        for sh in shards:
            sh.q.put(handle)
        return handle

    def collect(self, handle: _StepHandle,
                timeout: float) -> StepOutput:
        deadline = time.monotonic() + timeout
        try:
            for r, ev in enumerate(handle.events):
                if not ev.wait(max(0.0, deadline - time.monotonic())):
                    raise ShardTimeout(
                        f"shard {r} never replied to step "
                        f"{handle.step_no} within {timeout}s", rank=r)
            for r, err in enumerate(handle.errors):
                if err is not None:
                    raise err
            tokens = np.empty((self.slots,), np.int32)
            for r, (lo, hi) in enumerate(self.segments):
                tokens[lo:hi] = handle.tokens[r]
            return StepOutput(tokens, handle.state,
                              list(handle.compute_s),
                              list(handle.collective_s))
        finally:
            with self._lock:
                self._outstanding.discard(handle)
