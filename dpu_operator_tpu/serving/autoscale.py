"""RoleAutoscaler — live prefill:decode ratio control (ISSUE 20).

A disaggregated pool fixes its prefill:decode split at construction,
but the workload does not hold still: a prompt-heavy burst starves the
front (prefill) queue while decode replicas idle, and a long-decode
phase does the opposite. This controller retunes the ratio LIVE by
moving replicas between the two role pools — the executor object
(allocator, prefix tree, host tier, resident pages) survives the move;
only the batcher is rebuilt with the destination pool's kwargs, which
is exactly what a "role" is in this codebase (prefill batchers carry
the handoff hook, decode batchers do not).

Signals, all already exported by the serving plane:

  * prefill pressure — the front/admission queue depth (the front
    queue IS the prefill queue in the disagg topology);
  * decode pressure — decode queue depth + transfer backlog (pages
    enqueued or in flight prefill->decode: each is a decode admission
    the decode pool has not absorbed yet);
  * host-gap dampener — the decode pool's serving_host_gap share
    (host_gap / (host_gap + device)). When decode steps are dominated
    by host bookkeeping rather than device time, decode is not
    capacity-bound and a prefill->decode flip buys nothing — the
    controller skips it and counts the dampened tick instead.

Discipline: `hysteresis` consecutive one-sided ticks before any flip,
plus a `cooldown_s` dead time after each — a flip requeues in-flight
work (exactly once, no `attempts` burn), so flapping is strictly worse
than either steady state. Pools never drop below one live replica per
role.

Scale-to-zero reuses the breaker's PARKED state (PR 5): after
`idle_park_s` of zero pressure and zero active work, surplus replicas
park one per tick down to `min_live`; the first tick of returning
pressure unparks them one per tick, in LIFO order. Only replicas THIS
controller parked are ever unparked — a breaker-parked (crash-looping)
replica stays parked.

Every decision is driven through `tick()`, which is public and
thread-free so tests can step the controller deterministically;
`start()` merely runs `tick()` on a timer thread.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from ..obs import trace as obs_trace

log = logging.getLogger(__name__)

__all__ = ["RoleAutoscaler"]


class RoleAutoscaler:
    """Queue-depth / transfer-backlog / host-gap driven controller
    over a DisaggPool: role flips, plus park-to-zero on idle."""

    def __init__(self, pool, registry=None, *,
                 interval_s: float = 0.05,
                 flip_margin: int = 4,
                 hysteresis: int = 3,
                 cooldown_s: float = 1.0,
                 host_gap_ceiling: float = 0.9,
                 idle_park_s: Optional[float] = None,
                 min_live: int = 1,
                 tracer=None):
        self.pool = pool
        self.registry = registry
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        self.interval_s = float(interval_s)
        self.flip_margin = max(1, int(flip_margin))
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)
        self.host_gap_ceiling = float(host_gap_ceiling)
        self.idle_park_s = idle_park_s
        self.min_live = max(1, int(min_live))
        # Signed streak of one-sided pressure ticks: positive runs
        # argue decode->prefill, negative runs prefill->decode.
        self._streak = 0
        self._last_flip = float("-inf")
        self._idle_since: Optional[float] = None
        # (pool, replica name) parks THIS controller made, LIFO.
        self._parked: List[Tuple[object, str]] = []
        self.flips = 0
        self.parks = 0
        self.unparks = 0
        self.dampened = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="role-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # The controller is an optimizer, not a dependency: a
                # bad tick must cost one interval, never the thread.
                log.exception("role autoscaler: tick failed")
            self._stop.wait(self.interval_s)

    # -- signals --------------------------------------------------------------

    def pressures(self) -> Tuple[int, int]:
        """(prefill, decode) pressure right now."""
        prefill = int(self.pool.queue.depth())
        decode = int(self.pool.decode_queue.depth()
                     + self.pool.transfer_backlog())
        return prefill, decode

    def decode_host_gap_fraction(self) -> Optional[float]:
        """Aggregate host-gap share of the decode pool's step wall —
        None until the pool has stepped (no signal is not a veto)."""
        if self.registry is None:
            return None
        prefix = self.pool.decode_pool.name_prefix
        device = self.registry.histogram_totals(
            "serving_step_device_seconds")
        gap_sum = dev_sum = 0.0
        for key, (s, _n) in self.registry.histogram_totals(
                "serving_host_gap_seconds").items():
            labels = dict(key)
            if not str(labels.get("replica", "")).startswith(prefix):
                continue
            gap_sum += s
            dev_sum += device.get(key, (0.0, 0))[0]
        total = gap_sum + dev_sum
        if total <= 0:
            return None
        return gap_sum / total

    # -- the control loop ------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control decision. Returns the action taken
        ("flip_to_prefill" | "flip_to_decode" | "park" | "unpark" |
        None) — the deterministic seam the tests drive."""
        if now is None:
            now = time.monotonic()
        prefill, decode = self.pressures()
        self._publish(prefill, decode)

        # Scale-from-zero first: parked capacity is useless capacity
        # the moment there is pressure.
        if (prefill + decode) > 0 and self._parked:
            if self._unpark_one():
                self._idle_since = None
                return "unpark"

        skew = prefill - decode
        if skew >= self.flip_margin:
            self._streak = max(1, self._streak + 1)
        elif -skew >= self.flip_margin:
            self._streak = min(-1, self._streak - 1)
        else:
            self._streak = 0

        if abs(self._streak) >= self.hysteresis \
                and now - self._last_flip >= self.cooldown_s:
            if self._streak > 0:
                # Prefill-starved: borrow a decode replica.
                if self.pool.flip_role("decode") is not None:
                    return self._flipped(now, "flip_to_prefill")
            else:
                # Decode-starved — unless decode is host-bound, in
                # which case another decode replica just adds another
                # python loop to the same wall.
                frac = self.decode_host_gap_fraction()
                if frac is not None and frac > self.host_gap_ceiling:
                    self.dampened += 1
                    self._count("serving_autoscale_dampened_total",
                                {"reason": "host_gap"},
                                help="prefill->decode flips skipped "
                                     "because decode is host-bound, "
                                     "not capacity-bound")
                    self._streak = 0
                elif self.pool.flip_role("prefill") is not None:
                    return self._flipped(now, "flip_to_decode")

        # Park-to-zero bookkeeping.
        if self.idle_park_s is not None:
            idle = (prefill + decode) == 0 and self.pool.active() == 0
            if not idle:
                self._idle_since = None
            elif self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since >= self.idle_park_s:
                if self._park_one():
                    return "park"
        return None

    def _flipped(self, now: float, action: str) -> str:
        self.flips += 1
        self._last_flip = now
        self._streak = 0
        self._idle_since = None
        self._count("serving_autoscale_flips_total", {"action": action},
                    help="role flips committed by the autoscaler")
        return action

    # -- park / unpark ---------------------------------------------------------

    def _park_one(self) -> bool:
        # Prefill surplus parks first: with zero pressure the front
        # door sees new work before the decode plane does, and
        # unparking is LIFO, so the replica that wakes first is the
        # one the first new request needs.
        for p in (self.pool.prefill_pool, self.pool.decode_pool):
            name = p.park_replica(min_live=self.min_live)
            if name is not None:
                self._parked.append((p, name))
                self.parks += 1
                self._count("serving_autoscale_parks_total",
                            {"action": "park"},
                            help="scale-to-zero parks and unparks by "
                                 "the role autoscaler")
                return True
        return False

    def _unpark_one(self) -> bool:
        while self._parked:
            p, name = self._parked.pop()
            try:
                i = p._names.index(name)
            except ValueError:
                continue  # detached since (role flip); nothing to wake
            if p.unpark_replica(i) is not None:
                self.unparks += 1
                self._count("serving_autoscale_parks_total",
                            {"action": "unpark"},
                            help="scale-to-zero parks and unparks by "
                                 "the role autoscaler")
                return True
        return False

    # -- observability ---------------------------------------------------------

    def _count(self, name: str, labels: dict, help: str = "") -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, labels, help=help)

    def _publish(self, prefill: int, decode: int) -> None:
        if self.registry is None:
            return
        self.registry.gauge_set(
            "serving_autoscale_pressure", float(prefill),
            {"role": "prefill"},
            help="autoscaler pressure signal per role (queue depth; "
                 "decode adds transfer backlog)")
        self.registry.gauge_set(
            "serving_autoscale_pressure", float(decode),
            {"role": "decode"},
            help="autoscaler pressure signal per role (queue depth; "
                 "decode adds transfer backlog)")
        self.registry.gauge_set(
            "serving_autoscale_replicas",
            float(self.pool.prefill_pool.live_count()),
            {"role": "prefill"},
            help="live replicas per role as the autoscaler last "
                 "observed them")
        self.registry.gauge_set(
            "serving_autoscale_replicas",
            float(self.pool.decode_pool.live_count()),
            {"role": "decode"},
            help="live replicas per role as the autoscaler last "
                 "observed them")
