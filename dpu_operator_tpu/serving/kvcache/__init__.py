"""Paged KV-cache decode (ISSUE 7): device-resident attention state,
prefix reuse, chunked prefill.

Three layers (PagedAttention / Sarathi-Serve, sized to this repo):

  * host plane — allocator.py: fixed-size KV blocks with refcounts,
    owner-tagged leak accounting, per-request ``KVLease`` block tables
    that ride the PR 5 seize→requeue path, and a chained-hash
    ``PrefixTree`` for block-granular prefix sharing;
  * device plane — paged.py: one AOT-compiled fused step (embed →
    KV-append → paged attention → logits → argmax) over
    ``[num_blocks, block_size, heads, d_head]`` pools that never
    leave the device. Since ISSUE 13 the resident format is int8
    codes + per-block scales (4x context per HBM byte) and the
    attention+append core is selectable: the fused Pallas kernel
    (parallel/pallas_paged_attn.py — one launch per step, online
    softmax, HBM→VMEM page DMA) or the XLA reference composition
    (``kernel="pallas" | "xla"``);
  * executors — executor.py: ``PagedKVExecutor`` (real, jax) and
    ``SyntheticKVExecutor`` (jax-free, dialable step cost) behind the
    serving plane's two-phase submit/collect seam, with chunked
    prefill planned per step under a decode-protecting token budget.

Importing this package stays jax-free; jax loads only when a
PagedKVExecutor is constructed (the serving/__init__ discipline).
"""

from .allocator import (CACHE_OWNER, KVBlockAllocator, KVCacheOOM,
                        KVLease, PrefixTree)
from .executor import (NO_TOKEN, KVExecutorBase, PagedKVExecutor,
                       SyntheticKVExecutor)
from .paged import kv_bytes_per_slot, paged_kv_error_bound
from .sharded import (KVShardProcessSet, ShardedPagedKVExecutor,
                      SyntheticKVShardSet, resolve_shard_axis)
from .tiering import HostKVTier, ParkedKV, verify_block_tokens

__all__ = [
    "CACHE_OWNER",
    "HostKVTier",
    "KVBlockAllocator",
    "KVCacheOOM",
    "KVExecutorBase",
    "KVLease",
    "KVShardProcessSet",
    "NO_TOKEN",
    "PagedKVExecutor",
    "ParkedKV",
    "PrefixTree",
    "ShardedPagedKVExecutor",
    "SyntheticKVExecutor",
    "SyntheticKVShardSet",
    "kv_bytes_per_slot",
    "paged_kv_error_bound",
    "resolve_shard_axis",
    "verify_block_tokens",
]
