"""Token-level executors over the paged KV cache.

``KVExecutorBase`` is the host plane shared by every KV replica: it
owns the block allocator + prefix tree, the per-slot decode cursors,
and the per-step PLAN — which slots prefill how many prompt tokens
this step (bounded by the Sarathi-style ``prefill_budget``), which
slots decode one token, and whether each decode input chains from the
previous step's on-device output or is host-fed (fresh attach /
resume). Backends implement exactly two hooks — ``_dispatch(plan)``
and ``_materialize(raw)`` — so the scheduler-facing contract is one
class:

  * ``PagedKVExecutor`` — the real thing: kvcache/paged.py's
    AOT-compiled fused step over device-resident KV pools, decode
    recurrence chained on device (submit returns while the step runs).
  * ``SyntheticKVExecutor`` — the jax-free double: same allocator,
    same leases, same plans, but the "device" is a deterministic token
    function with a dialable step cost (optionally on a worker thread,
    the SyntheticExecutor pipelining idiom) — the knob that makes KV
    scheduler/chaos tests immune to CI-box noise.

Scheduling properties the plan enforces (the chunked-prefill
contract):

  * decode slots ALWAYS get their one token — the prefill budget only
    rations prefill, so a long prompt can never stall decode p99;
  * prefill is chunked to ``prefill_chunk`` tokens per slot and
    ``prefill_budget`` per step across slots, admitted round-robin
    from a rotating start so one long prompt cannot starve another;
  * every request's worst-case pages (``ceil((prompt + max_tokens) /
    block_size)``) are reserved at attach — KV OOM is an ADMISSION
    decision (shed with 503), never a mid-decode failure.

Crash-retry (the ISSUE 7 headline): cursors are rebuilt from
``req.tokens`` at (re-)attach — see KVLease — so a seized request
re-attaches its pages and resumes from its last settled token. A
lease from a DIFFERENT executor is released and the request re-prefills
from the prompt (possibly through this replica's own prefix cache).

Thread-safety: all slot-state mutation happens under ``_slock`` with a
generation check, so a batcher thread abandoned mid-dispatch by a
supervisor seize can never advance cursors of a restarted session
(its stale ``gen`` turns the submit into a no-op).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ... import faults
from ..executor import Executor, _GuardedWorker
# NO_TOKEN re-exported here for back-compat: the sentinel and the
# emit-masking idiom live in serving/spec.py (ISSUE 15 cleanup) so the
# one-token and speculative collect paths share one definition.
from ..spec import (NO_TOKEN, SpecConfig, accept_tree, clamp_spec_k,
                    propose_full, synthetic_next_token)
from .allocator import (_ROOT as _TREE_ROOT, KVBlockAllocator,
                        KVCacheOOM, KVLease, PrefixTree)
from .tiering import HostKVTier, ParkedKV, verify_block_tokens

log = logging.getLogger(__name__)


class _SlotState:
    __slots__ = ("req_id", "lease", "ctx", "prefill_pos", "last_token",
                 "chain_device", "pending_emit", "confirmed",
                 "max_total", "spec_ahead", "spec_epoch", "spec_ewma",
                 "repair")

    def __init__(self, req_id: str, lease: KVLease, ctx: int,
                 prefill_pos: int, last_token: Optional[int],
                 max_total: int = 0):
        self.req_id = req_id
        self.lease = lease
        self.ctx = int(ctx)
        self.prefill_pos = int(prefill_pos)
        self.last_token = last_token
        self.chain_device = False
        self.pending_emit = False
        # Pipelined speculation (ISSUE 18): the draft's own prediction
        # of the in-flight verify window's BONUS token — the seed for
        # planning window w+1 before window w collects. The true bonus
        # chains on DEVICE (the window's base row is use_host=False);
        # this host-side prediction only feeds the draft.
        self.spec_ahead: Optional[int] = None
        # Plan-ahead validity epoch: bumped by every rollback at
        # collect, recorded into each spec plan — a collected plan
        # whose epoch is stale was drafted from a provisional ctx a
        # rollback revoked, and settles NOTHING (a pure re-plan).
        self.spec_epoch = 0
        # Per-slot accept-rate EWMA, the adaptive draft-depth dial
        # (SpecConfig.k_for/width_for). Starts optimistic: a fresh
        # slot drafts at full depth until the target disagrees.
        self.spec_ewma = 1.0
        # Tree speculation: accepted tokens whose KV row was NOT
        # appended (a sibling path won — the trunk's append at that
        # position holds the rejected trunk token). The next window
        # re-feeds them as leading repair rows, closing the hole
        # before any later query can attend it.
        self.repair: List[int] = []
        # Positions whose KV writes a COLLECTED step has confirmed on
        # device. ctx advances at plan time — one step ahead in the
        # pipelined loop, and a full speculative window ahead in
        # verify steps — so anything derived from ctx alone (the
        # prefix-cache insert) would cover in-flight writes that a
        # failing step never lands, or rejected draft positions a
        # collect rolls back. Attach-time positions are genuinely
        # written: prefix-cache hits by the cache contract, re-attach
        # cursors by the settled tokens that imply their steps ran.
        self.confirmed = int(ctx)
        # prompt + max_tokens: the request's total position budget,
        # needed at plan time to clamp speculative proposals inside
        # the worst-case pages reserved at admission (spec.clamp_spec_k).
        self.max_total = int(max_total)


class _StepPlan:
    __slots__ = ("gen", "step_no", "host_tok", "use_host", "ctx",
                 "n_new", "tables", "emit", "owners", "spec_k",
                 "stale", "spec_off", "spec_w", "spec_epoch", "n_app",
                 "roff", "plim", "win")

    def __init__(self, gen, step_no, host_tok, use_host, ctx, n_new,
                 tables, emit, owners=None, spec_k=None, stale=False,
                 spec_off=None, spec_w=None, spec_epoch=None,
                 n_app=None, roff=None, plim=None, win=None):
        self.gen = gen
        self.step_no = step_no
        self.host_tok = host_tok
        self.use_host = use_host
        self.ctx = ctx
        self.n_new = n_new
        self.tables = tables
        self.emit = emit
        # Per-slot request id at PLAN time: collect() must attribute
        # an emit to the state that planned it — a retire + fresh
        # admit can rebind the slot between submit and collect.
        self.owners = owners
        # Speculative plans only: per-slot drafted-token count (>= 0
        # marks a verify slot; the drafts themselves are
        # host_tok[s, spec_off[s]+1 : spec_off[s]+1+spec_k[s]], so
        # collect can re-derive the acceptance comparison from the
        # plan alone).
        self.spec_k = spec_k
        self.stale = stale
        # Tree/pipelined speculation (ISSUE 18). Window row layout per
        # verify slot: [repair rows (spec_off), base row, trunk rows
        # (spec_k), sibling rows (spec_w)] — the first n_app rows
        # APPEND KV at positions ctx..ctx+n_app-1; sibling rows score
        # only. spec_epoch snapshots the slot's rollback epoch at plan
        # time (stale epoch at collect = invalidated plan-ahead).
        self.spec_off = spec_off
        self.spec_w = spec_w
        self.spec_epoch = spec_epoch
        self.n_app = n_app
        # Tree-step geometry (None unless tree_width > 1): per-row
        # position offset (pos = ctx + roff — siblings share the first
        # trunk position), per-row POOL attention limit (tpos < plim:
        # appended rows include their own scattered position,
        # score-only rows stop at their deepest appended ancestor),
        # and the in-window tree-causal mask win[s, i, j] (row i
        # attends row j's freshly computed K/V — our depth-1 sibling
        # topology only needs the sibling diagonal: a sibling's
        # ancestors are all appended, so only its SELF attention is
        # missing from the pool).
        self.roff = roff
        self.plim = plim
        self.win = win


class _KVHandle:
    __slots__ = ("plan", "raw")

    def __init__(self, plan: _StepPlan, raw):
        self.plan = plan
        self.raw = raw


class KVExecutorBase(Executor):
    kv = True
    #: no prompt_vec plane: KV replicas consume token ids.
    d = 0

    def __init__(self, slots: int, vocab: int = 64, block_size: int = 4,
                 num_blocks: int = 128, max_blocks_per_req: int = 16,
                 prefill_chunk: int = 8,
                 prefill_budget: Optional[int] = None,
                 prefix_cache: bool = True, pipelined: bool = True,
                 spec: Optional[SpecConfig] = None,
                 host_tier_bytes: Optional[int] = None):
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        self.slots = int(slots)
        self.vocab = int(vocab)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_req = int(max_blocks_per_req)
        self.max_context = self.max_blocks_per_req * self.block_size
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_budget = int(prefill_budget
                                  if prefill_budget is not None
                                  else prefill_chunk)
        if self.prefill_budget < 1:
            raise ValueError("prefill_budget must be >= 1")
        self.pipelined = bool(pipelined)
        self.allocator = KVBlockAllocator(self.num_blocks,
                                          self.block_size)
        self.prefix: Optional[PrefixTree] = (
            PrefixTree(self.allocator) if prefix_cache else None)
        # Host-RAM KV tier (ISSUE 17): opt-in via a byte budget. The
        # tree's LRU leaf eviction becomes evict-to-tier, and attach
        # extends a prefix hit past the HBM chain by restoring spilled
        # blocks (chained-hash re-verified, see tiering.py).
        self.tier: Optional[HostKVTier] = None
        if host_tier_bytes is not None and self.prefix is not None:
            self.tier = HostKVTier(host_tier_bytes)
            self.prefix.spill_hook = self._spill_block
        self._exec_id = f"kvexec-{id(self):x}"
        self._slock = threading.RLock()
        self._states: List[Optional[_SlotState]] = [None] * self.slots
        self._gen = 0
        self._rr = 0
        self._step_no = 0
        # Token-denominated counters for the serving_prefill/decode_
        # tokens_total series and the bench's prefill-stall fraction.
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.steps_decode = 0
        self.steps_mixed = 0
        self.resumed_total = 0
        # KV-aware preemption (ISSUE 20): victims parked / resumed.
        self.preempted_total = 0
        self.preempt_resumed_total = 0
        self.spec: Optional[SpecConfig] = None
        self._spec_inflight = 0  # spec windows submitted, uncollected
        if spec is not None:
            self._install_spec(spec)

    def _install_spec(self, spec: SpecConfig) -> None:
        """Arm speculative decoding. Must run before the first
        submit. Structural constraints, checked here once: the verify
        window rides the compiled chunk width (``k + 1 <=
        prefill_chunk``), with room for the sibling rows and one
        repair row when the draft is a tree.

        Since ISSUE 18 speculation composes with BOTH loop shapes.
        The sync shape is PR 15 verbatim: collect-before-plan, every
        window drafted from the previous step's accepted length. The
        pipelined shape drafts window w+1 while the device still
        verifies window w — from window w's PROPOSED tokens: under
        full acceptance every settled token except the bonus is
        host-known, the bonus chains on DEVICE (the plan-ahead
        window's base row is use_host=False), and the draft continues
        from its own prediction of it (spec.propose_full). A window
        drafted from a provisional ctx that a rollback later revokes
        is invalidated by the slot's epoch (recorded at plan, checked
        at collect) and settles nothing — the existing watermark
        rollback plus a re-plan, no new device state."""
        if spec.k + 1 > self.prefill_chunk:
            raise ValueError(
                f"spec k={spec.k} needs a verify window of k+1 <= "
                f"prefill_chunk={self.prefill_chunk}")
        if spec.tree_width + 1 > self.prefill_chunk:
            raise ValueError(
                f"tree_width={spec.tree_width} needs a verify window "
                f"of width+1 <= prefill_chunk={self.prefill_chunk}")
        self.spec = spec
        self.speculative = True
        self._spec_inflight = 0

    # -- attach / detach (called by the batcher under its settle lock) --------

    def kv_attach(self, slot: int, req) -> int:
        """Bind `req` to `slot`: re-attach its surviving lease (resume
        from the last settled token), or build a fresh one — prefix
        cache hit first, worst-case pages reserved up front. Returns
        the cached-token count (0 on resume/fresh-miss). Raises
        KVCacheOOM (shed) or ValueError (caller bug / over-long
        prompt). Atomic: on failure nothing stays bound or acquired."""
        tokens = getattr(req, "prompt_tokens", None)
        if not tokens:
            raise ValueError(
                f"kv executor needs prompt_tokens (request "
                f"{req.request_id})")
        plen = len(tokens)
        if plen + req.max_tokens > self.max_context:
            raise ValueError(
                f"prompt ({plen}) + max_tokens ({req.max_tokens}) "
                f"exceeds max context {self.max_context} (request "
                f"{req.request_id})")
        with self._slock:
            if self._states[slot] is not None:
                raise ValueError(f"slot {slot} already bound")
            lease = getattr(req, "kv_lease", None)
            if lease is not None and lease.in_transit:
                # The transfer plane owns a detached lease until it
                # acks (attach) or reattaches (failure) — a request
                # reaching admission mid-transfer means two owners.
                raise ValueError(
                    f"request {req.request_id}: lease is mid-transfer "
                    f"(detached, not yet acked)")
            if lease is not None and not lease.released:
                # The released check races the settle choke point
                # (finish() can release from the HTTP handler's thread
                # at ANY time, including right after this line) — and
                # that is fine, by the same argument that makes
                # release-while-bound safe mid-decode: a settled req
                # has req.done set, so _retire_kv evicts the binding at
                # the first retire; at most one in-flight plan scatters
                # into the freed blocks, and a stale write is always
                # overwritten by a block's next owner before it can be
                # attended (device steps execute in dispatch order, and
                # a position is appended by the step that processes it
                # before any later query's causal mask can reach it).
                # Shared prefix blocks are never scatter targets at
                # all — appends land at positions >= the block-aligned
                # cached prefix, in the request's own fresh blocks.
                if isinstance(lease, ParkedKV):
                    if (lease.exec_id == self._exec_id
                            and self.prefix is not None
                            and self.tier is not None):
                        return self._attach_parked(slot, req, lease)
                    # Parked on a different replica (or this one lost
                    # its tier): the pins mean nothing here — return
                    # them and re-prefill; deterministic decode makes
                    # the stream identical either way.
                    lease.release()
                    req.kv_lease = None
                    req.tokens.clear()
                    req.truncated = False
                elif lease.exec_id == self._exec_id:
                    return self._reattach(slot, req, lease)
                else:
                    # Foreign pages mean nothing in this pool: release
                    # them and restart the stream from the prompt (the
                    # deterministic recurrence makes the retried stream
                    # identical either way).
                    lease.release()
                    req.kv_lease = None
                    req.tokens.clear()
                    req.truncated = False
            owner = req.request_id
            cached_blocks: List[int] = []
            cached = 0
            cached_by_tier: dict = {}
            if self.prefix is not None:
                cached_blocks, cached = self.prefix.match_and_fork(
                    tokens, owner, by_tier=cached_by_tier)
                if self.tier is not None:
                    # Continue the hit past the HBM-resident chain:
                    # spilled blocks restore from the host tier
                    # (re-verified) before prefill of the suffix.
                    try:
                        cached = self._extend_from_tier(
                            tokens, owner, cached_blocks, cached,
                            cached_by_tier)
                    except Exception:
                        # Blocks restored before the failure are
                        # already appended to cached_blocks; drop the
                        # whole forked chain (the kv_match_prefix
                        # unwind) so a tier fault can't strand refs.
                        if cached_blocks:
                            self.allocator.release(cached_blocks, owner)
                        raise
            need_total = -(-(plen + req.max_tokens) // self.block_size)
            need = need_total - len(cached_blocks)
            try:
                fresh = self._acquire_with_evict(need, owner)
            except KVCacheOOM:
                if cached_blocks:
                    self.allocator.release(cached_blocks, owner)
                raise
            lease = KVLease(self.allocator, self._exec_id, owner,
                            cached_blocks + fresh, tuple(tokens),
                            cached, cached_by_tier=cached_by_tier)
            req.kv_lease = lease
            self._states[slot] = _SlotState(
                owner, lease, ctx=cached, prefill_pos=cached,
                last_token=None, max_total=plen + req.max_tokens)
            return cached

    def _attach_parked(self, slot: int, req, parked: ParkedKV) -> int:
        """Resume a preempted request from its host-parked KV (called
        under ``_slock`` from kv_attach). The parked chain covers
        prompt + settled tokens up to the preemption's confirmed
        extent, content-addressed exactly like any spilled prefix — so
        resume IS the tier-restore path: match the HBM tree first (the
        preemption's retire hook cached the prompt blocks), then
        restore the pinned suffix chain (chained-hash re-verified),
        then prefill only what neither covered. The final prefill
        position is seq[-1] — the last SETTLED token — whose step emits
        the next unsettled one: no duplicate, no gap, byte-identical to
        the unpreempted stream.

        The pins release only AFTER the fresh lease is built; a
        KVCacheOOM here leaves ``req.kv_lease`` as the ParkedKV, so the
        caller's fail() still settles the pins through finish()."""
        faults.fire("kvpreempt.resume")
        seq = list(parked.prompt) + [int(t) for t in req.tokens]
        plen = len(parked.prompt)
        owner = req.request_id
        cached_by_tier: dict = {}
        cached_blocks, cached = self.prefix.match_and_fork(
            seq, owner, by_tier=cached_by_tier)
        try:
            cached = self._extend_from_tier(
                seq, owner, cached_blocks, cached, cached_by_tier)
        except Exception:
            if cached_blocks:
                self.allocator.release(cached_blocks, owner)
            raise
        # Worst case from the ORIGINAL geometry: plen + max_tokens is
        # what admission reserved, and len(seq) + remaining budget
        # equals it exactly.
        need_total = -(-(plen + req.max_tokens) // self.block_size)
        need = need_total - len(cached_blocks)
        try:
            fresh = self._acquire_with_evict(need, owner)
        except KVCacheOOM:
            if cached_blocks:
                self.allocator.release(cached_blocks, owner)
            raise
        lease = KVLease(self.allocator, self._exec_id, owner,
                        cached_blocks + fresh, tuple(seq),
                        cached, cached_by_tier=cached_by_tier)
        req.kv_lease = lease
        parked.release()
        self._states[slot] = _SlotState(
            owner, lease, ctx=cached, prefill_pos=cached,
            last_token=None, max_total=plen + req.max_tokens)
        self.resumed_total += 1
        self.preempt_resumed_total += 1
        return cached

    def _reattach(self, slot: int, req, lease: KVLease) -> int:
        """Rebuild decode cursors from the request's SETTLED tokens —
        the durable truth a kill between dispatch and settle cannot
        skew. k settled tokens mean prompt + k-1 generated positions
        are (re)appendable; the next step feeds tokens[-1] and emits
        token k+1 — identical to the unfailed stream.

        ctx = plen + k - 1 deliberately treats the LAST settled
        token's own KV position as unwritten, which also covers tree
        speculation's one legal KV hole: a sibling-accepted token was
        verified on a score-only row (never appended) and normally
        healed by the next window's repair row — a kill between the
        sibling accept and that repair collect lands here, and
        re-feeding tokens[-1] re-appends exactly the missing
        position. Any pending st.repair dies with the old slot state;
        the rebuilt cursor needs none."""
        plen = len(lease.prompt)
        k = len(req.tokens)
        if k > 0:
            st = _SlotState(req.request_id, lease,
                            ctx=plen + k - 1, prefill_pos=plen,
                            last_token=int(req.tokens[-1]),
                            max_total=plen + req.max_tokens)
        else:
            # Killed mid-prefill: replay the prefill from the cached
            # prefix (pages already reserved — replay re-appends
            # identical values, overwrites are harmless).
            st = _SlotState(req.request_id, lease,
                            ctx=lease.cached_tokens,
                            prefill_pos=lease.cached_tokens,
                            last_token=None,
                            max_total=plen + req.max_tokens)
        self._states[slot] = st
        self.resumed_total += 1
        return 0

    def _acquire_with_evict(self, n: int, owner: str):
        """Page reservation with the admission eviction policy: on
        OOM, evict LRU prefix-cache leaves to make room; a second OOM
        is the real shed. ONE copy shared by kv_attach and kv_import
        so admission and transfer-import can never diverge on shed
        behavior. Callers own the blocks' way back (lease
        registration or the cached-blocks unwind) — the GL009 pairing
        lives at the call sites, which is why the acquires below are
        individually waived."""
        try:
            # graftlint: disable=GL009
            return self.allocator.acquire(n, owner)
        except KVCacheOOM:
            if self.prefix is None:
                raise
            # Under _slock BEFORE the tree lock: the evict-to-tier
            # spill hook exports pool bytes (which takes _slock on the
            # paged backend), and kv_attach already holds _slock when
            # it matches — one lock order everywhere, no deadlock.
            with self._slock:
                self.prefix.evict(n - self.allocator.free_count())
            # graftlint: disable=GL009
            return self.allocator.acquire(n, owner)

    # -- host tier (ISSUE 17) --------------------------------------------------

    def _spill_block(self, parent_key: str, tokens, key: str,
                     block: int) -> None:
        """PrefixTree evict hook — runs UNDER the tree lock, before
        the victim's cache ref is released, so a concurrent match
        either forked the block live or finds it already parked. The
        bytes move verbatim (the kv_export representation), so a
        later restore is bit-identical to the block being dropped."""
        faults.fire("kvtier.spill")
        planes = self._tier_export_block(block, tokens)
        self.tier.put(key, parent_key, tokens, planes)

    def _extend_from_tier(self, tokens, owner: str,
                          blocks: List[int], cached: int,
                          by_tier: dict) -> int:
        """Walk the prompt's chain past the HBM-matched depth and
        restore each spilled block from the host tier: checkout under
        an owner-tagged tier lease, re-verify the chained hash against
        the tokens THIS request brought (GL019's discipline — a stale
        or corrupted entry degrades to re-prefill, never wrong KV),
        write the bytes into a freshly acquired HBM block, and publish
        it through ``attach_restored`` under the tree lock. Appends
        the restored blocks to `blocks` (owner refs held, same unwind
        as the matched chain) and returns the new cached-token count."""
        bs = self.block_size
        limit = max(0, (len(tokens) - 1) // bs)
        parent = _TREE_ROOT
        for i in range(cached // bs):
            parent = PrefixTree._key(
                parent,
                tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
        i = cached // bs
        while i < limit:
            chunk = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            key = PrefixTree._key(parent, chunk)
            entry = self.tier.checkout(key, owner)
            if entry is None:
                break
            restored = corrupt = advanced = False
            try:
                try:
                    faults.fire("kvtier.restore")
                except Exception:
                    # An injected restore fault degrades to prefilling
                    # the suffix — the tier is an optimization, never
                    # a failure domain.
                    break
                if not verify_block_tokens(parent, chunk, key,
                                           entry.tokens):
                    corrupt = True
                    break
                try:
                    fresh = self._acquire_with_evict(1, owner)
                except KVCacheOOM:
                    break  # no room to restore into; prefill covers it
                try:
                    self._tier_import_block(fresh[0], entry.planes,
                                            chunk)
                except Exception:
                    log.warning(
                        "host tier: restored content diverges for "
                        "block %s — dropping entry, re-prefilling",
                        key[:12], extra={"request_id": owner})
                    self.allocator.release(fresh, owner)
                    corrupt = True
                    break
                blk, created = self.prefix.attach_restored(
                    parent, chunk, fresh[0], owner, tier="host")
                if not created:
                    # Lost the publish race: the tree already serves
                    # this chunk — use its block, drop our copy.
                    self.allocator.release(fresh, owner)
                blocks.append(blk)
                cached += bs
                tname = "host" if created else "hbm"
                by_tier[tname] = by_tier.get(tname, 0) + bs
                restored = created
                advanced = True
            finally:
                self.tier.checkin(key, owner, restored=restored,
                                  corrupt=corrupt)
            if not advanced:
                break
            parent = key
            i += 1
        return cached

    def kv_match_prefix(self, tokens, owner: str
                        ) -> Tuple[List[int], int]:
        """Fork the longest cached prefix of `tokens` — the HBM chain
        plus host-tier restores — to `owner`, WITHOUT binding a slot:
        the router pull's source-side primitive (ISSUE 17). The caller
        owns releasing the forked refs (success and failure paths
        both). Returns (blocks, cached_token_count)."""
        if self.prefix is None:
            return [], 0
        with self._slock:
            by_tier: dict = {}
            blocks, cached = self.prefix.match_and_fork(
                tokens, owner, by_tier=by_tier)
            try:
                if self.tier is not None:
                    cached = self._extend_from_tier(
                        tokens, owner, blocks, cached, by_tier)
            except Exception:
                self.allocator.release(blocks, owner)
                raise
            return blocks, cached

    def _tier_export_block(self, block: int, tokens) -> list:
        raise NotImplementedError

    def _tier_import_block(self, block: int, planes: list,
                           tokens) -> None:
        raise NotImplementedError

    def kv_release_slot(self, slot: int, cache: bool = True) -> None:
        """Unbind `slot` and release its lease exactly once; when
        `cache`, the request's full prompt blocks are inserted into
        the prefix tree INSIDE the release (owner refs still held, so
        the cache fork can never race a concurrent settle-path
        release)."""
        with self._slock:
            st = self._states[slot]
            self._states[slot] = None
        if st is None:
            return
        st.lease.release(
            cache_hook=self.prefix_cache_hook(st.confirmed)
            if cache else None)

    def prefix_cache_hook(self, confirmed: int):
        """The release-time prefix-cache insert covering only
        COLLECT-CONFIRMED prompt positions (confirmed, NOT ctx: a
        mid-prefill truncation retires the slot while its latest
        chunk is dispatched but uncollected — if that step then fails,
        ctx-derived caching would publish blocks whose KV was never
        written, and match_and_fork would serve them as truth to
        every later same-prefix request). Shared by the retire path
        above and the disagg transfer plane's post-ack release."""
        if self.prefix is None:
            return None
        prefix_tree, bs = self.prefix, self.block_size
        confirmed = int(confirmed)

        def hook(lease):
            written = min(len(lease.prompt), confirmed)
            full = (written // bs) * bs
            if full > 0:
                prefix_tree.insert(lease.prompt[:full],
                                   lease.blocks[:full // bs])
        return hook

    # -- cross-replica page hand-off (serving/disagg) --------------------------

    @property
    def kv_spec(self):
        """The pool layout + model identity as a KVSpec — declared
        once here, and everything the transfer path does (wire bytes,
        segmentation, the receiver's parse, the hello check) derives
        from it. Lazy import: kvcache must stay importable without
        the disagg package (which imports kvcache back)."""
        from ..disagg.spec import KVSpec

        return KVSpec(**self._spec_fields())

    def _spec_fields(self) -> dict:
        raise NotImplementedError

    def kv_detach_slot(self, slot: int) -> Optional[dict]:
        """Unbind `slot` and DETACH its lease for a cross-replica
        hand-off: the pages stay owned (a failed transfer reattaches
        and resumes here), the slot frees for new admissions, and the
        returned descriptor carries everything the transfer plane
        needs — the lease, the collect-CONFIRMED written extent
        (export must never ship positions a failed step left
        unwritten), and this executor (the export source). The
        detach/ack pairing is the GL016 contract: every caller must
        visibly hand the result to the transfer plane or settle it.

        Returns None when the request settled concurrently (the
        handler-thread finish() released the lease between the
        caller's done-check and here — the race every settle path
        tolerates): the slot is unbound, the pages already returned
        through the choke point, and there is nothing to hand off."""
        with self._slock:
            st = self._states[slot]
            self._states[slot] = None
        if st is None:
            raise ValueError(f"slot {slot}: nothing bound to detach")
        if not st.lease.detach():
            return None
        return {"lease": st.lease, "confirmed": int(st.confirmed),
                "req_id": st.req_id, "executor": self}

    def kv_preempt_slot(self, slot: int, req) -> Optional[dict]:
        """Preempt `slot`'s occupant for a higher-priority arrival
        (ISSUE 20): park its CONFIRMED KV in the host tier and free the
        HBM pages, so the request can requeue carrying a ParkedKV and
        resume later with only its uncovered suffix re-prefilled —
        strictly fewer replayed steps than re-decoding from the prompt.

        Two-phase, all-or-nothing, called under the batcher's settle
        lock like every attach/detach:

          * **Park (fallible).** Export each full confirmed block
            verbatim into the tier under its chained content key and
            pin it (``checkout``) for the victim. Any failure here
            unwinds the pins and leaves the victim BOUND — a crash-only
            exit mid-park looks exactly like a replica fault, and the
            supervisor's seize→requeue→_reattach path already lands the
            lease exactly once.
          * **Commit.** ``detach()`` the HBM lease (False → the request
            settled concurrently: unwind, unbind, nothing to requeue),
            swap ``req.kv_lease`` to the ParkedKV, and release the HBM
            pages through the ordinary retire hook (confirmed prompt
            blocks go to the prefix cache, everything else frees).

        Without a tier — or when nothing confirmed fills one block —
        falls back to detach-and-reattach: the pages stay reserved (no
        HBM freed) but the SLOT frees, which is the resource the
        interactive arrival is actually queued on. Returns the hand-off
        descriptor, or None when the victim settled concurrently."""
        with self._slock:
            st = self._states[slot]
            if st is None:
                raise ValueError(
                    f"slot {slot}: nothing bound to preempt")
            lease = st.lease
            owner = st.req_id
            bs = self.block_size
            pins: List[str] = []
            parent = _TREE_ROOT
            if (self.tier is not None and self.prefix is not None
                    and not lease.released):
                seq = list(lease.prompt) + [int(t) for t in req.tokens]
                nspill = min(int(st.confirmed), len(seq)) // bs
                nspill = min(nspill, len(lease.blocks))
                try:
                    for i in range(nspill):
                        chunk = tuple(seq[i * bs:(i + 1) * bs])
                        key = PrefixTree._key(parent, chunk)
                        planes = self._tier_export_block(
                            lease.blocks[i], chunk)
                        faults.fire("kvpreempt.park")
                        if not self.tier.put(key, parent, chunk,
                                             planes):
                            break  # tier full: park the prefix we got
                        if self.tier.checkout(key, owner) is None:
                            break
                        pins.append(key)
                        parent = key
                except BaseException:
                    # Crash-only: unwind the pins, leave the victim
                    # bound — the supervisor's seize path owns it now.
                    for pinned in pins:
                        self.tier.checkin(pinned, owner)
                    raise
            if not pins:
                # Nothing parkable (no tier, cold victim, or tier
                # full): free the SLOT, keep the pages — resume rides
                # the ordinary _reattach path.
                if not lease.detach():
                    self._states[slot] = None
                    return None
                lease.reattach()
                self._states[slot] = None
                self.preempted_total += 1
                return {"lease": lease, "confirmed": int(st.confirmed),
                        "req_id": st.req_id, "executor": self,
                        "parked_blocks": 0}
            if not lease.detach():
                # Settled concurrently (handler-thread finish() between
                # the caller's done-check and here): the pages already
                # returned through the choke point — unpin and unbind.
                for key in pins:
                    self.tier.checkin(key, owner)
                self._states[slot] = None
                return None
            parked = ParkedKV(self.tier, self._exec_id, owner, pins,
                              lease.prompt,
                              cached_tokens=len(pins) * bs,
                              cached_by_tier={"host": len(pins) * bs})
            req.kv_lease = parked
            # Release the HBM pages through the ordinary retire hook:
            # confirmed prompt blocks feed the prefix cache, the rest
            # free for the arrival that triggered the preemption.
            lease.release(
                cache_hook=self.prefix_cache_hook(st.confirmed))
            self._states[slot] = None
            self.preempted_total += 1
            if req.done:
                # finish() raced the swap: it settled the OLD lease;
                # the pins are ours to return.
                parked.release()
                return None
            return {"lease": parked, "confirmed": int(st.confirmed),
                    "req_id": st.req_id, "executor": self,
                    "parked_blocks": len(pins)}

    def kv_export(self, req, detach: dict) -> Tuple[dict, list]:
        """Read the detached lease's WRITTEN pages out of this pool:
        ``(meta, planes)`` where meta is the wire-ready transfer
        header (self-contained: the importer rebuilds the lease from
        it alone, no shared objects across the boundary) and planes
        the pool-layout arrays ``[(payload, scales), ...]`` for the
        stream's codec stage."""
        lease = detach["lease"]
        n_tokens = int(detach["confirmed"])
        n_blocks = -(-n_tokens // self.block_size)
        blocks = lease.blocks[:n_blocks]
        planes = self._export_pages(blocks, req, n_tokens)
        meta = {"req": req.request_id, "tokens": n_tokens,
                "n_blocks": n_blocks, "cached": lease.cached_tokens,
                "prompt_tokens": list(lease.prompt),
                "settled": [int(t) for t in req.tokens],
                "max_tokens": int(req.max_tokens)}
        return meta, planes

    def kv_import(self, meta: dict, planes: list):
        """Build a LOCAL lease for a transferred request: reserve its
        worst-case pages from THIS pool (OOM here is the importer's
        nack — capacity pressure, the transfer plane's retry/requeue
        decision), write the shipped pages into the first blocks, and
        return the new KVLease (exec_id = this executor, so the
        decode-side kv_attach takes the _reattach resume path). The
        caller owns attaching it to the request — and releasing it if
        the hand-off dies between ack and attach."""
        prompt = [int(t) for t in meta["prompt_tokens"]]
        plen = len(prompt)
        if plen + int(meta["max_tokens"]) > self.max_context:
            raise ValueError(
                f"transferred request {meta.get('req')} needs "
                f"{plen} + {meta['max_tokens']} context; this pool "
                f"caps at {self.max_context}")
        owner = str(meta["req"])
        need = -(-(plen + int(meta["max_tokens"])) // self.block_size)
        n_blocks = int(meta["n_blocks"])
        if n_blocks > need:
            raise ValueError(
                f"transfer ships {n_blocks} block(s) but the lease "
                f"geometry derives {need}")
        fresh = self._acquire_with_evict(need, owner)
        try:
            self._import_pages(fresh[:n_blocks], planes, meta)
        except BaseException:
            self.allocator.release(fresh, owner)
            raise
        return KVLease(self.allocator, self._exec_id, owner, fresh,
                       tuple(prompt),
                       cached_tokens=int(meta.get("cached", 0)))

    def _export_pages(self, blocks, req, n_tokens: int) -> list:
        raise NotImplementedError

    def _import_pages(self, blocks, planes: list, meta: dict) -> None:
        raise NotImplementedError

    # -- the two-phase decode contract ----------------------------------------

    def kv_gen(self) -> int:
        return self._gen

    def reset(self) -> None:
        """New decode session: slot bindings and the step plan
        generation reset; the KV POOLS and the prefix cache survive —
        surviving pages are exactly what makes a post-restart
        re-attach worth anything. Leases are owned by their requests,
        never by the session."""
        with self._slock:
            self._gen += 1
            self._states = [None] * self.slots
            self._spec_inflight = 0
            self._backend_reset()

    def submit(self, updates: Sequence = (), step=None,
               request_ids=None, gen: Optional[int] = None,
               occupants=None):
        """Plan and dispatch one fused step. `updates` is unused (the
        KV plane assembles its own token window from slot state);
        `gen` (from kv_gen(), captured under the batcher's settle
        lock) turns a submit raced by a supervisor seize→reset into a
        no-op stale handle instead of corrupting the new session.

        _dispatch runs UNDER _slock, deliberately: plan+dispatch must
        be atomic against reset(), or an abandoned thread could
        dispatch a stale plan AFTER the new session re-acquired its
        freed blocks — a silent scatter into another request's KV
        (device execution order is dispatch order only per thread).
        The cost is that a dispatch wedged on the device holds the
        lock and a restart's reset() blocks behind it — but reset
        runs under the PR 5 watchdog clock, so that degrades loudly
        to breaker-parking the replica, which is the designed outcome
        for an unresponsive device. The realistic wedge point
        (materialize/block_until_ready) is in collect(), which takes
        _slock only AFTER materializing."""
        with self._slock:
            if gen is not None and gen != self._gen:
                plan = _StepPlan(gen, 0, None, None, None, None, None,
                                 np.zeros((self.slots,), bool),
                                 stale=True)
                return _KVHandle(plan, None)
            plan = self._plan_step()
            raw = self._dispatch(plan)
            return _KVHandle(plan, raw)

    def _plan_step(self) -> _StepPlan:
        S, C, B = self.slots, self.prefill_chunk, self.max_blocks_per_req
        host_tok = np.zeros((S, C), np.int32)
        use_host = np.zeros((S,), bool)
        ctx = np.zeros((S,), np.int32)
        n_new = np.zeros((S,), np.int32)
        tables = np.zeros((S, B), np.int32)
        emit = np.zeros((S,), bool)
        owners: List = [None] * S
        spec = self.spec
        spec_k = np.full((S,), -1, np.int32) if spec is not None \
            else None
        spec_slots: List[int] = []
        budget = self.prefill_budget
        step_prefill = 0
        step_decode = 0
        # Rotating start: with the budget shared across slots, a long
        # prompt in slot 0 must not permanently starve slot 1's.
        order = [(self._rr + j) % S for j in range(S)]
        self._rr = (self._rr + 1) % S
        for s in order:
            st = self._states[s]
            if st is None:
                continue
            plen = len(st.lease.prompt)
            owners[s] = st.req_id
            ctx[s] = st.ctx
            tables[s, :len(st.lease.blocks)] = st.lease.blocks
            if st.prefill_pos < plen:
                take = min(C, plen - st.prefill_pos, budget)
                st.pending_emit = False
                if take <= 0:
                    st.chain_device = False
                    continue  # budget spent: this prompt waits a step
                host_tok[s, :take] = st.lease.prompt[
                    st.prefill_pos:st.prefill_pos + take]
                use_host[s] = True
                n_new[s] = take
                budget -= take
                step_prefill += take
                finishes = st.prefill_pos + take >= plen
                emit[s] = finishes
                st.ctx += take
                st.prefill_pos += take
                # Speculative mode never chains on device: the next
                # plan drafts FROM the last accepted token, which must
                # be host-side (stamped at collect — the sync loop
                # shape guarantees collect precedes the next plan).
                st.chain_device = bool(finishes) and spec is None
                st.pending_emit = bool(finishes)
            elif spec is not None:
                if st.last_token is None and st.spec_ahead is None:
                    if not self.pipelined:
                        raise RuntimeError(
                            f"slot {s}: speculative decode with no "
                            f"prior token (request {st.req_id})")
                    # Pipelined prefill finish: the slot's first emit
                    # is still in flight and the draft has nothing to
                    # chain from — bubble ONE step (n_new stays 0)
                    # until collect stamps last_token. Once the chain
                    # starts, spec_ahead carries it forward and the
                    # bubble never recurs.
                    st.chain_device = False
                    continue
                # Speculative decode: defer to the batched draft call
                # below (one propose per step — a jitted draft wants
                # one fixed-shape dispatch, not a per-slot loop).
                spec_slots.append(s)
            else:
                # Decode: one token, NEVER budget-rationed (the
                # bounded-prefill contract protecting decode p99).
                n_new[s] = 1
                emit[s] = True
                step_decode += 1
                if st.chain_device:
                    use_host[s] = False  # input = previous step's
                    # on-device emit, still in flight
                else:
                    if st.last_token is None:
                        raise RuntimeError(
                            f"slot {s}: decode with no prior token "
                            f"(request {st.req_id})")
                    host_tok[s, 0] = st.last_token
                    use_host[s] = True
                st.ctx += 1
                st.chain_device = True
                st.pending_emit = True
        tree = spec is not None and spec.tree_width > 1
        spec_off = spec_w = spec_epoch = n_app_v = None
        roff = plim = win = None
        if spec is not None:
            spec_off = np.zeros((S,), np.int32)
            spec_w = np.zeros((S,), np.int32)
            spec_epoch = np.zeros((S,), np.int32)
            n_app_v = n_new  # rebound to a tree copy below
        if spec_slots:
            # One fixed-shape propose over ALL slots (idle/prefill
            # rows carry zeros and are ignored): the draft's AOT
            # executable compiles once, like every other step shape.
            last = np.zeros((S,), np.int32)
            base = np.zeros((S,), np.int32)
            ahead_v = [False] * S
            for s in spec_slots:
                st = self._states[s]
                # Plan-ahead seam: a device-chained slot's base row
                # takes the TRUE bonus from the in-flight window on
                # device; the draft chains from its host-side
                # PREDICTION of it. Repair rows force the host path
                # (they are row 0, and only row 0 can device-chain) —
                # and a rollback broke the chain anyway.
                ahead_v[s] = (self.pipelined and st.chain_device
                              and st.spec_ahead is not None
                              and not st.repair)
                last[s] = (st.spec_ahead if ahead_v[s]
                           else st.last_token)
                base[s] = st.ctx + len(st.repair)
            if self.pipelined:
                pf = propose_full(spec.draft, last, base)
                drafts = pf[:, :spec.k]
            else:
                pf = None
                drafts = np.asarray(spec.draft.propose(last, base),
                                    np.int32)
            sibs = (np.asarray(spec.draft.propose_sibs(last, base),
                               np.int32) if tree else None)
            for s in spec_slots:
                st = self._states[s]
                R = len(st.repair)
                w_want = spec.width_for(st.spec_ewma) - 1
                # Clamp inside the admission-time page reservation:
                # the max position a verify step writes equals the
                # one-token loop's max, so speculation never needs
                # slack pages (see spec.clamp_spec_k). Repair and
                # sibling rows ride the same chunk width.
                ks = clamp_spec_k(spec.k_for(st.spec_ewma),
                                  int(base[s]), st.max_total,
                                  C - R - w_want)
                w = w_want if ks >= 1 else 0
                n_app = R + 1 + ks
                for i, rt in enumerate(st.repair):
                    host_tok[s, i] = rt
                if ahead_v[s]:
                    use_host[s] = False
                else:
                    host_tok[s, R] = st.last_token
                    use_host[s] = True
                if ks:
                    host_tok[s, R + 1:R + 1 + ks] = drafts[s, :ks]
                if w:
                    host_tok[s, n_app:n_app + w] = sibs[s, :w]
                n_new[s] = n_app + w
                spec_k[s] = ks
                spec_off[s] = R
                spec_w[s] = w
                spec_epoch[s] = st.spec_epoch
                emit[s] = True
                step_decode += 1
                # Provisional FULL-ACCEPTANCE advance over the
                # APPENDED rows: collect rolls ctx back to the
                # accepted extent. The confirmed watermark never
                # moves here — that is exactly what makes rejection
                # a pure truncation.
                st.ctx += n_app
                st.repair = []
                st.chain_device = bool(self.pipelined)
                st.spec_ahead = int(pf[s, ks]) if pf is not None \
                    else None
                st.pending_emit = True
                spec.stats.proposed += ks + w
            self._spec_inflight += 1
            if self._spec_inflight > spec.stats.pipeline_peak:
                spec.stats.pipeline_peak = self._spec_inflight
        if tree:
            # Tree-step geometry for EVERY row (prefill chunks too —
            # a tree-armed executor routes all steps through the one
            # tree executable, so chain rows carry their degenerate
            # layout: roff = row index, all rows append, empty
            # in-window mask). Sibling rows share the first trunk
            # position and stop their pool attention BEFORE it (the
            # trunk's append there is a different branch).
            n_app_v = n_new - np.maximum(spec_w, 0)
            roff = np.tile(np.arange(C, dtype=np.int32), (S, 1))
            for s in spec_slots:
                if spec_w[s]:
                    na = int(n_app_v[s])
                    roff[s, na:na + int(spec_w[s])] = \
                        int(spec_off[s]) + 1
            rows = np.arange(C, dtype=np.int32)[None, :]
            pos = ctx[:, None] + roff
            app_row = rows < n_app_v[:, None]
            valid_row = rows < n_new[:, None]
            plim = np.where(valid_row, pos + app_row, 0
                            ).astype(np.int32)
            win = np.zeros((S, C, C), bool)
            for s in spec_slots:
                na, w = int(n_app_v[s]), int(spec_w[s])
                for i in range(na, na + w):
                    win[s, i, i] = True
        self._step_no += 1
        self.prefill_tokens += step_prefill
        if step_decode:
            self.steps_decode += 1
            if step_prefill:
                self.steps_mixed += 1
        return _StepPlan(self._gen, self._step_no, host_tok, use_host,
                         ctx, n_new, tables, emit, owners,
                         spec_k=spec_k, spec_off=spec_off,
                         spec_w=spec_w, spec_epoch=spec_epoch,
                         n_app=n_app_v, roff=roff, plim=plim, win=win)

    def collect(self, handle: _KVHandle) -> np.ndarray:
        """[slots] int32: the emitted token per slot, NO_TOKEN (-1)
        where this step emitted nothing (mid-prefill chunk, idle slot,
        stale handle). Speculative executors return [slots, chunk]
        instead — each row the step's ACCEPTED token run, NO_TOKEN-
        padded (see _collect_spec); the scheduler's retire normalizes
        both shapes through spec.token_run. Pure — no state mutation,
        so an abandoned batcher thread waking from a wedge cannot
        corrupt the restarted session by collecting."""
        if self.spec is not None:
            return self._collect_spec(handle)
        out = np.full((self.slots,), NO_TOKEN, np.int32)
        if handle.plan.stale:
            return out
        raw = np.asarray(self._materialize(handle.raw), np.int32)
        emit = handle.plan.emit
        out[emit] = raw[emit]
        # Record last emitted tokens host-side: a re-attach after THIS
        # generation dies feeds them back through the host path. The
        # owner check attributes each emit to the state that PLANNED
        # it: a retire + fresh admit can rebind the slot between
        # submit and collect, and the old request's phantom emit must
        # not overwrite the new state's last_token. The decode-token
        # counter lives on the same guard, NOT at plan time — the
        # pipelined loop plans one phantom step per retiring request
        # whose token is dropped, so plan-time counting inflates
        # decode throughput by ~1/max_tokens and diverges from sync
        # mode on identical streams. A surviving owned emit is a
        # settled token: both modes count exactly what clients
        # receive.
        with self._slock:
            if handle.plan.gen == self._gen:
                for s in range(self.slots):
                    st = self._states[s]
                    if st is None or st.req_id != handle.plan.owners[s]:
                        continue
                    if handle.plan.n_new[s]:
                        # This step's device writes are now real:
                        # advance the confirmed-KV watermark (mid-
                        # prefill chunks too — they write without
                        # emitting).
                        st.confirmed = max(
                            st.confirmed,
                            int(handle.plan.ctx[s]
                                + handle.plan.n_new[s]))
                    if emit[s] and st.pending_emit:
                        st.last_token = int(raw[s])
                        self.decode_tokens += 1
        return out

    def _collect_spec(self, handle: _KVHandle) -> np.ndarray:
        """The speculative collect path: [slots, chunk] int32, row s
        holding the step's accepted token run left-aligned (NO_TOKEN
        padding). Greedy-verify acceptance per decode slot: the
        target's per-position argmax ``t_0..t_ks`` against the plan's
        drafts — ``a`` leading matches accept ``t_0..t_a`` (a+1
        tokens, at least the bonus).

        REJECTION IS ROLLBACK, done entirely here under the same
        owner guard the one-token path uses: ``st.ctx`` (advanced by
        ks+1 at plan time) rolls back to ``plan_ctx + a + 1`` and the
        confirmed watermark advances ONLY to that accepted extent.
        No device-side unwind exists or is needed — KV at rejected
        positions sits beyond the watermark, so the prefix cache can
        never publish it (the PR 7 confirmed contract), a re-attach
        rebuilds cursors from settled tokens below it, and the next
        verify step's append simply overwrites the dead rows (a
        position's K/V depends only on its own input embedding, so
        the overwrite equals what an unspeculated run writes).

        Mid-prefill chunks confirm their full n_new exactly like the
        one-token path; a prefill-finishing step emits its single
        token as a length-1 run. The owner guard + the ``n_new == 0``
        check keep the zero-work-slot no-op contract (a budget-
        starved slot raced by retire+re-admit between submit and
        collect must neither advance a watermark nor stamp a
        last_token) — the guard speculative rollback leans on.

        ISSUE 18 adds three cases, all inside the same guard:

        * EPOCH-STALE plan-ahead (pipelined): the plan was drafted
          from a provisional ctx a rollback has since revoked — it
          settles NOTHING and bumps nothing (the re-plan after the
          rollback already owns the slot's cursors); counted as a
          replan. Its device writes are dead bytes a later valid
          window overwrites, the standard watermark argument.
        * FULL acceptance under pipelining leaves ``st.ctx`` ALONE —
          the in-flight plan-ahead already advanced it past this
          window, and rolling it back here would replay positions the
          plan-ahead owns. Rollback (and the epoch bump invalidating
          in-flight plans) happens only when something was actually
          rejected.
        * TREE windows accept the longest matching root-to-leaf path
          (spec.accept_tree). A winning sibling settles its token
          WITHOUT an appended KV row (the trunk's append at that
          position holds the rejected trunk token), so confirmed
          stops before it and the token re-feeds as the next window's
          repair row — the hole closes before any later query can
          attend it."""
        C = self.prefill_chunk
        out = np.full((self.slots, C), NO_TOKEN, np.int32)
        if handle.plan.stale:
            return out
        raw = np.asarray(self._materialize(handle.raw), np.int32)
        plan = handle.plan
        spec = self.spec
        alpha = spec.ewma_alpha
        with self._slock:
            if plan.gen != self._gen:
                return out
            if plan.spec_k is not None and (plan.spec_k >= 0).any():
                self._spec_inflight = max(0, self._spec_inflight - 1)
            for s in range(self.slots):
                st = self._states[s]
                if st is None or st.req_id != plan.owners[s]:
                    continue
                n = int(plan.n_new[s])
                if n == 0:
                    continue
                base = int(plan.ctx[s])
                ks = int(plan.spec_k[s])
                if ks < 0:
                    # Prefill chunk: every planned position's KV is
                    # now real (chunks write without emitting); the
                    # finishing chunk emits one token.
                    st.confirmed = max(st.confirmed, base + n)
                    if plan.emit[s] and st.pending_emit:
                        t = int(raw[s, n - 1])
                        out[s, 0] = t
                        st.last_token = t
                        self.decode_tokens += 1
                    continue
                if not st.pending_emit:
                    continue
                if int(plan.spec_epoch[s]) != st.spec_epoch:
                    spec.stats.replans += 1
                    continue
                R = int(plan.spec_off[s])
                w = int(plan.spec_w[s])
                n_app = R + 1 + ks
                run, sib = accept_tree(
                    plan.host_tok[s, R + 1:R + 1 + ks],
                    plan.host_tok[s, n_app:n_app + w],
                    raw[s, R:R + ks + 1],
                    raw[s, n_app:n_app + w])
                a = len(run) - 1 if sib < 0 else 0
                out[s, :len(run)] = run
                if sib >= 0:
                    # Sibling path: t_0 is settled truth but the KV at
                    # its position holds the REJECTED trunk token —
                    # confirm up to the base row only and queue the
                    # repair re-append.
                    st.ctx = base + R + 1
                    st.confirmed = max(st.confirmed, base + R + 1)
                    st.repair = [int(run[0])]
                    st.spec_epoch += 1
                    st.chain_device = False
                    st.spec_ahead = None
                elif a < ks:
                    st.ctx = base + R + a + 1      # the rollback
                    st.confirmed = max(st.confirmed, base + R + a + 1)
                    st.spec_epoch += 1
                    st.chain_device = False
                    st.spec_ahead = None
                else:
                    # Full acceptance: the provisional advance stands
                    # (a pipelined plan-ahead may already sit past
                    # it); only the watermark catches up.
                    st.confirmed = max(st.confirmed, base + n_app)
                st.last_token = int(run[-1])
                self.decode_tokens += len(run)
                if ks > 0:
                    rate = (a if sib < 0 else 1) / ks
                    st.spec_ewma = ((1.0 - alpha) * st.spec_ewma
                                    + alpha * min(1.0, rate))
                spec.stats.record_run(accepted=len(run) - 1,
                                      path_len=len(run))
        return out

    def kv_stats(self) -> dict:
        """Scrape-time snapshot for /metrics and the bench."""
        stats = self.allocator.stats()
        out = {"blocks_used": stats["used"],
               "blocks_free": stats["free"],
               "blocks_shared": stats["shared"],
               "prefill_tokens": self.prefill_tokens,
               "decode_tokens": self.decode_tokens,
               "steps_decode": self.steps_decode,
               "steps_mixed": self.steps_mixed,
               "resumed": self.resumed_total,
               "preempted": self.preempted_total,
               "preempt_resumed": self.preempt_resumed_total,
               "prefix_hit_tokens": 0, "prefix_lookup_tokens": 0}
        if self.prefix is not None:
            out["prefix_hit_tokens"] = self.prefix.hit_tokens
            out["prefix_lookup_tokens"] = self.prefix.lookup_tokens
            for tname, v in self.prefix.hit_tokens_by_tier.items():
                out[f"prefix_hit_tokens_{tname}"] = v
        if self.tier is not None:
            for k, v in self.tier.stats().items():
                out[f"tier_{k}"] = v
        if self.spec is not None:
            st = self.spec.stats
            out["spec_proposed_tokens"] = st.proposed
            out["spec_accepted_tokens"] = st.accepted
            out["spec_verify_steps"] = st.runs
            out["spec_accept_rate"] = round(st.accept_rate(), 6)
            out["spec_tokens_per_step"] = round(st.tokens_per_step(),
                                                6)
            out["spec_replans"] = st.replans
            out["spec_pipeline_depth"] = self._spec_inflight
            out["spec_pipeline_peak"] = st.pipeline_peak
            out["spec_path_len"] = dict(st.path_len)
        return out

    # -- backend hooks --------------------------------------------------------

    def _backend_reset(self) -> None:
        raise NotImplementedError

    def _dispatch(self, plan: _StepPlan):
        raise NotImplementedError

    def _materialize(self, raw) -> np.ndarray:
        raise NotImplementedError

    # step() has no meaning on the token plane.
    def step(self, x):  # pragma: no cover - contract guard
        raise NotImplementedError(
            "KV executors speak the two-phase token contract only")


class PagedKVExecutor(KVExecutorBase):
    """Device-resident paged-attention replica (kvcache/paged.py).
    ``mode="pipelined"`` (default) leaves submit() async — jax
    dispatch returns while the step runs and the decode recurrence
    chains on device; ``mode="sync"`` drives the same executable
    through the scheduler's synchronous KV loop (the measured
    baseline); ``mode="speculative"`` (ISSUE 15) is the draft/verify
    third mode — the step compiles with PER-POSITION argmax outputs
    (``per_pos=True`` in PagedDecodeStep, both kernels) and the
    executor plans k-token verify windows against ``draft`` (default:
    a spec.TruncatedDraft built from this step's own embed/positional/
    output weights), behind the unchanged submit/collect seam in the
    sync loop shape; ``mode="speculative-pipelined"`` (ISSUE 18)
    overlaps the draft with the verify — window w+1 is planned from
    window w's proposed tokens while the device still verifies w, the
    true bonus chains on device, and a mis-speculation is the epoch-
    gated watermark rollback. ``spec_tree_width >= 2`` widens either
    speculative mode to a token tree (trunk chain + first-position
    siblings under a tree-causal mask; the step routes through the
    XLA tree composition — the Pallas kernel normalizes in-kernel and
    cannot merge in-window partials, the documented fallback).
    ``kernel=`` selects the fused Pallas paged-attention kernel or
    the XLA reference composition (default: pallas on a TPU backend,
    xla elsewhere) and ``pool_dtype=`` the resident KV layout (int8
    codes + per-block scales by default — 4x resident context per HBM
    byte; "fp32" is the exact reference) — both pass straight through
    to PagedDecodeStep, so the scheduler, chaos matrix and sharded
    plane ride any mode untouched."""

    def __init__(self, slots: int = 4, vocab: int = 64, d: int = 16,
                 heads: int = 2, block_size: int = 4,
                 num_blocks: int = 128, max_blocks_per_req: int = 16,
                 prefill_chunk: int = 8,
                 prefill_budget: Optional[int] = None,
                 prefix_cache: bool = True, seed: int = 0,
                 mode: str = "pipelined", warmup: bool = True,
                 donate: Optional[bool] = None,
                 kernel: Optional[str] = None,
                 pool_dtype: str = "int8",
                 interpret: Optional[bool] = None,
                 spec_k: int = 4, draft=None,
                 spec_tree_width: int = 1,
                 spec_adaptive: bool = False,
                 host_tier_bytes: Optional[int] = None):
        if mode not in ("pipelined", "sync", "speculative",
                        "speculative-pipelined"):
            raise ValueError(f"mode must be pipelined|sync|speculative"
                             f"|speculative-pipelined, got {mode!r}")
        speculative = mode in ("speculative", "speculative-pipelined")
        super().__init__(slots, vocab=vocab, block_size=block_size,
                         num_blocks=num_blocks,
                         max_blocks_per_req=max_blocks_per_req,
                         prefill_chunk=prefill_chunk,
                         prefill_budget=prefill_budget,
                         prefix_cache=prefix_cache,
                         pipelined=mode in ("pipelined",
                                            "speculative-pipelined"),
                         host_tier_bytes=host_tier_bytes)
        from ..spec import TruncatedDraft
        from .paged import PagedDecodeStep

        self._seed = int(seed)  # weight identity, stamped on kv_spec
        self._paged = PagedDecodeStep(
            slots=slots, vocab=vocab, d=d, heads=heads,
            block_size=block_size, num_blocks=num_blocks,
            max_blocks_per_req=max_blocks_per_req, chunk=prefill_chunk,
            seed=seed, donate=donate, kernel=kernel,
            pool_dtype=pool_dtype, interpret=interpret,
            per_pos=speculative,
            tree=speculative and spec_tree_width > 1)
        if speculative:
            if draft is None:
                draft = TruncatedDraft.from_paged(
                    self._paged, spec_k, tree_width=spec_tree_width)
            self._install_spec(SpecConfig(
                draft, spec_k, tree_width=spec_tree_width,
                adaptive=spec_adaptive))
        (self._kpool, self._kscale,
         self._vpool, self._vscale) = self._paged.init_pools()
        self._prev = self._paged.init_prev()
        if warmup:
            # One dispatched no-op step: first-execution lazy init is
            # paid here, not under the supervisor's watchdog.
            self.collect(self.submit((), gen=self._gen))
            self.reset()

    def _backend_reset(self) -> None:
        # Pools (codes AND scales) are kept — re-attach depends on
        # surviving pages; only the token recurrence restarts.
        self._prev = self._paged.init_prev()

    def _spec_fields(self) -> dict:
        p = self._paged
        return dict(model="paged", block_size=p.block_size,
                    heads=p.heads, d_head=p.d_head, vocab=p.vocab,
                    max_blocks_per_req=p.max_blocks_per_req,
                    pool_dtype=p.pool_dtype, planes=2,
                    seed=self._seed)

    def _export_pages(self, blocks, req, n_tokens: int) -> list:
        """Gather the written blocks device->host. Under _slock: the
        pool references must not be donated into a concurrently
        dispatched step mid-gather (the same plan+dispatch atomicity
        submit() documents). np.asarray blocks on any in-flight step,
        which is correct — the last step covering these positions was
        already collected, so the values are final; a later in-flight
        step only appends BEYOND the export extent (whole-block
        gathers may include such an append, which is exactly the
        value the decode side's own first step would write — the
        byte-identity argument in docs/serving.md)."""
        import jax.numpy as jnp

        idx = jnp.asarray(np.asarray(blocks, np.int32))
        with self._slock:
            k = np.asarray(self._kpool[idx])
            ksc = np.asarray(self._kscale[idx])
            v = np.asarray(self._vpool[idx])
            vsc = np.asarray(self._vscale[idx])
        return [(k, ksc), (v, vsc)]

    def _import_pages(self, blocks, planes: list, meta: dict) -> None:
        """Scatter transferred pages into this pool at the freshly
        acquired block ids. Under _slock, between steps: .at[].set
        builds NEW arrays, so an in-flight step keeps its own
        (donated or not) buffers and the next dispatch picks up the
        imported pools — no step ever sees a half-written import."""
        import jax.numpy as jnp

        (k, ksc), (v, vsc) = planes
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        with self._slock:
            self._kpool = self._kpool.at[idx].set(
                jnp.asarray(k, self._kpool.dtype))
            self._kscale = self._kscale.at[idx].set(jnp.asarray(ksc))
            self._vpool = self._vpool.at[idx].set(
                jnp.asarray(v, self._vpool.dtype))
            self._vscale = self._vscale.at[idx].set(jnp.asarray(vsc))

    def _tier_export_block(self, block: int, tokens) -> list:
        """Single-block HBM→host gather for the tier spill: the
        resident int8 codes + scales move VERBATIM (no re-quantize),
        so restore is byte-exact by construction. Same _slock
        discipline as _export_pages."""
        import jax.numpy as jnp

        idx = jnp.asarray(np.asarray([block], np.int32))
        with self._slock:
            k = np.asarray(self._kpool[idx])
            ksc = np.asarray(self._kscale[idx])
            v = np.asarray(self._vpool[idx])
            vsc = np.asarray(self._vscale[idx])
        return [(k, ksc), (v, vsc)]

    def _tier_import_block(self, block: int, planes: list,
                           tokens) -> None:
        """Host→HBM scatter of one restored block (the _import_pages
        .at[].set idiom — an in-flight step keeps its own buffers)."""
        import jax.numpy as jnp

        (k, ksc), (v, vsc) = planes
        idx = jnp.asarray(np.asarray([block], np.int32))
        with self._slock:
            self._kpool = self._kpool.at[idx].set(
                jnp.asarray(k, self._kpool.dtype))
            self._kscale = self._kscale.at[idx].set(jnp.asarray(ksc))
            self._vpool = self._vpool.at[idx].set(
                jnp.asarray(v, self._vpool.dtype))
            self._vscale = self._vscale.at[idx].set(jnp.asarray(vsc))

    def _dispatch(self, plan: _StepPlan):
        import jax.numpy as jnp

        if self.spec is not None and plan.roff is not None:
            (self._kpool, self._kscale, self._vpool, self._vscale,
             out) = self._paged.tree_step(
                self._kpool, self._kscale, self._vpool, self._vscale,
                self._prev,
                jnp.asarray(plan.host_tok), jnp.asarray(plan.use_host),
                jnp.asarray(plan.ctx), jnp.asarray(plan.n_new),
                jnp.asarray(plan.tables), jnp.asarray(plan.roff),
                jnp.asarray(plan.n_app), jnp.asarray(plan.plim),
                jnp.asarray(plan.win))
        else:
            (self._kpool, self._kscale, self._vpool, self._vscale,
             out) = self._paged(
                self._kpool, self._kscale, self._vpool, self._vscale,
                self._prev,
                jnp.asarray(plan.host_tok), jnp.asarray(plan.use_host),
                jnp.asarray(plan.ctx), jnp.asarray(plan.n_new),
                jnp.asarray(plan.tables))
        if self.spec is None:
            # out is the [slots] token recurrence the next pipelined
            # step may chain on device. The sync speculative step's
            # out is [slots, chunk] per-position argmax and never
            # chains — every verify window is host-fed from the last
            # ACCEPTED token, so _prev stays the zeroed init.
            self._prev = out
        elif self.pipelined:
            # Pipelined speculation: the NEXT window's base row
            # device-chains the TRUE bonus — the trunk leaf's
            # per-position output (row n_app-1). A tiny jitted
            # gather keeps the value device-resident; rows with no
            # work keep their previous chain value.
            self._prev = self._paged.take_prev(
                out, jnp.asarray(plan.n_app), self._prev)
        return out

    def _materialize(self, raw) -> np.ndarray:
        return np.asarray(raw)


class SyntheticKVExecutor(KVExecutorBase):
    """Jax-free KV replica: same allocator/lease/plan machinery, but
    the "device" is ``next = (31 * last_token + 7 * position + seed)
    % vocab`` (spec.synthetic_next_token) — deterministic AND
    position-dependent, so a resume that rewinds cursors wrong
    produces a visibly different stream. With ``pipelined=True``
    steps run FIFO on a worker thread with a dialable ``step_time_s``
    (the SyntheticExecutor overlap idiom); ``fault_site`` names the
    in-device chaos seam. ``spec=`` arms the draft/verify mode — the
    SpecConfig's draft is typically spec.OracleDraft, whose dialed
    acceptance rate is what the bench's controlled-speedup
    measurement turns; combined with ``pipelined=True`` (ISSUE 18)
    the executor plans window w+1 from window w's proposals while
    the worker thread still runs w — the overlap the pipelined-spec
    bench measures."""

    def __init__(self, slots: int = 4, vocab: int = 64,
                 block_size: int = 4, num_blocks: int = 128,
                 max_blocks_per_req: int = 16, prefill_chunk: int = 8,
                 prefill_budget: Optional[int] = None,
                 prefix_cache: bool = True, step_time_s: float = 0.0,
                 token_time_s: float = 0.0,
                 seed: int = 0, pipelined: bool = True,
                 fault_site: Optional[str] = None,
                 spec: Optional[SpecConfig] = None,
                 host_tier_bytes: Optional[int] = None):
        super().__init__(slots, vocab=vocab, block_size=block_size,
                         num_blocks=num_blocks,
                         max_blocks_per_req=max_blocks_per_req,
                         prefill_chunk=prefill_chunk,
                         prefill_budget=prefill_budget,
                         prefix_cache=prefix_cache, pipelined=pipelined,
                         spec=spec, host_tier_bytes=host_tier_bytes)
        self.step_time_s = float(step_time_s)
        # Per-PLANNED-TOKEN cost on top of the fixed floor: the knob
        # that makes prefill REAL in the cost model — a step co-running
        # an 8-token prefill chunk costs base + 8*token_time_s, and
        # every decode token in that batch pays it. Zero (the default)
        # keeps the PR 7 fixed-cost behavior; the disagg bench turns it
        # on to measure the cross-replica isolation claim (a prefill
        # flood CANNOT inflate a dedicated decode replica's steps).
        self.token_time_s = float(token_time_s)
        self.seed = int(seed)
        self.fault_site = fault_site
        self._dev_prev = np.zeros((self.slots,), np.int32)
        self._worker = _GuardedWorker(
            "synthetic-kv-step", step_fn=self._device_step,
            reset_fn=self._zero_dev_prev)

    def _zero_dev_prev(self) -> None:
        self._dev_prev = np.zeros((self.slots,), np.int32)

    # -- the "device" ---------------------------------------------------------

    def _device_step(self, plan: _StepPlan) -> np.ndarray:
        if self.fault_site is not None:
            faults.fire(f"{self.fault_site}.step")
        cost = self.step_time_s
        if self.token_time_s:
            # Per-PLANNED-token cost covers draft positions too: a
            # verify step really is wider than a one-token step, and
            # the spec bench's per-step-cost decomposition leans on
            # exactly this physics.
            cost += self.token_time_s * int(np.sum(plan.n_new))
        if cost:
            time.sleep(cost)
        if self.spec is not None:
            # Per-position outputs, the verify contract: out[s, j] is
            # the target's next token after consuming input j at its
            # row position (ctx + roff[j]; roff == j for chain rows —
            # tree siblings share the first trunk position). The
            # synthetic recurrence is Markov on (input, position), so
            # the per-position form IS the one-token recurrence
            # applied at each fed position. Row 0 alone may
            # device-chain (a pipelined plan-ahead's base row takes
            # the in-flight window's true bonus); rows >= 1 are
            # always host-fed drafts/siblings. The chain value
            # carries the trunk LEAF's output (row n_app-1) — the
            # bonus the next plan-ahead window chains from.
            C = self.prefill_chunk
            out = np.full((self.slots, C), NO_TOKEN, np.int32)
            prev = self._dev_prev.copy()
            for s in range(self.slots):
                n = int(plan.n_new[s])
                for j in range(n):
                    if j == 0:
                        tok_in = (int(plan.host_tok[s, 0])
                                  if plan.use_host[s]
                                  else int(prev[s]))
                    else:
                        tok_in = int(plan.host_tok[s, j])
                    ro = (int(plan.roff[s, j])
                          if plan.roff is not None else j)
                    out[s, j] = synthetic_next_token(
                        tok_in, int(plan.ctx[s]) + ro, self.seed,
                        self.vocab)
                if n > 0:
                    na = (int(plan.n_app[s])
                          if plan.n_app is not None else n)
                    prev[s] = out[s, na - 1]
            # Whole-attribute publish (copy-update-swap), never an
            # in-place mutation of the shared array: reset() and the
            # worker thread race only against an atomic swap.
            self._dev_prev = prev
            return out
        out = np.zeros((self.slots,), np.int32)
        for s in range(self.slots):
            n = int(plan.n_new[s])
            if n <= 0:
                out[s] = self._dev_prev[s]
                continue
            if plan.use_host[s]:
                last_in = int(plan.host_tok[s, n - 1])
            else:
                last_in = int(self._dev_prev[s])
            last_pos = int(plan.ctx[s]) + n - 1
            out[s] = synthetic_next_token(last_in, last_pos,
                                          self.seed, self.vocab)
        self._dev_prev = out
        return out

    def _backend_reset(self) -> None:
        # _GuardedWorker.reset serializes behind queued steps and
        # re-raises worker-side failures (the PR 5 discipline, shared
        # with the row-plane SyntheticExecutor).
        if not self.pipelined or not self._worker.started:
            self._zero_dev_prev()
            return
        self._worker.reset()

    def _dispatch(self, plan: _StepPlan):
        if not self.pipelined:
            return self._device_step(plan)
        return self._worker.submit(plan)

    def _materialize(self, raw) -> np.ndarray:
        if not self.pipelined:
            return raw
        raw.event.wait()
        if raw.error is not None:
            raise raw.error
        return raw.tokens

    # -- cross-replica hand-off (the jax-free double) --------------------------

    def _spec_fields(self) -> dict:
        return dict(model="synthetic-kv", block_size=self.block_size,
                    heads=1, d_head=1, vocab=self.vocab,
                    max_blocks_per_req=self.max_blocks_per_req,
                    pool_dtype="fp32", planes=1, seed=self.seed)

    def _page_content(self, prompt, settled, n_tokens: int
                      ) -> np.ndarray:
        """The synthetic plane's KV truth for positions
        [0, n_tokens): position p's "KV" is the token the step that
        wrote it CONSUMED — prompt[p] through prefill, then the
        settled stream shifted by one (position plen+j holds
        settled[j], the previous emit fed back as input). Computable
        host-side from the request alone on BOTH ends, which turns
        the synthetic import into a true end-to-end transport
        integrity check: the importer recomputes and compares."""
        plen = len(prompt)
        vals = [float(prompt[p]) if p < plen
                else float(settled[p - plen])
                for p in range(int(n_tokens))]
        n_blocks = -(-int(n_tokens) // self.block_size)
        arr = np.zeros((n_blocks, self.block_size, 1, 1), np.float32)
        if vals:
            arr.reshape(-1)[:len(vals)] = vals
        return arr

    def _export_pages(self, blocks, req, n_tokens: int) -> list:
        content = self._page_content(req.prompt_tokens, req.tokens,
                                     n_tokens)
        return [(content, np.ones((content.shape[0],), np.float32))]

    def _import_pages(self, blocks, planes: list, meta: dict) -> None:
        """Verify, don't store: the synthetic recurrence is position-
        only, so the pool content is the TRANSPORT'S correctness
        proof, not decode state. Exact even through the int8 wire:
        token values are small ints (< vocab <= 127/scale margin), so
        scale/2 rounding error < 0.5 and rint recovers them."""
        (payload, _scales), = planes
        expect = self._page_content(meta["prompt_tokens"],
                                    meta["settled"], meta["tokens"])
        got = np.rint(np.asarray(payload, np.float32))
        if not np.array_equal(got, np.rint(expect)):
            raise ValueError(
                f"transferred page content diverges for request "
                f"{meta.get('req')} (transport corruption)")

    def _chunk_content(self, tokens) -> np.ndarray:
        """One cached prefix block's synthetic "KV": prefill position
        p consumed prompt[p], and a prefix-tree block covers prompt
        positions only — so the block's content IS its chunk's token
        ids (the _page_content rule restricted to one block)."""
        arr = np.zeros((1, self.block_size, 1, 1), np.float32)
        vals = [float(t) for t in tokens]
        arr.reshape(-1)[:len(vals)] = vals
        return arr

    def _tier_export_block(self, block: int, tokens) -> list:
        content = self._chunk_content(tokens)
        return [(content, np.ones((1,), np.float32))]

    def _tier_import_block(self, block: int, planes: list,
                           tokens) -> None:
        """Verify, don't store (the _import_pages idiom): restored
        content must equal the chunk the chain says this block holds —
        a corrupted host payload surfaces HERE, and the caller
        degrades to re-prefill."""
        (payload, _scales), = planes
        expect = self._chunk_content(tokens)
        got = np.rint(np.asarray(payload, np.float32))
        if not np.array_equal(got, np.rint(expect)):
            raise ValueError(
                "restored page content diverges (tier corruption)")

    def close(self) -> None:
        self._worker.close()
