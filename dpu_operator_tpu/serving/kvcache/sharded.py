"""Context-parallel paged KV (ISSUE 16): the K/V pools of ONE paged
replica partitioned across shard workers, behind the unchanged
two-phase submit/collect seam.

Composition of three existing planes, none of which changes shape:

  * the HOST plane (kvcache/executor.py) stays global on the
    coordinator: the allocator, leases, prefix tree and the per-step
    ``_StepPlan`` are exactly the single-worker ones — a sharded
    replica plans like one worker and stores like ``world`` of them;
  * the DEVICE plane splits into per-rank ``PagedRankStep`` partial
    steps (kvcache/paged.py) along the axis the replica's ``KVSpec``
    declares — "head" (Ulysses: all pages, a head slice of each;
    decode and k+1 speculative verify windows attend entirely locally
    and the per-step wire cost is context-independent) or "page"
    (ring: all heads of a block-id range; long prefill chunks scan
    only each rank's own pages and the coordinator folds the flash
    partials with ring_attention's online-softmax recurrence);
  * the SHARD plane's failure semantics (serving/sharded/synthetic.py)
    carry over typed: per-rank fault sites ``{site}{rank}.step``,
    generation-keyed poison, an ``outstanding()`` leak ledger, and a
    ``reset()`` re-rendezvous that RESPAWNS workers but KEEPS every
    rank's pool slice — which is exactly why a seize→requeue after a
    shard kill re-attaches leases with all ranks' pages intact.

Why resident context scales ~linearly with world: per appended token,
rank r stores ``1/world`` of the bytes (a head slice on the head
axis, a whole page every ``world``-th block on the page axis), so at
fixed per-rank HBM a ``world``-sharded replica holds ``world``x the
pages. ``KVSpec.rank_resident_nbytes`` is that arithmetic; bench
section 14 gates on it plus measured throughput.

Two backends, one duck: ``SyntheticKVShardSet`` (rank threads +
coordinator thread, in-process, tier-1's deterministic double) and
``KVShardProcessSet`` (real ``shard_worker --kv`` subprocesses over
the sharded plane's framed protocol — the slow-marked
world-equivalence smoke). Both produce token streams byte-identical
to the single-worker ``PagedKVExecutor``: the rank steps and the
coordinator finish close over literally the same cached weights
(``build_paged_params``), and per-head attention (head axis) or the
rank-ordered flash fold (page axis) recompose the same math.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import faults
from ...obs import trace as obs_trace
from ..sharded.synthetic import (ShardAborted, ShardStepError,
                                 ShardTimeout)
from .executor import KVExecutorBase, _StepPlan

__all__ = ["SyntheticKVShardSet", "KVShardProcessSet",
           "ShardedPagedKVExecutor", "resolve_shard_axis"]


def resolve_shard_axis(axis: str, heads: int, world: int) -> str:
    """The ring-vs-Ulysses selection rule (docs/serving.md): "auto"
    picks head sharding whenever the Ulysses constraint holds
    (``heads % world == 0`` — decode/verify windows then attend
    all-local), page sharding otherwise. Explicit "head"/"page" pass
    through; validity is the KVSpec's job."""
    if axis == "auto":
        return "head" if heads % world == 0 else "page"
    return axis


def _np(a, dtype) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype))


class _KVJob:
    """One submitted step: the plan payload plus per-rank reply slots
    — the reply-board idiom of the row plane's ``_StepHandle``."""

    __slots__ = ("gen", "step_no", "payload", "done", "tokens",
                 "error", "partials", "rank_err", "rank_ev", "t0")

    def __init__(self, gen: int, step_no: int, payload: dict,
                 world: int):
        self.gen = gen
        self.step_no = step_no
        self.payload = payload
        self.done = threading.Event()
        self.tokens: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.partials: Dict[int, tuple] = {}
        self.rank_err: Dict[int, Exception] = {}
        self.rank_ev = [threading.Event() for _ in range(world)]
        self.t0 = time.monotonic()

    def abort(self, exc: Exception) -> None:
        self.error = exc
        for ev in self.rank_ev:
            ev.set()
        self.done.set()


class _RankState:
    """One rank's pool slice + compiled partial step. Owned by the
    SET, not the worker thread: a re-rendezvous respawns the thread
    and hands it the SAME state — pages survive, which is the whole
    point of re-attach."""

    def __init__(self, step, lock: threading.Lock):
        self.step = step
        self.lock = lock
        (self.kpool, self.kscale,
         self.vpool, self.vscale) = step.init_pools()


class SyntheticKVShardSet:
    """In-process KV shard workers: ``world`` rank threads each
    owning one pool slice, plus a coordinator thread that sequences
    the token recurrence (rank partials → merge → finish → prev).
    Jax-real (the rank steps are compiled executables) but
    single-process — tier-1's deterministic double of a fabric of KV
    shard workers."""

    def __init__(self, spec, *, slots: int, num_blocks: int,
                 chunk: int, per_pos: bool = False,
                 kernel: Optional[str] = None,
                 interpret: Optional[bool] = None,
                 donate: Optional[bool] = None,
                 fault_site: str = "kvshard",
                 step_timeout_s: float = 30.0):
        from .paged import PagedFinishStep, PagedRankStep

        spec.validate_codec(spec.default_codec())
        self.spec = spec
        self.world = int(spec.world)
        self.slots = int(slots)
        self.num_blocks = int(num_blocks)
        self.chunk = int(chunk)
        self.per_pos = bool(per_pos)
        self.fault_site = str(fault_site)
        self.step_timeout_s = float(step_timeout_s)
        d = spec.heads * spec.d_head
        self._states: List[_RankState] = []
        for r in range(self.world):
            step = PagedRankStep(
                slots=slots, vocab=spec.vocab, d=d, heads=spec.heads,
                block_size=spec.block_size, num_blocks=num_blocks,
                max_blocks_per_req=spec.max_blocks_per_req,
                chunk=chunk, shard_axis=spec.shard_axis,
                head_bounds=spec.rank_heads(r),
                block_bounds=spec.rank_blocks(r, num_blocks),
                seed=spec.seed, pool_dtype=spec.pool_dtype,
                kernel=kernel, interpret=interpret, donate=donate)
            self._states.append(_RankState(step, threading.Lock()))
        self._finish = PagedFinishStep(
            slots=slots, vocab=spec.vocab, d=d,
            block_size=spec.block_size,
            max_blocks_per_req=spec.max_blocks_per_req, chunk=chunk,
            seed=spec.seed, per_pos=per_pos)
        self.draft_params = self._finish.draft_params
        self._lock = threading.Lock()
        self._gen = 0
        self._closed = False
        self._poisoned: Optional[Exception] = None
        self._prev = np.zeros((self.slots,), np.int32)
        self._outstanding: set = set()
        self.resets = 0
        self._spawn()

    # -- worker lifecycle -----------------------------------------------------

    def _spawn(self) -> None:
        gen = self._gen
        self._rank_qs = [queue.Queue() for _ in range(self.world)]
        self._coord_q: "queue.Queue" = queue.Queue()
        self._threads = []
        for r in range(self.world):
            t = threading.Thread(target=self._rank_loop,
                                 args=(r, gen), daemon=True,
                                 name=f"kvshard-{r}")
            t.start()
            self._threads.append(t)
        self._coord = threading.Thread(target=self._coord_loop,
                                       args=(gen,), daemon=True,
                                       name="kvshard-coord")
        self._coord.start()

    def reset(self) -> None:
        """Re-rendezvous: bump the generation (a possibly-hung worker
        wakes to a stale gen and drops its job), abort every
        outstanding step, respawn the worker threads — and KEEP every
        rank's pools. The surviving pages are what a post-seize
        re-attach resumes on."""
        t0 = time.monotonic()
        with self._lock:
            # Revivable after close() — the _GuardedWorker discipline:
            # ReplicaPool.stop() closes every executor, and the next
            # pool's batcher start re-opens it through reset().
            self._closed = False
            self._gen += 1
            self._poisoned = None
            stale = set(self._outstanding)
            for job in stale:
                job.abort(ShardAborted(
                    f"kv shard set reset at gen {self._gen}"))
            self._outstanding.difference_update(stale)
            self._prev = np.zeros((self.slots,), np.int32)
            self.resets += 1
            self._spawn()
        obs_trace.get_tracer().record_span(
            "kvshard.rendezvous", t0, time.monotonic(),
            attrs={"world": self.world, "resets": self.resets,
                   "gen": self._gen})

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._gen += 1
            for job in set(self._outstanding):
                job.abort(ShardAborted("kv shard set closed"))
            self._outstanding.clear()

    def live_ranks(self) -> List[int]:
        return [r for r, t in enumerate(self._threads)
                if t.is_alive()]

    def outstanding(self) -> int:
        """Leak ledger: steps submitted and never collected nor
        aborted. Clean teardown means 0 — the board sibling of the
        allocator's ``assert_clean``."""
        return len(self._outstanding)

    # -- the two-phase backend contract ---------------------------------------

    def submit(self, payload: dict) -> _KVJob:
        with self._lock:
            if self._closed:
                raise ShardAborted("kv shard set is closed")
            job = _KVJob(self._gen, int(payload["step_no"]), payload,
                         self.world)
            if self._poisoned is not None:
                job.abort(ShardAborted(
                    f"kv shard gen {self._gen} poisoned: "
                    f"{self._poisoned}"))
                return job
            self._outstanding.add(job)
            q = self._coord_q
        # Enqueue outside the lock (GL004). If a reset slips between,
        # the job was already aborted from _outstanding and the stale
        # generation's coordinator drops it on its gen check.
        q.put(job)
        return job

    def collect(self, job: _KVJob, timeout: float) -> np.ndarray:
        ok = job.done.wait(timeout)
        with self._lock:
            self._outstanding.discard(job)
        if not ok:
            raise ShardTimeout(
                f"kv shard step {job.step_no} not done in "
                f"{timeout:.1f}s (live ranks: {self.live_ranks()})")
        if job.error is not None:
            raise job.error
        return job.tokens

    # -- worker loops ---------------------------------------------------------

    def _rank_loop(self, rank: int, gen: int) -> None:
        st = self._states[rank]
        q = self._rank_qs[rank]
        site = f"{self.fault_site}{rank}.step"
        while not self._closed and self._gen == gen:
            try:
                got = q.get(timeout=0.2)
            except queue.Empty:
                continue
            job, prev = got
            if job.gen != self._gen or job.error is not None:
                job.rank_ev[rank].set()
                continue
            try:
                faults.fire(site, attrs={"rank": rank,
                                         "step": job.step_no})
                p = job.payload
                import jax.numpy as jnp

                with st.lock:
                    out = st.step(
                        st.kpool, st.kscale, st.vpool, st.vscale,
                        jnp.asarray(prev), jnp.asarray(p["host_tok"]),
                        jnp.asarray(p["use_host"]),
                        jnp.asarray(p["ctx"]),
                        jnp.asarray(p["n_new"]),
                        jnp.asarray(p["tables"]))
                    (st.kpool, st.kscale, st.vpool,
                     st.vscale) = out[:4]
                    job.partials[rank] = tuple(
                        np.asarray(a) for a in out[4:])
            except Exception as e:  # noqa: BLE001 - posted typed
                job.rank_err[rank] = e
            job.rank_ev[rank].set()

    def _coord_loop(self, gen: int) -> None:
        """Sequences the token recurrence: rank partials for step N
        merge and finish BEFORE step N+1's rank work is released (the
        single-worker device recurrence, reconstructed across
        workers). Pipelining survives upward: submit() never blocks —
        the batcher's host bookkeeping overlaps all of this."""
        while not self._closed and self._gen == gen:
            try:
                job = self._coord_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if job.gen != self._gen or job.error is not None:
                continue
            prev = self._prev
            for r in range(self.world):
                self._rank_qs[r].put((job, prev))
            deadline = time.monotonic() + self.step_timeout_s
            err: Optional[Exception] = None
            for r in range(self.world):
                if not job.rank_ev[r].wait(
                        max(0.0, deadline - time.monotonic())):
                    err = ShardTimeout(
                        f"rank {r} silent for step {job.step_no}",
                        rank=r)
                    break
                if r in job.rank_err:
                    cause = job.rank_err[r]
                    err = ShardStepError(
                        f"rank {r} failed step {job.step_no}: "
                        f"{cause}", rank=r)
                    err.__cause__ = cause
                    break
            if job.gen != self._gen:
                continue
            if err is not None:
                with self._lock:
                    # Permanent poison for this generation — the
                    # reduce-board rule: a half-stepped pool set must
                    # never serve another step until re-rendezvous.
                    self._poisoned = err
                job.abort(err)
                continue
            tokens = self._merge_and_finish(job, prev)
            if not self.per_pos:
                self._prev = tokens
            job.tokens = tokens
            job.done.set()

    def _merge_and_finish(self, job: _KVJob,
                          prev: np.ndarray) -> np.ndarray:
        from ...parallel.ring_attention import merge_partial_softmax
        from ...parallel.ulysses_attention import concat_head_partials
        import jax.numpy as jnp

        S, C = self.slots, self.chunk
        H, dh = self.spec.heads, self.spec.d_head
        if self.spec.shard_axis == "head":
            o = concat_head_partials(
                [job.partials[r][0] for r in range(self.world)])
        else:
            merged = merge_partial_softmax(
                [job.partials[r] for r in range(self.world)])
            o = np.transpose(merged, (0, 2, 1, 3))  # [S,C,H,dh]
        p = job.payload
        return np.asarray(self._finish(
            jnp.asarray(prev), jnp.asarray(p["host_tok"]),
            jnp.asarray(p["use_host"]), jnp.asarray(p["ctx"]),
            jnp.asarray(p["n_new"]), jnp.asarray(
                o.reshape(S, C, H * dh))))

    # -- page export/import (per-rank plane sets) -----------------------------

    def export_rank_pages(self, blocks: Sequence[int]
                          ) -> Tuple[list, List[int]]:
        """Gather the written pages rank by rank:
        ``([(k, ksc), (v, vsc)] per rank, rank_block_counts)``. Head
        axis ships every rank's head slice of ALL requested blocks;
        page axis ships each rank's OWNED subset (in request order) —
        the per-rank point-to-point sets the disagg stream frames
        with ``KVSpec.rank_view`` geometry."""
        import jax.numpy as jnp

        blocks = [int(b) for b in blocks]
        planes, counts = [], []
        for r, st in enumerate(self._states):
            mine = self._rank_owned(r, blocks)
            idx = jnp.asarray(_np([blocks[j] for j in mine]
                                  if self.spec.shard_axis == "page"
                                  else blocks, np.int32))
            if self.spec.shard_axis == "page":
                lo, _ = self.spec.rank_blocks(r, self.num_blocks)
                idx = idx - lo
            with st.lock:
                planes.append([
                    (np.asarray(st.kpool[idx]),
                     np.asarray(st.kscale[idx])),
                    (np.asarray(st.vpool[idx]),
                     np.asarray(st.vscale[idx]))])
            counts.append(len(mine) if self.spec.shard_axis == "page"
                          else len(blocks))
        return planes, counts

    def import_rank_pages(self, blocks: Sequence[int],
                          rank_planes: list, meta: dict) -> None:
        """Scatter per-SOURCE-rank plane sets into this set's pools at
        freshly acquired block ids. Head axis: source rank r's slice
        IS dest rank r's slice (the hello check pinned world and
        axis). Page axis: reassemble request order from the source's
        ``rank_index``, then re-scatter by DEST ownership — fresh ids
        land wherever the dest partition puts them."""
        import jax.numpy as jnp

        blocks = [int(b) for b in blocks]
        if self.spec.shard_axis == "head":
            for r, st in enumerate(self._states):
                (k, ksc), (v, vsc) = rank_planes[r]
                idx = jnp.asarray(_np(blocks, np.int32))
                with st.lock:
                    st.kpool = st.kpool.at[idx].set(
                        jnp.asarray(k, st.kpool.dtype))
                    st.kscale = st.kscale.at[idx].set(
                        jnp.asarray(ksc))
                    st.vpool = st.vpool.at[idx].set(
                        jnp.asarray(v, st.vpool.dtype))
                    st.vscale = st.vscale.at[idx].set(
                        jnp.asarray(vsc))
            return
        # Page axis: request-order reassembly, then dest scatter.
        order = meta["rank_index"]
        n = len(blocks)
        full: List[Optional[tuple]] = [None] * n
        for r, mine in enumerate(order):
            (k, ksc), (v, vsc) = rank_planes[r]
            for i, j in enumerate(mine):
                full[j] = (k[i], ksc[i], v[i], vsc[i])
        for r, st in enumerate(self._states):
            lo, _ = self.spec.rank_blocks(r, self.num_blocks)
            mine = self._rank_owned(r, blocks)
            if not mine:
                continue
            idx = jnp.asarray(_np([blocks[j] - lo for j in mine],
                                  np.int32))
            k = np.stack([full[j][0] for j in mine])
            ksc = np.stack([full[j][1] for j in mine])
            v = np.stack([full[j][2] for j in mine])
            vsc = np.stack([full[j][3] for j in mine])
            with st.lock:
                st.kpool = st.kpool.at[idx].set(
                    jnp.asarray(k, st.kpool.dtype))
                st.kscale = st.kscale.at[idx].set(jnp.asarray(ksc))
                st.vpool = st.vpool.at[idx].set(
                    jnp.asarray(v, st.vpool.dtype))
                st.vscale = st.vscale.at[idx].set(jnp.asarray(vsc))

    def _rank_owned(self, rank: int, blocks: List[int]) -> List[int]:
        """Indices (into ``blocks``) of the entries rank's pool holds
        — spec-derived bounds, request order preserved."""
        lo, hi = self.spec.rank_blocks(rank, self.num_blocks)
        return [j for j, b in enumerate(blocks) if lo <= b < hi]


class KVShardProcessSet:
    """Real-subprocess KV shard workers (``shard_worker --kv``): the
    same backend duck as ``SyntheticKVShardSet`` with each rank's
    pool slice and partial step living in its own OS process, frames
    over the sharded plane's ``protocol.py`` transport. The
    coordinator (in-process thread) still owns merge/finish and the
    token recurrence — workers are stateless but for their pools,
    exactly the control/bulk split the row-plane worker uses.

    Scope: the world-equivalence smoke (decode paths). Page
    export/import stays on the in-process backend — migrating a
    sharded lease out of subprocess pools is ROADMAP item 2 (tiering)
    territory."""

    def __init__(self, spec, *, slots: int, num_blocks: int,
                 chunk: int, per_pos: bool = False,
                 step_timeout_s: float = 60.0,
                 spawn_timeout_s: float = 120.0):
        import socket
        import subprocess
        import sys

        from ..sharded.protocol import recv_msg, send_msg
        from .paged import PagedFinishStep

        self._send, self._recv = send_msg, recv_msg
        self.spec = spec
        self.world = int(spec.world)
        self.slots = int(slots)
        self.num_blocks = int(num_blocks)
        self.chunk = int(chunk)
        self.per_pos = bool(per_pos)
        self.step_timeout_s = float(step_timeout_s)
        d = spec.heads * spec.d_head
        self._finish = PagedFinishStep(
            slots=slots, vocab=spec.vocab, d=d,
            block_size=spec.block_size,
            max_blocks_per_req=spec.max_blocks_per_req, chunk=chunk,
            seed=spec.seed, per_pos=per_pos)
        self.draft_params = self._finish.draft_params
        self._lock = threading.Lock()
        self._gen = 0
        self._closed = False
        self._poisoned: Optional[Exception] = None
        self._prev = np.zeros((self.slots,), np.int32)
        self._outstanding: set = set()
        self.resets = 0
        self._procs, self._socks = [], []
        listeners = []
        for r in range(self.world):
            srv = socket.socket()
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            listeners.append(srv)
            cmd = [sys.executable, "-m",
                   "dpu_operator_tpu.serving.sharded.shard_worker",
                   "--kv", "--rank", str(r),
                   "--connect",
                   f"127.0.0.1:{srv.getsockname()[1]}",
                   "--slots", str(slots),
                   "--num-blocks", str(num_blocks),
                   "--chunk", str(chunk),
                   "--kv-spec", _spec_argv(spec)]
            self._procs.append(subprocess.Popen(cmd))
        for r, srv in enumerate(listeners):
            srv.settimeout(spawn_timeout_s)
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                self.close()
                raise ShardTimeout(
                    f"kv shard worker {r} never connected", rank=r)
            finally:
                srv.close()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                            1)
            self._socks.append(conn)
            hello, _ = recv_msg(conn, spawn_timeout_s)
            if hello.get("op") != "hello":
                raise ShardStepError(
                    f"rank {r} bad hello {hello}", rank=r)
        self._coord_q: "queue.Queue" = queue.Queue()
        self._spawn_coord()

    def _spawn_coord(self) -> None:
        gen = self._gen
        self._coord = threading.Thread(target=self._coord_loop,
                                       args=(gen,), daemon=True,
                                       name="kvproc-coord")
        self._coord.start()

    def reset(self) -> None:
        with self._lock:
            self._gen += 1
            self._poisoned = None
            stale = set(self._outstanding)
            for job in stale:
                job.abort(ShardAborted("kv proc set reset"))
            self._outstanding.difference_update(stale)
            self._prev = np.zeros((self.slots,), np.int32)
            self.resets += 1
            self._coord_q = queue.Queue()
            self._spawn_coord()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._gen += 1
            for job in set(self._outstanding):
                job.abort(ShardAborted("kv proc set closed"))
            self._outstanding.clear()
        for s in getattr(self, "_socks", ()):
            try:
                self._send(s, {"op": "close"})
                s.close()
            except Exception:
                pass
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()

    def live_ranks(self) -> List[int]:
        return [r for r, p in enumerate(self._procs)
                if p.poll() is None]

    def outstanding(self) -> int:
        return len(self._outstanding)

    def submit(self, payload: dict) -> _KVJob:
        with self._lock:
            if self._closed:
                raise ShardAborted("kv proc set is closed")
            job = _KVJob(self._gen, int(payload["step_no"]), payload,
                         self.world)
            if self._poisoned is not None:
                job.abort(ShardAborted(
                    f"gen poisoned: {self._poisoned}"))
                return job
            self._outstanding.add(job)
            q = self._coord_q
        # Enqueue outside the lock (GL004): same discipline as the
        # synthetic set's submit.
        q.put(job)
        return job

    def collect(self, job: _KVJob, timeout: float) -> np.ndarray:
        ok = job.done.wait(timeout)
        with self._lock:
            self._outstanding.discard(job)
        if not ok:
            raise ShardTimeout(
                f"kv proc step {job.step_no} not done in "
                f"{timeout:.1f}s (live: {self.live_ranks()})")
        if job.error is not None:
            raise job.error
        return job.tokens

    def _coord_loop(self, gen: int) -> None:
        S, C = self.slots, self.chunk
        H, dh = self.spec.heads, self.spec.d_head
        B = self.spec.max_blocks_per_req
        head = self.spec.shard_axis == "head"
        while not self._closed and self._gen == gen:
            try:
                job = self._coord_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if job.gen != self._gen or job.error is not None:
                continue
            p = job.payload
            prev = self._prev
            payload = b"".join([
                _np(prev, np.int32).tobytes(),
                _np(p["host_tok"], np.int32).tobytes(),
                _np(p["use_host"], np.uint8).tobytes(),
                _np(p["ctx"], np.int32).tobytes(),
                _np(p["n_new"], np.int32).tobytes(),
                _np(p["tables"], np.int32).tobytes()])
            err: Optional[Exception] = None
            try:
                for s in self._socks:
                    self._send(s, {"op": "step",
                                   "step": job.step_no}, payload)
                for r, s in enumerate(self._socks):
                    reply, buf = self._recv(s, self.step_timeout_s)
                    if reply.get("op") != "partial":
                        raise ShardStepError(
                            f"rank {r} replied {reply}", rank=r)
                    hr = int(reply["heads"])
                    if head:
                        o = np.frombuffer(
                            buf, np.float32).reshape(S, C, hr, dh)
                        job.partials[r] = (o,)
                    else:
                        stats = S * H * C
                        m = np.frombuffer(
                            buf, np.float32,
                            count=stats).reshape(S, H, C)
                        l = np.frombuffer(
                            buf, np.float32, count=stats,
                            offset=4 * stats).reshape(S, H, C)
                        o = np.frombuffer(
                            buf, np.float32,
                            offset=8 * stats).reshape(S, H, C, dh)
                        job.partials[r] = (m, l, o)
            except Exception as e:  # noqa: BLE001 - typed upward
                err = e if isinstance(e, ShardStepError) else \
                    ShardStepError(f"kv proc step failed: {e}")
                err.__cause__ = e
            if job.gen != self._gen:
                continue
            if err is not None:
                with self._lock:
                    self._poisoned = err
                job.abort(err)
                continue
            tokens = self._merge_and_finish(job, prev)
            if not self.per_pos:
                self._prev = tokens
            job.tokens = tokens
            job.done.set()
        _ = B  # geometry pinned by the spec argv, kept for clarity

    # Same fold as the synthetic set — one definition would be nicer
    # still, but the two classes share it via this module.
    _merge_and_finish = SyntheticKVShardSet._merge_and_finish


def _spec_argv(spec) -> str:
    """KVSpec → one argv token for the worker (k=v CSV over the
    fingerprint) — the worker rebuilds the spec and derives its OWN
    slice bounds from it, never receiving raw geometry."""
    return ",".join(f"{k}={v}" for k, v in
                    sorted(spec.fingerprint().items()))


def spec_from_argv(text: str):
    from ..disagg.spec import KVSpec

    kw: dict = {}
    for part in text.split(","):
        k, v = part.split("=", 1)
        kw[k] = v if k in ("model", "pool_dtype", "shard_axis") \
            else int(v)
    return KVSpec(**kw)


def serve_kv_rank(sock, rank: int, spec, *, slots: int,
                  num_blocks: int, chunk: int) -> None:
    """The ``shard_worker --kv`` serve loop: one rank's pool slice +
    partial step behind reset/step/close frames. Geometry comes from
    the spec ONLY (rank_heads/rank_blocks — the GL018 discipline
    holds across the process boundary)."""
    from ..sharded.protocol import recv_msg, send_msg
    from .paged import PagedRankStep

    import jax.numpy as jnp

    d = spec.heads * spec.d_head
    step = PagedRankStep(
        slots=slots, vocab=spec.vocab, d=d, heads=spec.heads,
        block_size=spec.block_size, num_blocks=num_blocks,
        max_blocks_per_req=spec.max_blocks_per_req, chunk=chunk,
        shard_axis=spec.shard_axis,
        head_bounds=spec.rank_heads(rank),
        block_bounds=spec.rank_blocks(rank, num_blocks),
        seed=spec.seed, pool_dtype=spec.pool_dtype, kernel="xla")
    kpool, kscale, vpool, vscale = step.init_pools()
    S, C, B = slots, chunk, spec.max_blocks_per_req
    send_msg(sock, {"op": "hello", "rank": rank,
                    "spec": spec.fingerprint()})
    sizes = np.cumsum([S * 4, S * C * 4, S, S * 4, S * 4,
                       S * B * 4])
    while True:
        msg, buf = recv_msg(sock, timeout=None)
        op = msg.get("op")
        if op == "close":
            return
        if op == "reset":
            kpool, kscale, vpool, vscale = step.init_pools()
            send_msg(sock, {"op": "reset-ok"})
            continue
        if op != "step":
            send_msg(sock, {"op": "error",
                            "error": f"unknown op {op!r}"})
            continue
        prev = np.frombuffer(buf[:sizes[0]], np.int32)
        host_tok = np.frombuffer(
            buf[sizes[0]:sizes[1]], np.int32).reshape(S, C)
        use_host = np.frombuffer(
            buf[sizes[1]:sizes[2]], np.uint8).astype(bool)
        ctx = np.frombuffer(buf[sizes[2]:sizes[3]], np.int32)
        n_new = np.frombuffer(buf[sizes[3]:sizes[4]], np.int32)
        tables = np.frombuffer(
            buf[sizes[4]:sizes[5]], np.int32).reshape(S, B)
        out = step(kpool, kscale, vpool, vscale,
                   jnp.asarray(prev), jnp.asarray(host_tok),
                   jnp.asarray(use_host), jnp.asarray(ctx),
                   jnp.asarray(n_new), jnp.asarray(tables))
        kpool, kscale, vpool, vscale = out[:4]
        parts = [np.ascontiguousarray(np.asarray(a, np.float32))
                 for a in out[4:]]
        send_msg(sock, {"op": "partial", "step": msg.get("step"),
                        "heads": step.pool_heads}, *parts)


class ShardedPagedKVExecutor(KVExecutorBase):
    """Context-parallel ``PagedKVExecutor``: same host plane, same
    modes (pipelined / sync / speculative), same submit/collect seam
    — the K/V pools live sliced across a KV shard set. The batcher,
    supervisor, chaos matrix and speculative mode ride it untouched;
    token streams are byte-identical to the single-worker executor
    on the same trace (the tier-1 equivalence lane's contract)."""

    def __init__(self, slots: int = 4, vocab: int = 64, d: int = 16,
                 heads: int = 2, block_size: int = 4,
                 num_blocks: int = 128, max_blocks_per_req: int = 16,
                 prefill_chunk: int = 8,
                 prefill_budget: Optional[int] = None,
                 prefix_cache: bool = True, seed: int = 0,
                 mode: str = "pipelined", warmup: bool = True,
                 kernel: Optional[str] = None,
                 pool_dtype: str = "int8",
                 interpret: Optional[bool] = None,
                 spec_k: int = 4, draft=None,
                 world: int = 2, shard_axis: str = "auto",
                 fault_site: str = "kvshard",
                 backend: Optional[object] = None,
                 step_timeout_s: float = 30.0):
        if mode not in ("pipelined", "sync", "speculative"):
            raise ValueError(f"mode must be pipelined|sync|"
                             f"speculative, got {mode!r}")
        speculative = mode == "speculative"
        super().__init__(slots, vocab=vocab, block_size=block_size,
                         num_blocks=num_blocks,
                         max_blocks_per_req=max_blocks_per_req,
                         prefill_chunk=prefill_chunk,
                         prefill_budget=prefill_budget,
                         prefix_cache=prefix_cache,
                         pipelined=mode == "pipelined")
        from ..spec import SpecConfig, TruncatedDraft
        from ..disagg.spec import KVSpec

        self._seed = int(seed)
        axis = resolve_shard_axis(shard_axis, heads, world)
        self._kvspec = KVSpec(
            model="paged", block_size=block_size, heads=heads,
            d_head=d // heads, vocab=vocab,
            max_blocks_per_req=max_blocks_per_req,
            pool_dtype=pool_dtype, planes=2, seed=seed,
            shard_axis=axis, world=world)
        self._timeout = float(step_timeout_s)
        if backend is None:
            backend = SyntheticKVShardSet(
                self._kvspec, slots=slots, num_blocks=num_blocks,
                chunk=prefill_chunk, per_pos=speculative,
                kernel=kernel, interpret=interpret,
                fault_site=fault_site,
                step_timeout_s=step_timeout_s)
        self.shards = backend
        if speculative:
            if draft is None:
                draft = TruncatedDraft(
                    *backend.draft_params, spec_k, slots)
            self._install_spec(SpecConfig(draft, spec_k))
        if warmup:
            self.collect(self.submit((), gen=self._gen))
            self.reset()

    # -- backend hooks --------------------------------------------------------

    @property
    def world(self) -> int:
        return self._kvspec.world

    def _backend_reset(self) -> None:
        # Pools survive on every rank (the shard set's reset keeps
        # _RankState); only the recurrence and in-flight steps drop.
        self.shards.reset()

    def _spec_fields(self) -> dict:
        sp = self._kvspec
        return dict(model=sp.model, block_size=sp.block_size,
                    heads=sp.heads, d_head=sp.d_head, vocab=sp.vocab,
                    max_blocks_per_req=sp.max_blocks_per_req,
                    pool_dtype=sp.pool_dtype, planes=sp.planes,
                    seed=sp.seed, shard_axis=sp.shard_axis,
                    world=sp.world)

    def _dispatch(self, plan: _StepPlan):
        return self.shards.submit(dict(
            step_no=plan.step_no, host_tok=plan.host_tok,
            use_host=plan.use_host, ctx=plan.ctx, n_new=plan.n_new,
            tables=plan.tables))

    def _materialize(self, raw) -> np.ndarray:
        return self.shards.collect(raw, timeout=self._timeout)

    def close(self) -> None:
        self.shards.close()

    # -- per-rank observability ----------------------------------------------

    def kv_rank_stats(self) -> Dict[int, Dict[str, int]]:
        """Per-rank resident page counts for the ``rank``-labelled
        ``serving_kv_blocks`` series: page axis counts each rank's
        owned slice of the allocator's live blocks; head axis pins
        every block on every rank (each holds its head slice of it).
        Derived from the spec's partition + the allocator's refcounts
        — the pools themselves are never touched at scrape time."""
        spec, alloc = self._kvspec, self.allocator
        used_ids = [b for b in range(self.num_blocks)
                    if alloc.refcount(b) > 0]
        out: Dict[int, Dict[str, int]] = {}
        for r in range(spec.world):
            lo, hi = spec.rank_blocks(r, self.num_blocks)
            used = (len([b for b in used_ids if lo <= b < hi])
                    if spec.shard_axis == "page" else len(used_ids))
            out[r] = {"blocks_used": used,
                      "blocks_free": (hi - lo) - used
                      if spec.shard_axis == "page"
                      else self.num_blocks - used}
        return out

    # -- per-rank transfer plane ----------------------------------------------

    def kv_export(self, req, detach: dict):
        meta, planes = super().kv_export(req, detach)
        n_blocks = int(meta["n_blocks"])
        lease = detach["lease"]
        blocks = [int(b) for b in lease.blocks[:n_blocks]]
        meta["rank_blocks"] = self._rank_counts
        meta["rank_index"] = [
            self.shards._rank_owned(r, blocks)
            if self._kvspec.shard_axis == "page"
            else list(range(n_blocks))
            for r in range(self._kvspec.world)]
        return meta, planes

    def _export_pages(self, blocks, req, n_tokens: int) -> list:
        planes, counts = self.shards.export_rank_pages(blocks)
        # Stashed for kv_export's meta (same _slock'd call chain).
        self._rank_counts = counts
        return planes

    def _import_pages(self, blocks, planes: list,
                      meta: dict) -> None:
        self.shards.import_rank_pages(blocks, planes, meta)
