"""Host-RAM KV tier under the paged allocator (ISSUE 17).

The HBM pool is the only place a block can be ATTENDED; this module
adds the place a cold block can be PARKED. When the PrefixTree's LRU
leaf scan would drop a cached block on the floor, the executor's spill
hook hands its bytes here instead (evict-to-tier), and a later prefix
hit restores them into a freshly acquired HBM block before prefill of
only the uncached suffix. Three properties make this safe enough to
sit under the allocator:

  * **Byte-exact by construction.** Spill/restore moves the pool's
    already-quantized int8 codes + per-block scales verbatim — the
    same representation ``kv_export`` ships across replicas — so a
    restored block is bit-identical to the block that was evicted.
    There is no re-quantization step to drift through.
  * **Chained-hash re-verification at every restore.** A tier entry
    is content-addressed by the PrefixTree's chained key (node key =
    H(parent_key, block token ids)), and ``verify_block_tokens`` —
    the one blessed helper, see GL019 — re-derives that key from the
    tokens the REQUEST brought before any restored bytes are
    published into the tree. A corrupted, recycled or colliding host
    entry therefore degrades to re-prefill; it can never serve wrong
    KV.
  * **The same leak discipline as the allocator.** Restores pin their
    entry under an owner-tagged tier lease (``checkout``/``checkin``)
    recorded in a ledger with ``leaked()``/``assert_clean()``
    mirroring ``KVBlockAllocator``'s — "both ledgers clean" is one
    teardown assertion away in every test.

Capacity is a HARD host-bytes budget: a spill that does not fit after
LRU-evicting unpinned tier entries falls back to today's behavior
(drop on evict, counted), so the tier can only ever add reuse, never
unbounded host growth.
"""

from __future__ import annotations

import threading
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .allocator import PrefixTree

__all__ = ["HostKVTier", "ParkedKV", "TierEntry",
           "verify_block_tokens"]


def verify_block_tokens(parent_key: str, tokens: Sequence[int],
                        key: str,
                        stored_tokens: Optional[Sequence[int]] = None
                        ) -> bool:
    """THE chained-hash token re-verification (GL019's blessed helper).

    Every path that publishes foreign bytes into the prefix tree — a
    host-tier restore, a cross-replica pull import — must pass the
    claimed chain key through here before insert: the key is re-derived
    from ``parent_key`` and the token ids the REQUEST (not the claimant)
    brought, and, when the claimant also carries its own token ids
    (``stored_tokens``), those must match too. A mismatch means the
    entry is stale, corrupted, or a hash collision — all of which must
    degrade to re-prefill, never to serving someone else's KV."""
    chunk = tuple(int(t) for t in tokens)
    if PrefixTree._key(parent_key, chunk) != key:
        return False
    if stored_tokens is not None:
        if tuple(int(t) for t in stored_tokens) != chunk:
            return False
    return True


class TierEntry:
    """One spilled block: the chain identity (key/parent/tokens) plus
    the verbatim plane bytes exactly as the backend exported them."""

    __slots__ = ("key", "parent", "tokens", "planes", "nbytes",
                 "last_used", "pins")

    def __init__(self, key: str, parent: str, tokens: Tuple[int, ...],
                 planes: list, nbytes: int, last_used: int):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.planes = planes
        self.nbytes = nbytes
        self.last_used = last_used
        self.pins = 0


def _planes_nbytes(planes: list) -> int:
    total = 0
    for pair in planes:
        for arr in pair:
            total += int(np.asarray(arr).nbytes)
    return total


class HostKVTier:
    """LRU host-RAM store of spilled prefix blocks under a hard byte
    budget, with owner-tagged restore leases and a leak ledger."""

    def __init__(self, budget_bytes: int):
        if budget_bytes < 1:
            raise ValueError(
                f"tier budget must be >= 1 byte, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: Dict[str, TierEntry] = {}
        self._clock = 0
        self.bytes_used = 0
        # Lifetime counters for kv_stats()/bench decomposition.
        self.spilled_blocks = 0
        self.spilled_bytes = 0
        self.restored_blocks = 0
        self.restored_bytes = 0
        self.dropped_blocks = 0   # budget overflow → drop-on-evict
        self.evicted_blocks = 0   # tier-LRU eviction to admit a spill
        self.corrupt_blocks = 0   # failed re-verification at restore
        # owner -> Counter(entry key -> pin count): the tier lease
        # ledger, same shape as KVBlockAllocator._owners.
        self._leases: Dict[str, Counter] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- spill side (called from the PrefixTree evict hook) -------------------

    def put(self, key: str, parent: str, tokens: Sequence[int],
            planes: list) -> bool:
        """Admit one spilled block. Evicts UNPINNED tier-LRU entries to
        fit; returns False (drop-on-evict, counted) when the block
        cannot fit even then — oversized block, or every resident byte
        is pinned by in-flight restores."""
        chunk = tuple(int(t) for t in tokens)
        nbytes = _planes_nbytes(planes)
        with self._lock:
            self._clock += 1
            prev = self._entries.get(key)
            if prev is not None:
                # Re-spill of a restored-then-re-evicted block: the
                # bytes are identical by construction, just refresh.
                prev.last_used = self._clock
                return True
            if nbytes > self.budget_bytes:
                self.dropped_blocks += 1
                return False
            while self.bytes_used + nbytes > self.budget_bytes:
                victim = min(
                    (e for e in self._entries.values() if e.pins == 0),
                    key=lambda e: e.last_used, default=None)
                if victim is None:
                    self.dropped_blocks += 1
                    return False
                del self._entries[victim.key]
                self.bytes_used -= victim.nbytes
                self.evicted_blocks += 1
            self._entries[key] = TierEntry(key, parent, chunk, planes,
                                           nbytes, self._clock)
            self.bytes_used += nbytes
            self.spilled_blocks += 1
            self.spilled_bytes += nbytes
            return True

    # -- restore side ---------------------------------------------------------

    def checkout(self, key: str, owner: str) -> Optional[TierEntry]:
        """Pin `key` for a restore under an owner-tagged tier lease.
        The pin keeps the entry out of tier-LRU eviction until the
        matching ``checkin`` — the restore window's use-after-free
        guard, recorded in the leak ledger."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            entry.pins += 1
            self._clock += 1
            entry.last_used = self._clock
            self._leases.setdefault(owner, Counter())[key] += 1
            return entry

    def checkin(self, key: str, owner: str, restored: bool = False,
                corrupt: bool = False) -> None:
        """Return a checkout. ``restored`` credits the restore
        counters; ``corrupt`` additionally DROPS the entry — a block
        that failed re-verification must never be served again.
        Checking in a lease the owner does not hold raises (the
        double-free discipline, same as the allocator's)."""
        with self._lock:
            held = self._leases.get(owner)
            if held is None or held[key] <= 0:
                raise ValueError(
                    f"tier checkin of {key[:12]!r} not held by "
                    f"{owner!r}")
            held[key] -= 1
            if held[key] <= 0:
                del held[key]
            if not held:
                del self._leases[owner]
            entry = self._entries.get(key)
            if entry is None:
                return
            entry.pins -= 1
            if restored:
                self.restored_blocks += 1
                self.restored_bytes += entry.nbytes
            if corrupt:
                self.corrupt_blocks += 1
                del self._entries[key]
                self.bytes_used -= entry.nbytes

    # -- accounting -----------------------------------------------------------

    def keys(self) -> List[str]:
        """Resident entry keys — the gossip publisher's host-tier half."""
        with self._lock:
            return list(self._entries)

    def leaked(self, ignore: Sequence[str] = ()) -> Dict[str, List[str]]:
        """Tier leases still pinned per owner. Empty means every
        checkout was checked back in."""
        with self._lock:
            return {o: sorted(c.elements())
                    for o, c in self._leases.items()
                    if o not in ignore and c}

    def assert_clean(self, ignore: Sequence[str] = ()) -> None:
        """Teardown contract: zero leaked tier leases (the second
        ledger in 'both leak ledgers clean')."""
        leaks = self.leaked(ignore)
        if leaks:
            raise AssertionError(f"leaked tier leases: {leaks}")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes_used": self.bytes_used,
                    "budget_bytes": self.budget_bytes,
                    "spilled_blocks": self.spilled_blocks,
                    "spilled_bytes": self.spilled_bytes,
                    "restored_blocks": self.restored_blocks,
                    "restored_bytes": self.restored_bytes,
                    "dropped_blocks": self.dropped_blocks,
                    "evicted_blocks": self.evicted_blocks,
                    "corrupt_blocks": self.corrupt_blocks}

    def flush(self) -> int:
        """Drop every UNPINNED entry (teardown / tests)."""
        with self._lock:
            victims = [e for e in self._entries.values()
                       if e.pins == 0]
            for e in victims:
                del self._entries[e.key]
                self.bytes_used -= e.nbytes
            return len(victims)


class ParkedKV:
    """A preempted request's KV, parked in the host tier (ISSUE 20).

    When the batcher preempts a batch-class occupant, the executor
    spills its settled KV blocks into the HostKVTier and pins each
    chain entry under an owner-tagged ``checkout`` — then rides THIS
    object on ``req.kv_lease`` through the requeue. It duck-types
    ``KVLease`` for every consumer on that path:

      * the queue's requeue trace reads ``blocks`` (here: the pinned
        chain keys, in chain order);
      * ``resumable`` tells the resume path whether the pins are still
        held;
      * ``on_request_settled()`` — the ``finish()`` choke point —
        releases the pins exactly once, so a request that dies while
        parked (deadline, drain, server stop) can never leak a tier
        lease;
      * ``release()`` is idempotent, and ``HostKVTier.checkin`` is
        safe after a ``flush`` dropped the entry (the ledger, not the
        entry, is what must balance).

    The resume path (``kv_attach`` on the SAME executor) restores the
    pinned chain via the ordinary tier-hit machinery — chained-hash
    re-verification included — then releases this object; a foreign
    executor just releases it and re-prefills (deterministic decode
    makes the streams byte-identical either way).
    """

    def __init__(self, tier: "HostKVTier", exec_id: str, owner: str,
                 keys: Sequence[str], prompt: Sequence[int],
                 cached_tokens: int,
                 cached_by_tier: Optional[Dict[str, int]] = None):
        self.tier = tier
        self.exec_id = exec_id
        self.owner = owner
        self.keys: Tuple[str, ...] = tuple(keys)
        self.prompt: Tuple[int, ...] = tuple(int(t) for t in prompt)
        self.cached_tokens = int(cached_tokens)
        self.cached_by_tier: Dict[str, int] = dict(cached_by_tier or {})
        self.in_transit = False
        self._released = False
        self._lock = threading.Lock()

    @property
    def blocks(self) -> Tuple[str, ...]:
        """Duck-typed KVLease.blocks: the parked chain keys (len() is
        what the requeue trace and response body record)."""
        return self.keys

    @property
    def released(self) -> bool:
        return self._released

    @property
    def resumable(self) -> bool:
        return not self._released

    def release(self, cache_hook=None) -> None:
        """Unpin every parked chain entry, exactly once (idempotent —
        second and later calls no-op, like KVLease.release). The
        ``cache_hook`` parameter exists only for call-shape parity;
        parked blocks are already content-addressed tier residents."""
        with self._lock:
            if self._released:
                return
            self._released = True
        for key in self.keys:
            self.tier.checkin(key, self.owner)

    def on_request_settled(self) -> None:
        """GenerateRequest.finish() hook — same contract as KVLease."""
        self.release()
