"""Device-resident paged-attention decode step (PagedAttention-style).

The PR 3 ``DecodeStep`` keeps a ``[slots, d]`` hidden vector on device;
this is its KV-bearing sibling: attention state lives in flat
``[num_blocks, block_size, heads, d_head]`` K/V pools that NEVER leave
the device, indexed through per-slot block tables the host allocator
(kvcache/allocator.py) hands out. One compiled executable — one
compile, ever — fuses, per step:

  * token embedding of a fixed ``[slots, chunk]`` token window
    (decode = 1 valid token, chunked prefill = up to ``chunk``);
  * KV APPEND: each new token's K/V lands at
    ``pool[table[pos // bs], pos % bs]``; padding rows use an
    out-of-range block id and drop (the PR 3 ``mode="drop"`` scatter
    discipline, extended from row indices to (block, offset) pairs);
  * paged attention: gather the slot's pages through its block table,
    causal-mask to each query's own position, softmax, weighted sum —
    with an explicit VALID-BLOCK GUARD (gathered K/V beyond the
    slot's written context is zeroed before use, so unwritten pool
    contents can never leak into outputs, not even as ``0 * NaN`` on
    the value path);
  * a small residual MLP and untied-head logits, argmax → the
    ``[slots]`` int32 token ids — the only thing that crosses PCIe.

ISSUE 13 made this a two-by-two of selectable layouts behind the SAME
call signature:

``kernel="pallas" | "xla"``
    *pallas* (the deploy default on a TPU backend) runs the fused
    parallel/pallas_paged_attn.py kernel: one launch per step gathers
    pages by table straight from HBM into double-buffered VMEM tiles,
    attends with an online-softmax accumulator (the ``[S, H, C, T]``
    score tensor is never materialized) and appends the step's new
    K/V in the same launch. *xla* is the reference composition (full
    ``pool[tables]`` gather → masked softmax → einsum → scatter),
    kept selectable and the tier-1 CPU default; off-TPU the pallas
    path runs under the Pallas interpreter, which is how CPU tier-1
    proves the two paths equivalent (tests/test_paged_attn.py).

``pool_dtype="int8" | "fp32"``
    *int8* is the RESIDENT format (the ISSUE 13 default): codes
    ``[N, bs, H, dh]`` int8 plus per-block scales ``[N]`` f32 — the
    parallel/quantize.py block-axis codec layout — 4x more resident
    slots/context per HBM byte. A block's scale is set ONCE, by the
    step that writes its row 0 (``scale = margin * amax(first rows)
    / 127``; later rows quantize with the stored scale and clip),
    which makes appends IDEMPOTENT: a re-attach replay re-quantizes
    identical bytes, so kill/resume streams stay byte-identical to
    unfailed ones — the property the whole-block requantize
    alternative cannot give (re-rounding already-resident rows makes
    replay path-dependent). The documented per-element error bound is
    ``paged_kv_error_bound`` below. *fp32* keeps exact residency for
    the byte-identical invariance lanes and as the quality reference.

``per_pos=True`` (ISSUE 15)
    compiles the step with argmax tokens for EVERY chunk position
    (``[slots, chunk]`` int32) instead of last-position-only — the
    speculative verify contract: a k-token draft window needs the
    target's prediction after each fed position, and both kernels
    already compute per-row attention outputs, so the widening is the
    post-kernel logits projection alone.

The fixed shapes are the whole contract: occupancy, prefill progress
and prompt length vary, ``[slots, chunk]``/``[slots, max_blocks]``
never do, so admissions and chunked prefill re-use the same executable
as pure decode — and the speculative verify window (``n_new = k+1``
host-fed tokens) is just a chunk plan whose rows happen to be drafts. The decode recurrence chains ON DEVICE through
``prev_tokens`` gated per slot by ``use_host`` — the pipelined
scheduler can dispatch step k+1 before step k's tokens ever reach the
host. Donation follows DecodeStep's measured platform policy: the
pools (4 arrays now: codes + scales, twice) are donated on accelerator
backends; on CPU donation is off by default because the CPU runtime
blocks dispatch on donated-input producers (~500us/step, measured in
PR 3). ``donate=`` overrides.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ...parallel.quantize import int8_block_decode_xp

#: One weight set per (seed, vocab, d, max_context, hidden) identity,
#: shared by every step object built from it — the single-worker step,
#: every rank's partial step and the coordinator finish step MUST
#: close over literally the same arrays, or the byte-identical
#: sharded-vs-single stream contract (ISSUE 16) rests on rng-order
#: luck instead of object identity.
_PARAM_CACHE: dict = {}


def build_paged_params(seed: int, vocab: int, d: int,
                       max_context: int,
                       hidden: Optional[int] = None) -> dict:
    """The paged model's weights, in the ONE blessed rng draw order
    (embed, wpos, wq, wk, wv, wo, w1, w2, wout — the PR 13 order;
    every consumer that re-derived this order independently would be
    a silent stream-divergence bug). Returns device (jnp) arrays,
    cached per identity."""
    import jax.numpy as jnp

    hidden = int(hidden or 2 * d)
    key = (int(seed), int(vocab), int(d), int(max_context), hidden)
    got = _PARAM_CACHE.get(key)
    if got is not None:
        return got
    rng = np.random.RandomState(seed)

    def w(*shape):
        return jnp.asarray(
            rng.randn(*shape).astype(np.float32)
            / np.sqrt(shape[0]))

    params = dict(
        embed=w(vocab, d), wpos=w(max_context, d),
        wq=w(d, d), wk=w(d, d), wv=w(d, d), wo=w(d, d),
        w1=w(d, hidden), w2=w(hidden, d), wout=w(d, vocab))
    _PARAM_CACHE[key] = params
    return params


def kv_bytes_per_slot(max_blocks_per_req: int, block_size: int,
                      heads: int, d_head: int,
                      pool_dtype: str = "int8") -> int:
    """Resident KV bytes one slot's worst-case reservation pins:
    ``max_blocks_per_req`` blocks of K and V rows plus their per-block
    scale floats. Pure arithmetic on the layout (no device, no
    compile) — the bench's ``serving_kv_bytes_per_slot`` and the
    capacity math of ROADMAP item 2 both read it, and the >= 3.5x
    int8-vs-fp32 reduction acceptance is checked against exactly this
    accounting."""
    elems = block_size * heads * d_head
    itemsize = 1 if pool_dtype == "int8" else 4
    return max_blocks_per_req * 2 * (elems * itemsize + 4)


def paged_kv_error_bound(scale: float, amax: float) -> float:
    """The documented per-element absolute error bound for one
    resident int8 KV element against its fp32 truth (the PR 9
    ``quantized_error_bound`` methodology applied to residency):
    rounding contributes ``scale / 2``; a row whose magnitude exceeds
    the block's first-write dynamic range clips at ``127 * scale`` and
    contributes the excess. ``scale`` is the block's STORED scale,
    ``amax`` the true fp32 max-abs over the block — both observable,
    so tests and the bench verify the bound per block per step."""
    return scale / 2.0 + max(0.0, amax - 127.0 * scale)


class PagedDecodeStep:
    """AOT-compiled fused chunk step over the paged KV pools. Params
    bind as executable constants (the DecodeStep discipline: per-step
    python dispatch never re-flattens a pytree; a weight swap means a
    new PagedDecodeStep)."""

    def __init__(self, slots: int, vocab: int, d: int, heads: int,
                 block_size: int, num_blocks: int,
                 max_blocks_per_req: int, chunk: int,
                 hidden: Optional[int] = None, seed: int = 0,
                 donate: Optional[bool] = None,
                 kernel: Optional[str] = None,
                 pool_dtype: str = "int8",
                 scale_margin: float = 1.5,
                 interpret: Optional[bool] = None,
                 per_pos: bool = False, tree: bool = False):
        import jax
        import jax.numpy as jnp

        if d % heads:
            raise ValueError(f"d={d} must divide by heads={heads}")
        if tree and not per_pos:
            raise ValueError("tree verify windows need per_pos=True "
                             "(per-position argmax is the verify "
                             "contract)")
        if kernel is None:
            # Deploy default: the fused kernel on a real TPU backend,
            # the XLA composition on CPU tier-1 (where pallas would
            # run interpreted — correct but orders slower per step).
            from ...parallel.pallas_paged_attn import _is_tpu_backend
            kernel = "pallas" if _is_tpu_backend() else "xla"
        if kernel not in ("pallas", "xla"):
            raise ValueError(f"kernel must be pallas|xla, got {kernel!r}")
        if pool_dtype not in ("int8", "fp32"):
            raise ValueError(f"pool_dtype must be int8|fp32, got "
                             f"{pool_dtype!r}")
        self.kernel = kernel
        self.pool_dtype = pool_dtype
        # per_pos (ISSUE 15): the step emits argmax tokens for EVERY
        # chunk position ([S, C] int32) instead of only the last
        # written one ([S]) — the output shape speculative verify
        # needs (target tokens t_0..t_k against the drafted window).
        # Both kernels share it for free: the Pallas kernel already
        # returns per-row attention outputs for all C appended rows
        # (o is [S, C, H, dh]); only the logits projection after the
        # kernel narrows to one row, so widening it is an XLA-side
        # change common to both paths.
        self.per_pos = bool(per_pos)
        self.scale_margin = float(scale_margin)
        self.slots = int(slots)
        self.vocab = int(vocab)
        self.d = int(d)
        self.heads = int(heads)
        self.d_head = d // heads
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_req = int(max_blocks_per_req)
        self.chunk = int(chunk)
        hidden = int(hidden or 2 * d)

        # Shared weight identity (build_paged_params): absolute
        # positional embedding (or the argmax recurrence collapses to
        # a fixed point and every resume/prefix test is vacuously
        # green; absolute positions also keep cached prefix KV
        # bit-identical on reuse) and an UNTIED output head (logits =
        # y @ embed.T would let the residual stream's own embedding
        # dominate into the same fixed-point collapse). Rank partial
        # steps and the coordinator finish step (ISSUE 16) close over
        # the SAME cached arrays.
        params = build_paged_params(seed, vocab, d,
                                    max_blocks_per_req * block_size,
                                    hidden)
        embed, wpos = params["embed"], params["wpos"]
        wq, wk, wv, wo = (params["wq"], params["wk"], params["wv"],
                          params["wo"])
        w1, w2, wout = params["w1"], params["w2"], params["wout"]
        # The truncated-stage draft (spec.TruncatedDraft) reuses
        # exactly these three — draft and target share one token
        # space by construction.
        self.draft_params = (embed, wpos, wout)

        S, C = self.slots, self.chunk
        B, bs = self.max_blocks_per_req, self.block_size
        H, dh = self.heads, self.d_head
        N, T = self.num_blocks, B * bs
        int8 = pool_dtype == "int8"
        margin = self.scale_margin

        fused = None
        if kernel == "pallas":
            from ...parallel.pallas_paged_attn import make_paged_attn_step

            fused = make_paged_attn_step(
                slots=S, chunk=C, max_blocks=B, block_size=bs,
                heads=H, d_head=dh, num_blocks=N,
                pool_dtype=pool_dtype, interpret=interpret)

        def update_scales(scales, vals, blk, pos, valid, ctx):
            """Per-block scale, set once by the step that writes the
            block's row 0 (``bstart >= ctx`` — appends only ever
            extend a block upward, so the block's first write this
            session is exactly the step whose new rows include its
            base position). Two drop-scatters: reset the touched
            blocks, then scatter-max the group amax. Deterministic
            under duplicate targets (set writes one value; max is
            order-free) and IDEMPOTENT under re-attach replay (the
            replay group equals the original first-write group —
            replays restart at block-aligned cursors)."""
            bstart = (pos // bs) * bs
            reset = valid & (bstart >= ctx[:, None])
            amax = jnp.max(jnp.abs(vals), axis=(2, 3))     # [S, C]
            tgt = jnp.where(reset, blk, N)                 # N = drop
            scales = scales.at[tgt].set(0.0, mode="drop")
            scales = scales.at[tgt].max(
                amax * np.float32(margin / 127.0), mode="drop")
            # All-zero first group: the chunk codec's scale-1.0
            # convention (decode stays exact zero, never 0/0).
            return jnp.where(scales > 0, scales,
                             jnp.float32(1.0)).astype(jnp.float32)

        def quantize_rows(vals, row_scales):
            q = jnp.round(vals / row_scales[:, :, None, None])
            return jnp.clip(q, -127, 127).astype(jnp.int8)

        def step(kpool, kscale, vpool, vscale, prev_tok, host_tok,
                 use_host, ctx, n_new, tables):
            # Slot 0 of the token window is the only position the
            # device recurrence can feed (decode is always one token);
            # prefill chunks come from the host wholesale.
            tok0 = jnp.where(use_host, host_tok[:, 0], prev_tok)
            toks = jnp.concatenate([tok0[:, None], host_tok[:, 1:]],
                                   axis=1)
            pos_ids = jnp.clip(
                ctx[:, None] + jnp.arange(C)[None, :], 0, T - 1)
            x = embed[toks] + wpos[pos_ids]              # [S, C, d]
            q = (x @ wq).reshape(S, C, H, dh)
            k = (x @ wk).reshape(S, C, H, dh)
            v = (x @ wv).reshape(S, C, H, dh)
            pos = ctx[:, None] + jnp.arange(C)[None, :]   # [S, C]
            valid = jnp.arange(C)[None, :] < n_new[:, None]
            blk_all = jnp.take_along_axis(
                tables, jnp.clip(pos // bs, 0, B - 1), axis=1)
            # Invalid positions scatter to block id N — out of range,
            # dropped (never a masked-multiply: the pool must keep
            # exact prior contents at untouched positions).
            blk = jnp.where(valid, blk_all, N)
            off = pos % bs
            if int8:
                # Scale update runs in XLA for BOTH kernels (cheap
                # [S, C] scatter math), so the two paths quantize
                # with bit-identical scales.
                kscale = update_scales(kscale, k, blk, pos, valid, ctx)
                vscale = update_scales(vscale, v, blk, pos, valid, ctx)
                ksc_rows = kscale[blk_all]
                vsc_rows = vscale[blk_all]
            else:
                ksc_rows = vsc_rows = jnp.ones((S, C), jnp.float32)
            limit = ctx + n_new
            if kernel == "pallas":
                o, kpool, vpool = fused(
                    tables, ctx, n_new, q, k, v, ksc_rows, vsc_rows,
                    kscale[tables] if int8
                    else jnp.ones((S, B), jnp.float32),
                    vscale[tables] if int8
                    else jnp.ones((S, B), jnp.float32),
                    kpool, vpool)
                o = o.reshape(S, C, H * dh)
            else:
                if int8:
                    kpool = kpool.at[blk, off].set(
                        quantize_rows(k, ksc_rows), mode="drop")
                    vpool = vpool.at[blk, off].set(
                        quantize_rows(v, vsc_rows), mode="drop")
                    keys = int8_block_decode_xp(
                        kpool[tables], kscale[tables],
                        xp=jnp).reshape(S, T, H, dh)
                    vals = int8_block_decode_xp(
                        vpool[tables], vscale[tables],
                        xp=jnp).reshape(S, T, H, dh)
                else:
                    kpool = kpool.at[blk, off].set(k, mode="drop")
                    vpool = vpool.at[blk, off].set(v, mode="drop")
                    keys = kpool[tables].reshape(S, T, H, dh)
                    vals = vpool[tables].reshape(S, T, H, dh)
                # The explicit valid-block guard (ISSUE 13 satellite):
                # zero gathered K/V beyond the written context BEFORE
                # any arithmetic. The additive score mask alone cannot
                # stop garbage on the VALUE path — softmax weight 0
                # times a poisoned NaN/Inf is NaN, and stale pages
                # from a previous block owner are exactly that risk
                # once pools hold dequantized int8 scratch.
                tpos = jnp.arange(T)
                t_ok = (tpos[None, :] < limit[:, None]
                        )[:, :, None, None]
                keys = jnp.where(t_ok, keys, 0.0)
                vals = jnp.where(t_ok, vals, 0.0)
                scores = jnp.einsum("schd,sthd->shct", q,
                                    keys) / np.sqrt(dh)
                causal = ((tpos[None, None, :] <= pos[:, :, None])
                          & (tpos[None, None, :] < limit[:, None, None])
                          & valid[:, :, None])           # [S, C, T]
                scores = jnp.where(causal[:, None, :, :], scores,
                                   jnp.float32(-1e30))
                attn = jax.nn.softmax(scores, axis=-1)
                o = jnp.einsum("shct,sthd->schd", attn, vals).reshape(
                    S, C, H * dh)
            y = x + o @ wo
            y = y + jax.nn.relu(y @ w1) @ w2
            if per_pos:
                # Speculative verify: logits for EVERY chunk position
                # — out[s, j] is the target's argmax after consuming
                # input j (padding rows yield garbage the collect
                # path never reads: n_new bounds the comparison).
                logits = y @ wout                        # [S, C, V]
                out = jnp.argmax(logits, axis=2).astype(jnp.int32)
            else:
                last = jnp.clip(n_new - 1, 0, C - 1)
                yl = jnp.take_along_axis(
                    y, last[:, None, None], axis=1)[:, 0]    # [S, d]
                logits = yl @ wout
                out = jnp.argmax(logits, axis=1).astype(jnp.int32)
            return kpool, kscale, vpool, vscale, out

        if donate is None:
            donate = jax.devices()[0].platform != "cpu"
        self.donate = bool(donate)
        dn = (0, 1, 2, 3) if self.donate else ()
        pdt = jnp.int8 if int8 else jnp.float32
        kp = jnp.zeros((N, bs, H, dh), pdt)
        vp = jnp.zeros((N, bs, H, dh), pdt)
        ksc = jnp.ones((N,), jnp.float32)
        vsc = jnp.ones((N,), jnp.float32)
        pt = jnp.zeros((S,), jnp.int32)
        ht = jnp.zeros((S, C), jnp.int32)
        uh = jnp.zeros((S,), jnp.bool_)
        i32 = jnp.zeros((S,), jnp.int32)
        tb = jnp.zeros((S, B), jnp.int32)
        # AOT compile in the constructor (the LocalExecutor contract
        # since PR 2): admission latency never includes XLA, and the
        # supervisor's watchdog never reads a cold compile as a wedge.
        self._step = jax.jit(step, donate_argnums=dn).lower(
            kp, ksc, vp, vsc, pt, ht, uh, i32, i32, tb).compile()

        self.tree = bool(tree)
        self._tree_step = None
        if tree:
            # Tree-topology verify step (ISSUE 18): rows carry an
            # explicit per-row position offset (siblings share the
            # first trunk position), only the first n_app rows APPEND
            # (the contiguous repair+base+trunk layout — score-only
            # sibling rows drop-scatter to block N), pool attention
            # is bounded per row by plim (appended rows include their
            # own scattered position; score-only rows stop at their
            # deepest appended ancestor, so a sibling never attends
            # the other branch's KV at its own position), and the
            # in-window tree-causal mask `win` wires row-to-row
            # attention over the step's FRESH K/V — the only path a
            # score-only row can reach its own key/value, which never
            # enters the pool. ALWAYS the XLA composition, both
            # kernel modes: the fused Pallas kernel normalizes its
            # softmax in-kernel and cannot merge the in-window
            # partials — the documented per-row-mask fallback (see
            # parallel/pallas_paged_attn.py). A tree-armed executor
            # routes EVERY step through this one executable, so
            # within-stream determinism never depends on mixing two
            # reduction shapes.
            def tree_step(kpool, kscale, vpool, vscale, prev_tok,
                          host_tok, use_host, ctx, n_new, tables,
                          roff, n_app, plim, win):
                tok0 = jnp.where(use_host, host_tok[:, 0], prev_tok)
                toks = jnp.concatenate(
                    [tok0[:, None], host_tok[:, 1:]], axis=1)
                pos = ctx[:, None] + roff                 # [S, C]
                x = embed[toks] + wpos[jnp.clip(pos, 0, T - 1)]
                q = (x @ wq).reshape(S, C, H, dh)
                k = (x @ wk).reshape(S, C, H, dh)
                v = (x @ wv).reshape(S, C, H, dh)
                app = jnp.arange(C)[None, :] < n_app[:, None]
                blk_all = jnp.take_along_axis(
                    tables, jnp.clip(pos // bs, 0, B - 1), axis=1)
                blk = jnp.where(app, blk_all, N)
                off = pos % bs
                if int8:
                    kscale = update_scales(kscale, k, blk, pos, app,
                                           ctx)
                    vscale = update_scales(vscale, v, blk, pos, app,
                                           ctx)
                    ksc_rows = kscale[blk_all]
                    vsc_rows = vscale[blk_all]
                    kpool = kpool.at[blk, off].set(
                        quantize_rows(k, ksc_rows), mode="drop")
                    vpool = vpool.at[blk, off].set(
                        quantize_rows(v, vsc_rows), mode="drop")
                    keys = int8_block_decode_xp(
                        kpool[tables], kscale[tables],
                        xp=jnp).reshape(S, T, H, dh)
                    vals = int8_block_decode_xp(
                        vpool[tables], vscale[tables],
                        xp=jnp).reshape(S, T, H, dh)
                else:
                    kpool = kpool.at[blk, off].set(k, mode="drop")
                    vpool = vpool.at[blk, off].set(v, mode="drop")
                    keys = kpool[tables].reshape(S, T, H, dh)
                    vals = vpool[tables].reshape(S, T, H, dh)
                limit = ctx + n_app
                tpos = jnp.arange(T)
                t_ok = (tpos[None, :] < limit[:, None]
                        )[:, :, None, None]
                keys = jnp.where(t_ok, keys, 0.0)
                vals = jnp.where(t_ok, vals, 0.0)
                scores = jnp.einsum("schd,sthd->shct", q,
                                    keys) / np.sqrt(dh)
                causal = tpos[None, None, :] < plim[:, :, None]
                scores = jnp.where(causal[:, None, :, :], scores,
                                   jnp.float32(-1e30))
                swin = jnp.einsum("schd,swhd->shcw", q,
                                  k) / np.sqrt(dh)
                swin = jnp.where(win[:, None, :, :], swin,
                                 jnp.float32(-1e30))
                # One softmax over pool + in-window columns: masked
                # columns underflow to exact 0.0 weight, and a fully
                # masked (invalid) row degrades to a uniform
                # distribution over garbage the collect path never
                # reads (n_new bounds every comparison).
                full = jnp.concatenate([scores, swin], axis=-1)
                attn = jax.nn.softmax(full, axis=-1)
                vfull = jnp.concatenate([vals, v], axis=1)
                o = jnp.einsum("shct,sthd->schd", attn,
                               vfull).reshape(S, C, H * dh)
                y = x + o @ wo
                y = y + jax.nn.relu(y @ w1) @ w2
                logits = y @ wout                        # [S, C, V]
                out = jnp.argmax(logits, axis=2).astype(jnp.int32)
                return kpool, kscale, vpool, vscale, out

            rf = jnp.zeros((S, C), jnp.int32)
            wn = jnp.zeros((S, C, C), jnp.bool_)
            self._tree_step = jax.jit(
                tree_step, donate_argnums=dn).lower(
                kp, ksc, vp, vsc, pt, ht, uh, i32, i32, tb,
                rf, i32, rf, wn).compile()

        self._take_prev = None
        if self.per_pos:
            # The pipelined-speculation chain gather: the NEXT verify
            # window's base row device-chains the trunk LEAF's output
            # (the window's bonus under full acceptance) — row
            # n_app-1 of the per-position argmax. Rows that planned
            # nothing keep their previous chain value.
            def take_prev(out, n_app, prev):
                idx = jnp.clip(n_app - 1, 0, C - 1)
                leaf = jnp.take_along_axis(
                    out, idx[:, None], axis=1)[:, 0]
                return jnp.where(n_app > 0, leaf,
                                 prev).astype(jnp.int32)

            oz = jnp.zeros((S, C), jnp.int32)
            self._take_prev = jax.jit(take_prev).lower(
                oz, i32, pt).compile()

    def init_pools(self):
        """Fresh zeroed (kpool, kscale, vpool, vscale) device arrays —
        int8 codes + per-block scales in the resident default, fp32
        rows + all-ones scales in the exact reference layout."""
        import jax.numpy as jnp

        shape = (self.num_blocks, self.block_size, self.heads,
                 self.d_head)

        def scales():
            # DISTINCT arrays for K and V: the four pool args are all
            # donated on accelerator backends, and donating one buffer
            # twice is a runtime error.
            return jnp.ones((self.num_blocks,), jnp.float32)

        if self.pool_dtype == "int8":
            return (jnp.zeros(shape, jnp.int8), scales(),
                    jnp.zeros(shape, jnp.int8), scales())
        # kv-dtype-policy: fp32 residency is the selectable EXACT
        # reference layout (byte-identical invariance lanes + the
        # int8 quality baseline); the resident default is int8.
        kpool = jnp.zeros(shape, jnp.float32)
        vpool = jnp.zeros(shape, jnp.float32)  # kv-dtype-policy: ditto
        return (kpool, scales(), vpool, scales())

    def init_prev(self):
        """Zeroed [slots] int32 device array for the token recurrence."""
        import jax.numpy as jnp

        return jnp.zeros((self.slots,), jnp.int32)

    def kv_bytes_per_slot(self) -> int:
        """Resident KV bytes one slot's worst-case reservation pins —
        the module-level ``kv_bytes_per_slot`` on this step's layout."""
        return kv_bytes_per_slot(self.max_blocks_per_req,
                                 self.block_size, self.heads,
                                 self.d_head, self.pool_dtype)

    def dequantized_pools(self, kpool, kscale, vpool, vscale):
        """Host-side fp32 view of resident pools (numpy): the
        parallel/quantize.py block-axis decode twin — what the fabric
        KV-transfer path would ship, and what the error-bound tests
        compare against fp32-resident truth."""
        if self.pool_dtype != "int8":
            return np.asarray(kpool), np.asarray(vpool)
        return (int8_block_decode_xp(np.asarray(kpool),
                                     np.asarray(kscale)),
                int8_block_decode_xp(np.asarray(vpool),
                                     np.asarray(vscale)))

    def __call__(self, kpool, kscale, vpool, vscale, prev_tok,
                 host_tok, use_host, ctx, n_new, tables):
        """(kpool', kscale', vpool', vscale', out_tokens) — all device
        arrays still in flight (jax async dispatch); the scheduler's
        pipelined loop overlaps host bookkeeping against them. The
        pools are consumed when donation is on: thread them
        linearly."""
        return self._step(kpool, kscale, vpool, vscale, prev_tok,
                          host_tok, use_host, ctx, n_new, tables)

    def tree_step(self, kpool, kscale, vpool, vscale, prev_tok,
                  host_tok, use_host, ctx, n_new, tables, roff,
                  n_app, plim, win):
        """The tree-topology verify executable (tree=True only): the
        chain step's signature plus the tree geometry — per-row
        position offsets, the appended-row count, per-row pool
        attention limits, and the in-window tree-causal mask."""
        if self._tree_step is None:
            raise RuntimeError("step compiled without tree=True")
        return self._tree_step(kpool, kscale, vpool, vscale, prev_tok,
                               host_tok, use_host, ctx, n_new, tables,
                               roff, n_app, plim, win)

    def take_prev(self, out, n_app, prev):
        """Device-side chain gather for pipelined speculation: the
        trunk leaf's per-position output (row n_app-1), or the
        previous chain value where nothing was planned."""
        if self._take_prev is None:
            raise RuntimeError("take_prev needs per_pos=True")
        return self._take_prev(out, n_app, prev)


class PagedRankStep:
    """ONE shard worker's half of the fused paged step (ISSUE 16):
    append into this rank's pool slice, attend over this rank's
    residency, return un-finished attention partials. The projection
    compute (embed → q/k/v) is REPLICATED — O(chunk * d) per step, the
    cheap part — while the pools, the append scatter and the attention
    gather (the O(context) parts) are sharded, which is exactly what
    makes resident context per replica scale with world size.

    Two axes, the slice bounds handed IN from the replica's KVSpec
    (``KVSpec.rank_heads`` / ``KVSpec.rank_blocks`` — never derived
    here, the GL018 contract):

    ``shard_axis="head"`` (Ulysses)
        pool ``[num_blocks, bs, rank_heads, dh]``: all block ids, a
        contiguous head slice of each. Attention for the rank's heads
        is COMPLETE locally (per-head attention is independent), so
        the partial is the exact per-head output ``o_r`` — the
        degenerate all-to-all of ulysses_attention._ulysses_body with
        the q/k/v re-shard replaced by replicated projection: heads
        stay where they live, nothing crosses the fabric but the
        ``[S, C, Hr*dh]`` outputs. Decode/verify windows (C = k+1)
        ride this: the per-step wire cost is independent of context.
        On a TPU backend the rank step runs the SAME fused Pallas
        paged-attention kernel as the single-worker step, built at
        the rank's head count.

    ``shard_axis="page"`` (ring)
        pool ``[rank_blocks, bs, heads, dh]``: all heads of a
        contiguous global block-id range. The rank attends its OWN
        pages only and returns un-normalized flash partials
        ``(m, l, o)`` per (slot, head, chunk-row); the coordinator
        folds rank partials in rank order with ring_attention's
        online-softmax recurrence (``merge_partial_softmax``) — the
        ring fold with the per-hop RDMA replaced by the collect
        gather. Long prefill chunks ride this: every rank scans only
        its share of the pages.

    int8 residency: every rank computes the FULL k/v projection, so
    the per-block scale (margin * amax over ALL heads of the block's
    first-write group) is bit-identical on every rank and to the
    single-worker pool — a head slice quantized under that scale IS
    the corresponding slice of the single-worker codes. Scale
    set-once/idempotence carries over unchanged."""

    def __init__(self, slots: int, vocab: int, d: int, heads: int,
                 block_size: int, num_blocks: int,
                 max_blocks_per_req: int, chunk: int, *,
                 shard_axis: str, head_bounds: Tuple[int, int],
                 block_bounds: Tuple[int, int],
                 hidden: Optional[int] = None, seed: int = 0,
                 donate: Optional[bool] = None,
                 kernel: Optional[str] = None,
                 pool_dtype: str = "int8",
                 scale_margin: float = 1.5,
                 interpret: Optional[bool] = None):
        import jax
        import jax.numpy as jnp

        if shard_axis not in ("head", "page"):
            raise ValueError(f"shard_axis must be head|page, got "
                             f"{shard_axis!r}")
        if pool_dtype not in ("int8", "fp32"):
            raise ValueError(f"pool_dtype must be int8|fp32, got "
                             f"{pool_dtype!r}")
        if kernel is None:
            from ...parallel.pallas_paged_attn import _is_tpu_backend
            kernel = ("pallas" if _is_tpu_backend()
                      and shard_axis == "head" else "xla")
        if kernel == "pallas" and shard_axis == "page":
            raise ValueError(
                "the fused pallas kernel normalizes its softmax; "
                "page-sharded ranks return flash partials (kernel="
                "'xla')")
        self.kernel = kernel
        self.shard_axis = shard_axis
        self.pool_dtype = pool_dtype
        self.slots, self.chunk = int(slots), int(chunk)
        self.heads, self.d_head = int(heads), d // heads
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_req = int(max_blocks_per_req)
        h_lo, h_hi = (int(head_bounds[0]), int(head_bounds[1]))
        b_lo, b_hi = (int(block_bounds[0]), int(block_bounds[1]))
        self.head_bounds = (h_lo, h_hi)
        self.block_bounds = (b_lo, b_hi)
        #: Local pool geometry — all of it from the bounds the KVSpec
        #: derived, none recomputed here.
        self.pool_heads = h_hi - h_lo if shard_axis == "head" \
            else self.heads
        self.pool_blocks = b_hi - b_lo if shard_axis == "page" \
            else self.num_blocks

        params = build_paged_params(
            seed, vocab, d, max_blocks_per_req * block_size, hidden)
        embed, wpos = params["embed"], params["wpos"]
        wq, wk, wv = params["wq"], params["wk"], params["wv"]

        S, C = self.slots, self.chunk
        B, bs = self.max_blocks_per_req, self.block_size
        H, dh = self.heads, self.d_head
        Hr, Nr = self.pool_heads, self.pool_blocks
        T = B * bs
        int8 = pool_dtype == "int8"
        margin = float(scale_margin)
        head = shard_axis == "head"

        fused = None
        if kernel == "pallas":
            from ...parallel.pallas_paged_attn import \
                make_paged_attn_step

            fused = make_paged_attn_step(
                slots=S, chunk=C, max_blocks=B, block_size=bs,
                heads=Hr, d_head=dh, num_blocks=Nr,
                pool_dtype=pool_dtype, interpret=interpret)

        def update_scales(scales, vals, tgt, ctx, pos, valid):
            """The single-worker set-once scale rule against the
            LOCAL drop bound Nr: ``tgt`` already maps un-owned and
            invalid rows out of range. ``vals`` is the FULL-head k/v,
            so the stored scale equals the single-worker pool's."""
            bstart = (pos // bs) * bs
            reset = valid & (bstart >= ctx[:, None])
            amax = jnp.max(jnp.abs(vals), axis=(2, 3))     # [S, C]
            t = jnp.where(reset, tgt, Nr)
            scales = scales.at[t].set(0.0, mode="drop")
            scales = scales.at[t].max(
                amax * np.float32(margin / 127.0), mode="drop")
            return jnp.where(scales > 0, scales,
                             jnp.float32(1.0)).astype(jnp.float32)

        def quantize_rows(vals, row_scales):
            q = jnp.round(vals / row_scales[:, :, None, None])
            return jnp.clip(q, -127, 127).astype(jnp.int8)

        def step(kpool, kscale, vpool, vscale, prev_tok, host_tok,
                 use_host, ctx, n_new, tables):
            tok0 = jnp.where(use_host, host_tok[:, 0], prev_tok)
            toks = jnp.concatenate([tok0[:, None], host_tok[:, 1:]],
                                   axis=1)
            pos_ids = jnp.clip(
                ctx[:, None] + jnp.arange(C)[None, :], 0, T - 1)
            x = embed[toks] + wpos[pos_ids]              # [S, C, d]
            # FULL-head projections, replicated on every rank: the
            # scale rule needs the whole row's amax, and decode's one
            # token makes this O(d) — never the O(context) part.
            q = (x @ wq).reshape(S, C, H, dh)
            k = (x @ wk).reshape(S, C, H, dh)
            v = (x @ wv).reshape(S, C, H, dh)
            pos = ctx[:, None] + jnp.arange(C)[None, :]   # [S, C]
            valid = jnp.arange(C)[None, :] < n_new[:, None]
            blk_all = jnp.take_along_axis(
                tables, jnp.clip(pos // bs, 0, B - 1), axis=1)
            if head:
                # All block ids local; local id == global id.
                lblk_all = blk_all
                ltab = tables
                owned_tab = jnp.ones((S, B), jnp.bool_)
            else:
                owned = (blk_all >= b_lo) & (blk_all < b_hi)
                lblk_all = jnp.where(owned, blk_all - b_lo, Nr)
                owned_tab = (tables >= b_lo) & (tables < b_hi)
                ltab = jnp.where(owned_tab, tables - b_lo, 0)
            lblk = jnp.where(valid, lblk_all, Nr)
            off = pos % bs
            kw = k[:, :, h_lo:h_hi] if head else k
            vw = v[:, :, h_lo:h_hi] if head else v
            qw = q[:, :, h_lo:h_hi] if head else q
            if int8:
                kscale = update_scales(kscale, k, lblk, ctx, pos,
                                       valid)
                vscale = update_scales(vscale, v, lblk, ctx, pos,
                                       valid)
                ksc_rows = kscale[jnp.clip(lblk_all, 0, Nr - 1)]
                vsc_rows = vscale[jnp.clip(lblk_all, 0, Nr - 1)]
            else:
                ksc_rows = vsc_rows = jnp.ones((S, C), jnp.float32)
            limit = ctx + n_new
            if kernel == "pallas":
                o, kpool, vpool = fused(
                    ltab, ctx, n_new, qw, kw, vw, ksc_rows, vsc_rows,
                    kscale[ltab] if int8
                    else jnp.ones((S, B), jnp.float32),
                    vscale[ltab] if int8
                    else jnp.ones((S, B), jnp.float32),
                    kpool, vpool)
                return (kpool, kscale, vpool, vscale,
                        o.reshape(S, C, Hr, dh))
            if int8:
                kpool = kpool.at[lblk, off].set(
                    quantize_rows(kw, ksc_rows), mode="drop")
                vpool = vpool.at[lblk, off].set(
                    quantize_rows(vw, vsc_rows), mode="drop")
                keys = int8_block_decode_xp(
                    kpool[ltab], kscale[ltab],
                    xp=jnp).reshape(S, T, Hr, dh)
                vals = int8_block_decode_xp(
                    vpool[ltab], vscale[ltab],
                    xp=jnp).reshape(S, T, Hr, dh)
            else:
                kpool = kpool.at[lblk, off].set(kw, mode="drop")
                vpool = vpool.at[lblk, off].set(vw, mode="drop")
                keys = kpool[ltab].reshape(S, T, Hr, dh)
                vals = vpool[ltab].reshape(S, T, Hr, dh)
            # The single-worker valid-block guard, with block
            # OWNERSHIP folded in: positions outside this rank's page
            # range must contribute nothing on either the score or
            # the value path.
            tpos = jnp.arange(T)
            owned_pos = jnp.repeat(owned_tab, bs, axis=1)  # [S, T]
            t_ok = ((tpos[None, :] < limit[:, None]) & owned_pos
                    )[:, :, None, None]
            keys = jnp.where(t_ok, keys, 0.0)
            vals = jnp.where(t_ok, vals, 0.0)
            scores = jnp.einsum("schd,sthd->shct", qw,
                                keys) / np.sqrt(dh)
            causal = ((tpos[None, None, :] <= pos[:, :, None])
                      & (tpos[None, None, :] < limit[:, None, None])
                      & valid[:, :, None]
                      & owned_pos[:, None, :])             # [S, C, T]
            scores = jnp.where(causal[:, None, :, :], scores,
                               jnp.float32(-1e30))
            if head:
                # Per-head attention is complete locally: normalize
                # here, exactly the single-worker softmax.
                attn = jax.nn.softmax(scores, axis=-1)
                o = jnp.einsum("shct,sthd->schd", attn, vals)
                return kpool, kscale, vpool, vscale, o
            # Page axis: flash partials over the rank's pages only —
            # the masked-out guard keeps a rank that owns NOTHING for
            # a row at (m=-1e30, l=0, o=0), which the coordinator
            # fold treats as the identity.
            m = jnp.max(scores, axis=-1)                   # [S, H, C]
            p = jnp.where(scores > jnp.float32(-1e29),
                          jnp.exp(scores - m[..., None]), 0.0)
            l = jnp.sum(p, axis=-1)                        # [S, H, C]
            o = jnp.einsum("shct,sthd->shcd", p, vals)
            return kpool, kscale, vpool, vscale, m, l, o

        if donate is None:
            donate = jax.devices()[0].platform != "cpu"
        dn = (0, 1, 2, 3) if donate else ()
        pdt = jnp.int8 if int8 else jnp.float32
        kp = jnp.zeros((Nr, bs, Hr, dh), pdt)
        vp = jnp.zeros((Nr, bs, Hr, dh), pdt)
        ksc = jnp.ones((Nr,), jnp.float32)
        vsc = jnp.ones((Nr,), jnp.float32)
        pt = jnp.zeros((S,), jnp.int32)
        ht = jnp.zeros((S, C), jnp.int32)
        uh = jnp.zeros((S,), jnp.bool_)
        i32 = jnp.zeros((S,), jnp.int32)
        tb = jnp.zeros((S, B), jnp.int32)
        self._step = jax.jit(step, donate_argnums=dn).lower(
            kp, ksc, vp, vsc, pt, ht, uh, i32, i32, tb).compile()

    def init_pools(self):
        """Fresh zeroed per-rank (kpool, kscale, vpool, vscale)."""
        import jax.numpy as jnp

        shape = (self.pool_blocks, self.block_size, self.pool_heads,
                 self.d_head)
        pdt = jnp.int8 if self.pool_dtype == "int8" else jnp.float32
        return (jnp.zeros(shape, pdt),
                jnp.ones((self.pool_blocks,), jnp.float32),
                jnp.zeros(shape, pdt),
                jnp.ones((self.pool_blocks,), jnp.float32))

    def __call__(self, kpool, kscale, vpool, vscale, prev_tok,
                 host_tok, use_host, ctx, n_new, tables):
        """head axis: ``(pools..., o_r [S, C, Hr, dh])``; page axis:
        ``(pools..., m [S, H, C], l [S, H, C], o [S, H, C, dh])``."""
        return self._step(kpool, kscale, vpool, vscale, prev_tok,
                          host_tok, use_host, ctx, n_new, tables)


class PagedFinishStep:
    """The coordinator's tail of the sharded paged step: residual +
    MLP + untied-head logits + argmax over the MERGED attention
    output — operation-for-operation the tail of PagedDecodeStep's
    fused step (same cached weights, same clip/take_along_axis
    shapes), so a bit-identical merged ``o`` yields a bit-identical
    token stream. ``per_pos`` widens the logits projection exactly as
    the single-worker step does for speculative verify windows."""

    def __init__(self, slots: int, vocab: int, d: int,
                 block_size: int, max_blocks_per_req: int, chunk: int,
                 hidden: Optional[int] = None, seed: int = 0,
                 per_pos: bool = False):
        import jax
        import jax.numpy as jnp

        self.slots, self.chunk = int(slots), int(chunk)
        self.per_pos = bool(per_pos)
        T = int(max_blocks_per_req) * int(block_size)
        params = build_paged_params(seed, vocab, d, T, hidden)
        embed, wpos, wo = params["embed"], params["wpos"], params["wo"]
        w1, w2, wout = params["w1"], params["w2"], params["wout"]
        self.draft_params = (embed, wpos, wout)
        S, C = self.slots, self.chunk
        per_pos = self.per_pos

        def finish(prev_tok, host_tok, use_host, ctx, n_new, o):
            tok0 = jnp.where(use_host, host_tok[:, 0], prev_tok)
            toks = jnp.concatenate([tok0[:, None], host_tok[:, 1:]],
                                   axis=1)
            pos_ids = jnp.clip(
                ctx[:, None] + jnp.arange(C)[None, :], 0, T - 1)
            x = embed[toks] + wpos[pos_ids]              # [S, C, d]
            y = x + o @ wo
            y = y + jax.nn.relu(y @ w1) @ w2
            if per_pos:
                logits = y @ wout                        # [S, C, V]
                return jnp.argmax(logits, axis=2).astype(jnp.int32)
            last = jnp.clip(n_new - 1, 0, C - 1)
            yl = jnp.take_along_axis(
                y, last[:, None, None], axis=1)[:, 0]    # [S, d]
            logits = yl @ wout
            return jnp.argmax(logits, axis=1).astype(jnp.int32)

        pt = jnp.zeros((S,), jnp.int32)
        ht = jnp.zeros((S, C), jnp.int32)
        uh = jnp.zeros((S,), jnp.bool_)
        i32 = jnp.zeros((S,), jnp.int32)
        of = jnp.zeros((S, C, int(d)), jnp.float32)
        self._finish = jax.jit(finish).lower(
            pt, ht, uh, i32, i32, of).compile()

    def __call__(self, prev_tok, host_tok, use_host, ctx, n_new, o):
        return self._finish(prev_tok, host_tok, use_host, ctx, n_new,
                            o)
