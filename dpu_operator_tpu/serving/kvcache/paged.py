"""Device-resident paged-attention decode step (PagedAttention-style).

The PR 3 ``DecodeStep`` keeps a ``[slots, d]`` hidden vector on device;
this is its KV-bearing sibling: attention state lives in one flat
``[num_blocks, block_size, heads, d_head]`` K pool and one V pool that
NEVER leave the device, indexed through per-slot block tables the host
allocator (kvcache/allocator.py) hands out. One compiled executable —
one compile, ever — fuses, per step:

  * token embedding of a fixed ``[slots, chunk]`` token window
    (decode = 1 valid token, chunked prefill = up to ``chunk``);
  * KV APPEND by scatter: each new token's K/V lands at
    ``pool[table[pos // bs], pos % bs]``; padding rows use an
    out-of-range block id and drop (the PR 3 ``mode="drop"`` scatter
    discipline, extended from row indices to (block, offset) pairs);
  * paged attention: gather the slot's pages through its block table,
    causal-mask to each query's own position, softmax, weighted sum;
  * a small residual MLP and tied-embedding logits, argmax → the
    ``[slots]`` int32 token ids — the only thing that crosses PCIe.

The fixed shapes are the whole contract: occupancy, prefill progress
and prompt length vary, ``[slots, chunk]``/``[slots, max_blocks]``
never do, so admissions and chunked prefill re-use the same executable
as pure decode. The decode recurrence chains ON DEVICE: the previous
step's (possibly still in-flight) token output feeds the next step's
input through ``prev_tokens``, gated per slot by ``use_host`` — the
pipelined scheduler can dispatch step k+1 before step k's tokens ever
reach the host (the ISSUE 3 overlap, now with KV state).

Donation follows DecodeStep's measured platform policy: the two pools
are donated on accelerator backends (the decode session allocates its
KV memory once); on CPU donation is off by default because the CPU
runtime blocks dispatch on donated-input producers (~500us/step,
measured in PR 3 — it serializes exactly the pipeline this exists
for). ``donate=`` overrides.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class PagedDecodeStep:
    """AOT-compiled fused chunk step over the paged KV pools. Params
    bind as executable constants (the DecodeStep discipline: per-step
    python dispatch never re-flattens a pytree; a weight swap means a
    new PagedDecodeStep)."""

    def __init__(self, slots: int, vocab: int, d: int, heads: int,
                 block_size: int, num_blocks: int,
                 max_blocks_per_req: int, chunk: int,
                 hidden: Optional[int] = None, seed: int = 0,
                 donate: Optional[bool] = None):
        import jax
        import jax.numpy as jnp

        if d % heads:
            raise ValueError(f"d={d} must divide by heads={heads}")
        self.slots = int(slots)
        self.vocab = int(vocab)
        self.d = int(d)
        self.heads = int(heads)
        self.d_head = d // heads
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_blocks_per_req = int(max_blocks_per_req)
        self.chunk = int(chunk)
        hidden = int(hidden or 2 * d)

        rng = np.random.RandomState(seed)

        def w(*shape):
            return jnp.asarray(
                rng.randn(*shape).astype(np.float32)
                / np.sqrt(shape[0]))

        embed = w(vocab, d)
        # Absolute positional embedding: decode output must depend on
        # WHERE in the sequence a token sits, or the argmax recurrence
        # collapses to a fixed point and every resume/prefix test is
        # vacuously green. Positions are absolute, so cached prefix KV
        # (computed at the same positions) stays bit-identical on
        # reuse.
        wpos = w(max_blocks_per_req * block_size, d)
        wq, wk, wv, wo = w(d, d), w(d, d), w(d, d), w(d, d)
        w1, w2 = w(d, hidden), w(hidden, d)
        # UNTIED output head: with logits = y @ embed.T the residual
        # stream's own embedding dominates and argmax collapses to a
        # fixed point (token t forever) — which would make every
        # stream-equality test in the suite vacuously green.
        wout = w(d, vocab)

        S, C = self.slots, self.chunk
        B, bs = self.max_blocks_per_req, self.block_size
        H, dh = self.heads, self.d_head
        N, T = self.num_blocks, B * bs

        def step(kpool, vpool, prev_tok, host_tok, use_host, ctx,
                 n_new, tables):
            # Slot 0 of the token window is the only position the
            # device recurrence can feed (decode is always one token);
            # prefill chunks come from the host wholesale.
            tok0 = jnp.where(use_host, host_tok[:, 0], prev_tok)
            toks = jnp.concatenate([tok0[:, None], host_tok[:, 1:]],
                                   axis=1)
            pos_ids = jnp.clip(
                ctx[:, None] + jnp.arange(C)[None, :], 0, T - 1)
            x = embed[toks] + wpos[pos_ids]              # [S, C, d]
            q = (x @ wq).reshape(S, C, H, dh)
            k = (x @ wk).reshape(S, C, H, dh)
            v = (x @ wv).reshape(S, C, H, dh)
            pos = ctx[:, None] + jnp.arange(C)[None, :]   # [S, C]
            valid = jnp.arange(C)[None, :] < n_new[:, None]
            blk = jnp.take_along_axis(
                tables, jnp.clip(pos // bs, 0, B - 1), axis=1)
            # Invalid positions scatter to block id N — out of range,
            # dropped (never a masked-multiply: the pool must keep
            # exact prior contents at untouched positions).
            blk = jnp.where(valid, blk, N)
            off = pos % bs
            kpool = kpool.at[blk, off].set(k, mode="drop")
            vpool = vpool.at[blk, off].set(v, mode="drop")
            keys = kpool[tables].reshape(S, T, H, dh)
            vals = vpool[tables].reshape(S, T, H, dh)
            scores = jnp.einsum("schd,sthd->shct", q, keys) / np.sqrt(dh)
            tpos = jnp.arange(T)
            causal = ((tpos[None, None, :] <= pos[:, :, None])
                      & valid[:, :, None])               # [S, C, T]
            scores = jnp.where(causal[:, None, :, :], scores,
                               jnp.float32(-1e30))
            attn = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("shct,sthd->schd", attn, vals).reshape(
                S, C, H * dh)
            y = x + o @ wo
            y = y + jax.nn.relu(y @ w1) @ w2
            last = jnp.clip(n_new - 1, 0, C - 1)
            yl = jnp.take_along_axis(
                y, last[:, None, None], axis=1)[:, 0]    # [S, d]
            logits = yl @ wout
            out = jnp.argmax(logits, axis=1).astype(jnp.int32)
            return kpool, vpool, out

        if donate is None:
            donate = jax.devices()[0].platform != "cpu"
        self.donate = bool(donate)
        dn = (0, 1) if self.donate else ()
        kp = jnp.zeros((N, bs, H, dh), jnp.float32)
        vp = jnp.zeros((N, bs, H, dh), jnp.float32)
        pt = jnp.zeros((S,), jnp.int32)
        ht = jnp.zeros((S, C), jnp.int32)
        uh = jnp.zeros((S,), jnp.bool_)
        i32 = jnp.zeros((S,), jnp.int32)
        tb = jnp.zeros((S, B), jnp.int32)
        # AOT compile in the constructor (the LocalExecutor contract
        # since PR 2): admission latency never includes XLA, and the
        # supervisor's watchdog never reads a cold compile as a wedge.
        self._step = jax.jit(step, donate_argnums=dn).lower(
            kp, vp, pt, ht, uh, i32, i32, tb).compile()

    def init_pools(self):
        """Fresh zeroed (kpool, vpool) device arrays."""
        import jax.numpy as jnp

        shape = (self.num_blocks, self.block_size, self.heads,
                 self.d_head)
        return (jnp.zeros(shape, jnp.float32),
                jnp.zeros(shape, jnp.float32))

    def init_prev(self):
        """Zeroed [slots] int32 device array for the token recurrence."""
        import jax.numpy as jnp

        return jnp.zeros((self.slots,), jnp.int32)

    def __call__(self, kpool, vpool, prev_tok, host_tok, use_host,
                 ctx, n_new, tables):
        """(kpool', vpool', out_tokens) — all device arrays still in
        flight (jax async dispatch); the scheduler's pipelined loop
        overlaps host bookkeeping against them. The pools are consumed
        when donation is on: thread them linearly."""
        return self._step(kpool, vpool, prev_tok, host_tok, use_host,
                          ctx, n_new, tables)
