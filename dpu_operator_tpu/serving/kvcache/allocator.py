"""Host-side paged KV-cache management: block allocator + prefix tree.

The device keeps one flat ``[num_blocks, block_size, heads, d_head]``
KV pool (kvcache/paged.py); everything about WHO owns WHICH pages is
host-side python in this module, jax-free by design so the scheduler
plane can import it in any process:

  * ``KVBlockAllocator`` — fixed-size blocks on an explicit free list
    with per-block refcounts and OWNER-TAGGED accounting: every
    ``acquire``/``fork`` names its owner (a request id, or the prefix
    cache), every ``release`` must come from an owner that actually
    holds the ref, and ``leaked()``/``assert_clean()`` make "zero
    leaked KV blocks after every test" an assertable teardown contract
    instead of a hope (the vLLM block-manager discipline, with the
    leak ledger made first-class).
  * ``KVLease`` — one request's block table. It lives ON the
    ``GenerateRequest`` (``req.kv_lease``) and therefore rides the
    PR 5 seize→requeue path through the AdmissionQueue: a replica kill
    mid-decode re-attaches these pages instead of re-decoding from the
    prompt. Release is idempotent and funnelled through one choke
    point (``GenerateRequest.finish`` calls ``on_request_settled``),
    so every settle path — retire, fail, shed, server stop — returns
    the pages exactly once.
  * ``PrefixTree`` — refcounted prefix sharing keyed on chained
    token-id hashes at BLOCK granularity (PagedAttention's prefix
    reuse): a finished request's full prompt blocks are inserted under
    the cache's own owner tag; a later request with the same prefix
    forks them (refcount++) and skips that much prefill. Only FULL
    blocks are ever shared and a request's appends always land in its
    own freshly-acquired blocks (positions ≥ the block-aligned cached
    prefix), so shared pages are immutable by construction — no
    copy-on-write machinery is needed. Matches are capped at
    ``len(prompt) - 1`` tokens: the last prompt token always
    recomputes, because its forward pass is what EMITS the first
    decode token (logits are not cached, KV is).

Thread-safety: the allocator and tree each hold one lock. Leases are
released from batcher, supervisor and HTTP-handler threads; the
``match → fork`` window is closed by doing both under the tree lock
(``match_and_fork``) so eviction can never free a block between the
lookup and the ref.
"""

from __future__ import annotations

import hashlib
import heapq
import logging
import threading
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

#: Owner tag for refs held by the prefix cache itself (exempt from
#: leak accounting: cached blocks are retained capacity, not a leak).
CACHE_OWNER = "__prefix_cache__"


class KVCacheOOM(Exception):
    """Not enough free KV blocks. Admission-control signal, not a
    replica failure: the scheduler sheds the request with a 503-shaped
    error (server maps ``KV_OOM_ERROR``) instead of crashing the loop."""

    def __init__(self, need: int, free: int):
        super().__init__(
            f"kv cache exhausted: need {need} block(s), {free} free")
        self.need = need
        self.free = free


class KVBlockAllocator:
    """Fixed-size KV blocks with refcounts and owner-tagged leak
    accounting. ``acquire`` hands out exclusively-owned blocks
    (ref=1); ``fork`` adds a ref to existing blocks (prefix sharing);
    ``release`` drops the caller's refs and returns fully-released
    blocks to the free list."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need num_blocks >= 1 and block_size >= 1, got "
                f"{num_blocks}/{block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        # Stack of free block ids; popping from the end gives LIFO
        # reuse (warm pages, and deterministic ids for tests).
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref = [0] * self.num_blocks
        self._owners: Dict[str, Counter] = {}
        self.acquired_total = 0
        self.released_total = 0

    # -- core lifecycle -------------------------------------------------------

    def acquire(self, n: int, owner: str) -> List[int]:
        """n fresh exclusively-owned blocks, or KVCacheOOM (atomic:
        never a partial grant — a partial grant is a leak the caller
        has to remember to unwind mid-error-path)."""
        if n < 0:
            raise ValueError(f"acquire({n}): negative block count")
        with self._lock:
            if n > len(self._free):
                raise KVCacheOOM(n, len(self._free))
            blocks = [self._free.pop() for _ in range(n)]
            for b in blocks:
                self._ref[b] = 1
            if blocks:
                self._owners.setdefault(owner, Counter()).update(blocks)
                self.acquired_total += n
            return blocks

    def fork(self, blocks: Sequence[int], owner: str) -> None:
        """Add one ref per block for `owner` — the prefix-sharing ref.
        Every block must already be live (ref > 0): forking a freed
        block is a use-after-free and raises."""
        with self._lock:
            for b in blocks:
                if not 0 <= b < self.num_blocks or self._ref[b] <= 0:
                    raise ValueError(
                        f"fork of non-live block {b} (owner {owner!r})")
            for b in blocks:
                self._ref[b] += 1
            if blocks:
                self._owners.setdefault(owner, Counter()).update(blocks)
                self.acquired_total += len(blocks)

    def release(self, blocks: Sequence[int], owner: str) -> int:
        """Drop `owner`'s ref on each block; returns how many blocks
        actually went back to the free list (ref hit 0). Releasing a
        ref the owner does not hold raises — that is the double-free
        the leak ledger exists to catch."""
        freed = 0
        with self._lock:
            held = self._owners.get(owner)
            for b in blocks:
                if held is None or held[b] <= 0:
                    raise ValueError(
                        f"release of block {b} not held by {owner!r}")
                held[b] -= 1
                if held[b] <= 0:
                    del held[b]
                self._ref[b] -= 1
                self.released_total += 1
                if self._ref[b] == 0:
                    self._free.append(b)
                    freed += 1
            if held is not None and not held:
                del self._owners[owner]
        return freed

    # -- accounting -----------------------------------------------------------

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref[block]

    def stats(self) -> Dict[str, int]:
        """used/free/shared block counts for the
        ``serving_kv_blocks{state=}`` gauge (shared = ref > 1)."""
        with self._lock:
            used = self.num_blocks - len(self._free)
            shared = sum(1 for r in self._ref if r > 1)
            return {"used": used, "free": len(self._free),
                    "shared": shared}

    def leaked(self, ignore: Sequence[str] = (CACHE_OWNER,)
               ) -> Dict[str, List[int]]:
        """Blocks still held per owner, excluding `ignore` (the prefix
        cache's refs are retained capacity, not a leak). Empty means
        every request-owned ref was returned."""
        with self._lock:
            return {o: sorted(c.elements())
                    for o, c in self._owners.items()
                    if o not in ignore and c}

    def assert_clean(self, ignore: Sequence[str] = (CACHE_OWNER,)) -> None:
        """Teardown contract: zero leaked KV blocks (tier-1 serving and
        chaos tests call this after every run)."""
        leaks = self.leaked(ignore)
        if leaks:
            raise AssertionError(f"leaked KV blocks: {leaks}")


class KVLease:
    """One request's KV-page ownership: the ordered block table plus
    the immutable facts a re-attach rebuilds decode cursors from (the
    prompt itself and the block-aligned cached-prefix length). Mutable
    per-step cursors (ctx, prefill position, last emitted token) live
    in the EXECUTOR's slot state, not here: on seize→requeue→re-attach
    they are rewound from ``req.tokens`` — the request's settled tokens
    are the durable truth, so a kill between dispatch and settle can
    never leave the lease ahead of (or behind) what the client saw."""

    __slots__ = ("allocator", "exec_id", "owner", "blocks", "prompt",
                 "cached_tokens", "cached_by_tier", "_released",
                 "_in_transit", "_lock")

    def __init__(self, allocator: KVBlockAllocator, exec_id: str,
                 owner: str, blocks: List[int],
                 prompt: Tuple[int, ...], cached_tokens: int,
                 cached_by_tier: Optional[Dict[str, int]] = None):
        self.allocator = allocator
        self.exec_id = exec_id
        self.owner = owner
        self.blocks = list(blocks)
        self.prompt = tuple(int(t) for t in prompt)
        self.cached_tokens = int(cached_tokens)
        # Where the cached prefix came from (ISSUE 17): the response
        # body's per-tier ``cached_tokens`` decomposition. Defaults to
        # all-HBM, the only tier that existed before tiering.
        self.cached_by_tier = dict(
            cached_by_tier if cached_by_tier is not None
            else {"hbm": self.cached_tokens})
        self._released = False
        self._in_transit = False
        self._lock = threading.Lock()

    @property
    def released(self) -> bool:
        return self._released

    @property
    def in_transit(self) -> bool:
        return self._in_transit

    @property
    def resumable(self) -> bool:
        """True while the pages are still owned — the supervisor's
        requeue keeps decoded tokens (retry resumes) iff this holds."""
        return not self._released

    # -- cross-replica hand-off (serving/disagg) ------------------------------

    def detach(self) -> bool:
        """Mark the lease as crossing a replica boundary (pages being
        exported/streamed). The pages stay owned — a failed transfer
        must be able to ``reattach()`` and resume on the source side —
        but a detached lease refuses a second concurrent hand-off and
        refuses ``kv_attach`` until the transfer plane settles it one
        way or the other (the detach/ack pairing GL016 polices).

        Returns False when the lease is ALREADY RELEASED: the settle
        choke point can fire from the HTTP handler's thread at any
        time (the same race every release path tolerates by
        idempotency), so detach-of-released is a benign lost race —
        the caller must treat the request as settled, never hand it
        off. A DOUBLE detach still raises: two concurrent hand-offs
        means two owners, an ownership bug no disposition fixes."""
        with self._lock:
            if self._released:
                return False
            if self._in_transit:
                raise ValueError(
                    f"double detach of lease (owner {self.owner!r})")
            self._in_transit = True
            return True

    def reattach(self) -> None:
        """Ack the hand-off's FAILURE path: the transfer did not go
        through, ownership returns to the source pool (the request can
        requeue and resume there). Idempotent; the success path's ack
        is ``release()`` after the destination lease is attached."""
        with self._lock:
            self._in_transit = False

    def release(self, cache_hook=None) -> bool:
        """Idempotent: returns the pages exactly once, False on the
        second and later calls (every settle path may call it).
        `cache_hook(lease)`, when given by the WINNING caller, runs
        after the claim but before the allocator release — the owner
        refs are still held, so a prefix-cache insert inside it can
        never fork a freed block, and a concurrent settle-path release
        cannot race it (it lost the claim)."""
        with self._lock:
            if self._released:
                return False
            self._released = True
        if cache_hook is not None:
            try:
                cache_hook(self)
            except Exception:
                # Caching is opportunistic; the pages return regardless.
                log.exception("kv lease %s: prefix-cache insert failed",
                              self.owner)
        self.allocator.release(self.blocks, self.owner)
        return True

    def on_request_settled(self) -> None:
        """GenerateRequest.finish() hook — the one choke point that
        guarantees pages return on EVERY settle path (fail, shed,
        server stop, handler abandon), not only the happy retire."""
        self.release()

    def __repr__(self):
        return (f"KVLease(owner={self.owner!r}, blocks={self.blocks}, "
                f"cached={self.cached_tokens}, "
                f"released={self._released})")


class _Node:
    __slots__ = ("key", "parent", "tokens", "block", "children",
                 "last_used", "origin")

    def __init__(self, key: str, parent: str, tokens: Tuple[int, ...],
                 block: int, last_used: int, origin: str = "hbm"):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.block = block
        self.children = 0
        self.last_used = last_used
        # Where this block's bytes came from, pending first credit:
        # "hbm" for locally computed KV, "remote" for a cross-replica
        # pull (ISSUE 17) — the first match consumes the tag so the
        # pull is credited to the request it actually saved prefill
        # for, and every later hit counts as the HBM hit it is.
        self.origin = origin


_ROOT = "root"


class PrefixTree:
    """Block-granular prefix cache keyed on CHAINED token-id hashes:
    node key = H(parent_key, this block's token ids). The chain makes
    a block's identity its whole prefix, so two prompts sharing only a
    middle run never alias; token ids are stored on the node and
    re-verified on match, so even a hash collision cannot serve wrong
    KV. Eviction is LRU over LEAF nodes only (an interior block must
    outlive chains extending through it)."""

    def __init__(self, allocator: KVBlockAllocator):
        self.allocator = allocator
        self.block_size = allocator.block_size
        self._lock = threading.Lock()
        self._nodes: Dict[str, _Node] = {}
        self._clock = 0
        # Token-denominated hit accounting for the scrape-time
        # serving_kv_prefix_hit_frac gauge — split by WHERE the hit's
        # bytes came from (ISSUE 17): plain HBM residency, a host-tier
        # restore, or a cross-replica pull. ``hit_tokens`` (the sum)
        # keeps its historical meaning for existing callers.
        self.hit_tokens_by_tier: Dict[str, int] = {
            "hbm": 0, "host": 0, "remote": 0}
        self.lookup_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0
        # Evict-to-tier seam (ISSUE 17): when set, ``evict`` offers
        # each victim's (parent_key, tokens, key, block) here BEFORE
        # releasing the cache ref, still under the tree lock — the
        # lock is what resolves the spill-vs-match race (a concurrent
        # match_and_fork either sees the node and forks it live, or
        # runs after the spill completed and takes the restore path;
        # never a freed-block fork).
        self.spill_hook = None

    @property
    def hit_tokens(self) -> int:
        return sum(self.hit_tokens_by_tier.values())

    @staticmethod
    def _key(parent: str, tokens: Tuple[int, ...]) -> str:
        h = hashlib.sha1(
            f"{parent}|{','.join(map(str, tokens))}".encode())
        return h.hexdigest()

    def __len__(self) -> int:
        with self._lock:
            return len(self._nodes)

    def match_and_fork(self, tokens: Sequence[int], owner: str,
                       by_tier: Optional[Dict[str, int]] = None
                       ) -> Tuple[List[int], int]:
        """Longest cached full-block prefix of `tokens`, capped at
        ``len(tokens) - 1`` (the last prompt token always recomputes —
        it emits the first decode token). The matched blocks are
        forked to `owner` UNDER THE TREE LOCK, so eviction can never
        recycle them between lookup and ref. Returns (blocks,
        cached_token_count); when `by_tier` is given, per-tier hit
        token counts are added into it (remote-pulled blocks credit
        "remote" on their first serve, "hbm" after)."""
        bs = self.block_size
        with self._lock:
            self.lookup_tokens += len(tokens)
            limit = max(0, (len(tokens) - 1) // bs)
            node_key = _ROOT
            blocks: List[int] = []
            matched: List[_Node] = []
            for i in range(limit):
                chunk = tuple(int(t)
                              for t in tokens[i * bs:(i + 1) * bs])
                key = self._key(node_key, chunk)
                node = self._nodes.get(key)
                if node is None or node.tokens != chunk:
                    break
                self._clock += 1
                node.last_used = self._clock
                blocks.append(node.block)
                matched.append(node)
                node_key = key
            if blocks:
                self.allocator.fork(blocks, owner)
                for node in matched:
                    self.hit_tokens_by_tier[node.origin] += bs
                    if by_tier is not None:
                        by_tier[node.origin] = (
                            by_tier.get(node.origin, 0) + bs)
                    node.origin = "hbm"
            return blocks, len(blocks) * bs

    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               origin: str = "hbm") -> int:
        """Cache every full block of `tokens` (block i must be
        ``blocks[i]``). The TREE takes its own ref on each newly
        cached block; already-cached chunks keep their original block
        (first insert wins — both hold identical KV by construction).
        ``origin`` tags newly created nodes ("remote" for a
        cross-replica pull, so their first serve is credited to the
        pull that fetched them). Returns the number of blocks newly
        cached."""
        bs = self.block_size
        added = 0
        with self._lock:
            node_key = _ROOT
            for i in range(len(tokens) // bs):
                chunk = tuple(int(t)
                              for t in tokens[i * bs:(i + 1) * bs])
                key = self._key(node_key, chunk)
                node = self._nodes.get(key)
                if node is None:
                    self.allocator.fork([blocks[i]], CACHE_OWNER)
                    self._clock += 1
                    node = _Node(key, node_key, chunk, blocks[i],
                                 self._clock, origin=origin)
                    self._nodes[key] = node
                    parent = self._nodes.get(node_key)
                    if parent is not None:
                        parent.children += 1
                    self.inserted_blocks += 1
                    added += 1
                self._clock += 1
                node.last_used = self._clock
                node_key = key
        return added

    def attach_restored(self, parent_key: str, tokens: Sequence[int],
                        block: int, owner: str, tier: str = "host"
                        ) -> Tuple[int, bool]:
        """Publish ONE restored block (host-tier or remote-pulled
        bytes, already written into `block`) as the cache node for
        `tokens` under `parent_key`, and fork the winning block to
        `owner` — all under the tree lock. The caller must already
        hold an owner ref on `block` (its fresh acquire).

        Returns ``(block_to_use, created)``: when the chain node
        already exists (a concurrent request re-inserted the same
        chunk — first insert wins, same as ``insert``), the EXISTING
        node's block is forked instead and the caller must release its
        now-redundant copy. The hit is credited to `tier` only when
        this restore actually created the node; a lost race is the
        HBM hit it turned out to be."""
        chunk = tuple(int(t) for t in tokens)
        key = self._key(parent_key, chunk)
        with self._lock:
            self._clock += 1
            node = self._nodes.get(key)
            if node is not None and node.tokens == chunk:
                node.last_used = self._clock
                self.allocator.fork([node.block], owner)
                self.hit_tokens_by_tier[node.origin] += len(chunk)
                node.origin = "hbm"
                return node.block, False
            self.allocator.fork([block], CACHE_OWNER)
            node = _Node(key, parent_key, chunk, block, self._clock)
            self._nodes[key] = node
            parent = self._nodes.get(parent_key)
            if parent is not None:
                parent.children += 1
            self.inserted_blocks += 1
            self.hit_tokens_by_tier[tier] += len(chunk)
            return block, True

    def evict(self, want_free: int, spill: bool = True) -> int:
        """Drop LRU leaf entries until `want_free` blocks actually hit
        the free list (or no leaves remain). A victim still shared
        with a live request frees nothing — its cache entry goes, the
        pages live on with the request — so the loop keeps going until
        real capacity appears. With a ``spill_hook`` installed (and
        ``spill`` true), each victim's bytes are offered to the host
        tier BEFORE its ref is released — still under the tree lock,
        so a concurrent match can never fork the freed block (the
        ISSUE 17 spill-vs-fork contract). Spilling is opportunistic:
        a hook failure degrades to plain drop-on-evict. Returns blocks
        actually freed."""
        freed = 0
        hook = self.spill_hook if spill else None
        with self._lock:
            # One leaf scan, then an incrementally-maintained heap:
            # last_used is frozen while we hold the lock (match/insert
            # need it too), so heap order stays truthful and evicting
            # k of n blocks is O(n + k log n) — the old rescan-per-
            # victim loop was O(k*n) on the admission hot path.
            heap = [(n.last_used, n.key) for n in self._nodes.values()
                    if n.children == 0]
            heapq.heapify(heap)
            while freed < want_free and heap:
                _, key = heapq.heappop(heap)
                victim = self._nodes.pop(key)
                parent = self._nodes.get(victim.parent)
                if parent is not None:
                    parent.children -= 1
                    if parent.children == 0:
                        heapq.heappush(
                            heap, (parent.last_used, parent.key))
                if hook is not None:
                    try:
                        hook(victim.parent, victim.tokens, victim.key,
                             victim.block)
                    except Exception:
                        log.exception(
                            "prefix tree: spill hook failed for block "
                            "%d (dropping)", victim.block)
                freed += self.allocator.release([victim.block],
                                                CACHE_OWNER)
                self.evicted_blocks += 1
        return freed

    def flush(self) -> int:
        """Release every cached ref (teardown / tests) — no spill:
        flushing exists to FREE memory, parking the flushed bytes in
        host RAM would defeat it."""
        return self.evict(self.allocator.num_blocks, spill=False)

    def keys(self) -> List[str]:
        """Resident chain keys — the gossip publisher's HBM half
        (ISSUE 17 router): membership is all the router needs, the
        chain construction already encodes each key's whole prefix."""
        with self._lock:
            return list(self._nodes)

    def hit_frac(self) -> float:
        return (self.hit_tokens / self.lookup_tokens
                if self.lookup_tokens else 0.0)
