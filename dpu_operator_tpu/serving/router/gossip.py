"""Prefix-key gossip: how replicas tell the router what they hold.

Each replica periodically publishes its content-addressed prefix map —
every chain key resident in its PrefixTree (tier ``hbm``) or parked in
its host tier (tier ``host``) — to a shared ``GossipBoard``. The
publish cadence rides the existing metrics/health rhythm: callers
invoke ``maybe_publish()`` from paths that already run on that clock
(the server's derived-metrics scrape, the router's route loop) and the
publisher rate-limits itself to ``cadence_s``, so gossip adds no new
threads and no new timers.

The staleness contract (docs/serving.md): the board stores each
snapshot with its publish time and the ROUTER filters at read time —
a map older than ``max_age_s`` reads as empty, i.e. as a cache miss.
Staleness is therefore a pure performance event (a wasted pull, a
missed affinity); it can never be a correctness event, because every
byte a stale map causes to move is chained-hash re-verified on the
receiving side before it is published into a tree
(``tiering.verify_block_tokens``, the GL019 discipline).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = ["GossipBoard", "ReplicaGossip"]


class GossipBoard:
    """The cluster-shared key map: replica name → (publish time,
    {chain key → tier}). In-process stand-in for a gossip fabric —
    replicas write snapshots, the router reads a merged, age-filtered
    view. Thread-safe; snapshots are replaced whole (a reader never
    sees a half-published map)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._maps: Dict[str, tuple] = {}

    def publish(self, replica: str, keymap: Dict[str, str],
                now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else now
        with self._lock:
            self._maps[replica] = (t, dict(keymap))

    def published_at(self, replica: str) -> Optional[float]:
        with self._lock:
            entry = self._maps.get(replica)
            return entry[0] if entry else None

    def snapshot(self, max_age_s: Optional[float] = None,
                 now: Optional[float] = None
                 ) -> Dict[str, Dict[str, str]]:
        """Merged view for scoring. With ``max_age_s``, maps older
        than that read as EMPTY — the staleness contract: a lagging
        replica simply stops attracting affinity until it gossips
        again."""
        t = time.monotonic() if now is None else now
        with self._lock:
            out = {}
            for name, (published, keymap) in self._maps.items():
                if max_age_s is not None and t - published > max_age_s:
                    out[name] = {}
                else:
                    out[name] = keymap
            return out


class ReplicaGossip:
    """One replica's publisher: collects {chain key → tier} from its
    executors (PrefixTree keys as ``hbm``, host-tier keys as ``host``
    — HBM wins when a block is resident in both) and publishes to the
    board, rate-limited to ``cadence_s``."""

    def __init__(self, board: GossipBoard, name: str, executors,
                 cadence_s: float = 0.25):
        self.board = board
        self.name = name
        self.executors = list(executors)
        self.cadence_s = float(cadence_s)
        self._lock = threading.Lock()
        self._last_publish = 0.0

    def collect(self) -> Dict[str, str]:
        keymap: Dict[str, str] = {}
        for ex in self.executors:
            tier = getattr(ex, "tier", None)
            if tier is not None:
                for key in tier.keys():
                    keymap[key] = "host"
            prefix = getattr(ex, "prefix", None)
            if prefix is not None:
                for key in prefix.keys():
                    keymap[key] = "hbm"
        return keymap

    def maybe_publish(self, force: bool = False) -> bool:
        """Publish if the cadence allows (or ``force``). Returns
        whether a publish happened — the router's scoring freshness
        depends only on this being CALLED often enough, the cadence
        bounds how often it actually walks the trees."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_publish < self.cadence_s:
                return False
            self._last_publish = now
        self.board.publish(self.name, self.collect(), now=now)
        return True


def chain_keys(tokens, block_size: int) -> List[str]:
    """The request's own chain, one key per FULL block, capped at
    ``len(tokens) - 1`` (match_and_fork's cap: the last prompt token
    always recomputes). Key i's chain construction encodes the whole
    prefix through block i, so membership of key i in a replica's map
    implies that replica once held the entire prefix."""
    from ..kvcache.allocator import _ROOT, PrefixTree

    bs = int(block_size)
    limit = max(0, (len(tokens) - 1) // bs)
    keys: List[str] = []
    parent = _ROOT
    for i in range(limit):
        chunk = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
        parent = PrefixTree._key(parent, chunk)
        keys.append(parent)
    return keys
