"""Prefix-aware front-end router (ISSUE 17): route to the KV, and
when the KV is elsewhere, move the KV — never re-prefill shared bytes.

``PrefixRouter`` sits in front of N replicas (each a ``RouterReplica``:
an admission queue + a KV executor + its gossip publisher). For each
incoming request it:

  1. computes the request's own chain keys (gossip.chain_keys — the
     same chained sha1 the PrefixTree uses, so scoring is
     content-addressed end to end);
  2. scores every replica by its longest CONTIGUOUS cached prefix in
     the age-filtered gossip snapshot (contiguity matters: the restore
     and pull paths both walk the chain from the matched depth, an
     island past a gap is unreachable);
  3. routes to the owning replica (ties broken by load), UNLESS the
     owner is overloaded past ``max_load_skew`` queued requests
     relative to the least-loaded replica — then the request goes to
     the least-loaded replica and the router first PULLS the prefix
     blocks from the owner over ``KVPageStream`` into the target pool,
     so prefill covers only the uncached suffix.

The pull is best-effort by design: any stream failure (cut
mid-transfer, nack, refused hello) falls back to local prefill of the
whole prompt — the deterministic recurrence makes the resulting stream
identical either way, only slower. The receiving side re-verifies the
claimed chain keys against the shipped token ids
(``verify_block_tokens``) before publishing anything into its tree:
a lying or stale sender degrades to re-prefill, never to wrong KV.
``KVSpec`` hello-checks both ends of every stream (model identity,
layout, codec), and sharded pools inherit the per-rank ``rank_view``
sub-stream transfer from the PR 16 stream plane untouched.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from types import SimpleNamespace
from typing import Dict, List, Optional

from ... import faults
from ..disagg.stream import (KVPageStream, KVPageStreamServer,
                             KVStreamError)
from ..kvcache.allocator import _ROOT as _TREE_ROOT
from ..kvcache.tiering import verify_block_tokens
from .gossip import GossipBoard, ReplicaGossip, chain_keys

log = logging.getLogger(__name__)

__all__ = ["PrefixRouter", "RouterReplica"]


class RouterReplica:
    """One routable serving replica: name, admission queue, KV
    executor, and (lazily, when pulls are enabled) a
    ``KVPageStreamServer`` importing pulled prefixes into the
    executor's pool. The batcher driving the queue is owned by the
    caller — the router only submits and moves KV."""

    def __init__(self, name: str, queue, executor,
                 registry=None):
        self.name = name
        self.queue = queue
        self.executor = executor
        self.registry = registry
        self.gossip: Optional[ReplicaGossip] = None  # set by router
        self._server: Optional[KVPageStreamServer] = None
        self._streams: Dict[str, KVPageStream] = {}
        self._lock = threading.Lock()

    def load(self) -> int:
        return int(self.queue.depth() + self.queue.inflight())

    # -- pull plumbing --------------------------------------------------------

    def pull_addr(self):
        """This replica's import endpoint, starting the server on
        first use (hello-checked by its executor's KVSpec)."""
        with self._lock:
            if self._server is None:
                self._server = KVPageStreamServer(
                    self.executor.kv_spec, self._pull_import)
            return self._server.addr

    def stream_to(self, dst: "RouterReplica") -> KVPageStream:
        """Source-side stream client toward `dst`, cached per pair —
        the hello/spec check runs once per (src, dst) connection."""
        with self._lock:
            stream = self._streams.get(dst.name)
        if stream is None:
            stream = KVPageStream(self.executor.kv_spec,
                                  dst.pull_addr())
            with self._lock:
                self._streams[dst.name] = stream
        return stream

    def drop_stream(self, dst_name: str) -> None:
        with self._lock:
            stream = self._streams.pop(dst_name, None)
        if stream is not None:
            stream.close()

    def _pull_import(self, meta: dict, planes: list) -> dict:
        """Import one pulled prefix: re-derive every claimed chain key
        from the shipped token ids (the GL019 chained-hash
        re-verification — a collision or a lying sender degrades to
        re-prefill), write the planes into freshly acquired blocks,
        and publish them tagged ``origin="remote"`` so their first
        serve is credited to the pull. The temp owner's refs release
        in the finally — on ANY failure the ledger stays clean and
        the nack falls back to local prefill."""
        # ``kind`` is the stream protocol's field ("pages" on the
        # wire); the pull marker rides its own key.
        if not meta.get("prefix_pull"):
            raise ValueError("pull endpoint got a non-pull transfer")
        ex = self.executor
        bs = ex.block_size
        tokens = [int(t) for t in meta["prompt_tokens"]]
        keys = list(meta["keys"])
        n_blocks = int(meta["n_blocks"])
        if n_blocks != len(keys) or n_blocks * bs != len(tokens):
            raise ValueError(
                f"pull geometry mismatch: {n_blocks} block(s), "
                f"{len(keys)} key(s), {len(tokens)} token(s)")
        parent = _TREE_ROOT
        for i, key in enumerate(keys):
            chunk = tokens[i * bs:(i + 1) * bs]
            if not verify_block_tokens(parent, chunk, key):
                raise ValueError(
                    f"pulled prefix fails chained-hash "
                    f"re-verification at block {i}")
            parent = key
        owner = f"__pull_import__{meta.get('xfer', 'x')}"
        fresh = ex._acquire_with_evict(n_blocks, owner)
        try:
            ex._import_pages(fresh, planes, dict(meta))
            ex.prefix.insert(tokens, fresh, origin="remote")
        finally:
            # The tree holds CACHE_OWNER refs on whatever it kept
            # (first insert wins); the temp owner always lets go.
            ex.allocator.release(fresh, owner)
        return {"blocks": n_blocks}

    def close(self) -> None:
        with self._lock:
            streams = list(self._streams.values())
            self._streams.clear()
            server, self._server = self._server, None
        for s in streams:
            s.close()
        if server is not None:
            server.close()


class PrefixRouter:
    """The scoring + placement front end over ``RouterReplica``s.

    ``policy="prefix"`` is the routed arm; ``policy="round_robin"``
    is the baseline arm the bench compares against (same machinery,
    no scoring, no pulls)."""

    def __init__(self, replicas: List[RouterReplica],
                 policy: str = "prefix", max_age_s: float = 5.0,
                 cadence_s: float = 0.05, pull: bool = True,
                 pull_min_blocks: int = 1, max_load_skew: int = 8,
                 registry=None, tracer=None):
        if policy not in ("prefix", "round_robin"):
            raise ValueError(
                f"policy must be prefix|round_robin, got {policy!r}")
        if not replicas:
            raise ValueError("router needs at least one replica")
        sizes = {r.executor.block_size for r in replicas}
        if len(sizes) != 1:
            raise ValueError(
                f"replicas disagree on block_size: {sorted(sizes)} — "
                f"chain keys would never match across them")
        self.replicas = list(replicas)
        self.block_size = sizes.pop()
        self.policy = policy
        self.max_age_s = float(max_age_s)
        self.pull = bool(pull)
        self.pull_min_blocks = int(pull_min_blocks)
        self.max_load_skew = int(max_load_skew)
        self.registry = registry
        self.tracer = tracer
        self.board = GossipBoard()
        for r in self.replicas:
            r.gossip = ReplicaGossip(self.board, r.name, [r.executor],
                                     cadence_s=cadence_s)
        self._rr = 0
        self._lock = threading.Lock()

    # -- bookkeeping ----------------------------------------------------------

    def _count(self, name: str, labels=None, by: float = 1.0,
               help: str = "") -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, labels, by=by, help=help)

    def _event(self, name: str, req, attrs: dict) -> None:
        if self.tracer is not None:
            self.tracer.event(
                name, request_id=getattr(req, "request_id", None),
                parent_id=getattr(req, "trace_parent", None),
                attrs=attrs)

    # -- scoring --------------------------------------------------------------

    def scores(self, tokens) -> Dict[str, int]:
        """Cached-prefix tokens per replica: the longest contiguous
        run of the request's chain present in each (age-filtered)
        gossip map."""
        keys = chain_keys(tokens, self.block_size)
        view = self.board.snapshot(max_age_s=self.max_age_s)
        out: Dict[str, int] = {}
        for r in self.replicas:
            keymap = view.get(r.name, {})
            depth = 0
            for key in keys:
                if key not in keymap:
                    break
                depth += 1
            out[r.name] = depth * self.block_size
        return out

    def route(self, req) -> RouterReplica:
        """Pick the replica (and run the affinity-miss pull when one
        applies). Does NOT submit — ``submit()`` wraps this."""
        for r in self.replicas:
            r.gossip.maybe_publish()
        if self.policy == "round_robin":
            with self._lock:
                chosen = self.replicas[self._rr % len(self.replicas)]
                self._rr += 1
            self._count("serving_router_routed_total",
                        {"outcome": "rr"},
                        help="router placements by outcome")
            return chosen
        tokens = getattr(req, "prompt_tokens", None) or []
        scored = self.scores(tokens)
        best = max(self.replicas, key=lambda r: (scored[r.name],
                                                 -r.load()))
        # Rotate load ties: min() alone would pin every cold request
        # to the first replica while loads are equal (fast replicas
        # drain to zero between arrivals), starving the rest of the
        # fleet of any prefix to own.
        with self._lock:
            start = self._rr % len(self.replicas)
            self._rr += 1
        order = self.replicas[start:] + self.replicas[:start]
        least = min(order, key=lambda r: r.load())
        outcome, chosen = "cold", least
        if scored[best.name] > 0:
            if best.load() - least.load() <= self.max_load_skew:
                outcome, chosen = "affinity", best
            else:
                # The owner is swamped: place by load and move the
                # prefix to the chosen replica instead of the request
                # to the hot one.
                outcome, chosen = "load", least
                gain = scored[best.name] - scored[chosen.name]
                if (self.pull and chosen is not best
                        and gain >= self.pull_min_blocks
                        * self.block_size):
                    self._pull(best, chosen, tokens, req)
        self._count("serving_router_routed_total",
                    {"outcome": outcome},
                    help="router placements by outcome")
        self._event("router.route", req,
                    {"replica": chosen.name, "outcome": outcome,
                     "score_tokens": scored[chosen.name],
                     "best": best.name,
                     "best_tokens": scored[best.name]})
        return chosen

    def submit(self, req) -> RouterReplica:
        chosen = self.route(req)
        chosen.queue.submit(req)
        return chosen

    # -- the affinity-miss pull ------------------------------------------------

    def _pull(self, src: RouterReplica, dst: RouterReplica, tokens,
              req) -> int:
        """Stream `src`'s cached prefix of `tokens` into `dst`'s pool.
        Best-effort: returns pulled block count, 0 on any failure
        (local prefill covers it). Source refs are forked under a temp
        owner and ALWAYS released — a cut transfer leaves both
        ledgers clean."""
        owner = f"__pull__{uuid.uuid4().hex[:8]}"
        ex = src.executor
        t0 = time.monotonic()
        try:
            faults.fire("router.pull",
                        attrs={"src": src.name, "dst": dst.name})
            blocks, cached = ex.kv_match_prefix(tokens, owner)
        except Exception:
            log.warning("router: pull source match failed "
                        "(%s -> %s), prefilling locally",
                        src.name, dst.name, exc_info=True)
            self._count("serving_router_pull_failed_total",
                        help="cross-replica pulls that fell back to "
                             "local prefill")
            return 0
        if not blocks:
            self.allocator_release(ex, blocks, owner)
            return 0
        try:
            shim = SimpleNamespace(
                request_id=owner,
                prompt_tokens=[int(t) for t in tokens[:cached]],
                tokens=[])
            planes = ex._export_pages(blocks, shim, cached)
            meta = {"req": owner, "prefix_pull": True,
                    "xfer": owner.rsplit("__", 1)[-1],
                    "tokens": cached, "n_blocks": len(blocks),
                    "prompt_tokens": [int(t)
                                      for t in tokens[:cached]],
                    "settled": [], "max_tokens": 0,
                    "keys": chain_keys(tokens[:cached + 1],
                                       self.block_size)[:len(blocks)]}
            stream = src.stream_to(dst)
            ack = stream.send_pages(meta, planes)
            dt = time.monotonic() - t0
            nbytes = sum(int(arr.nbytes) for pair in planes
                         for arr in pair)
            self._count("serving_router_pulled_blocks_total",
                        by=float(len(blocks)),
                        help="prefix blocks moved by cross-replica "
                             "pulls")
            self._count("serving_router_pull_bytes_total",
                        by=float(nbytes),
                        help="pool bytes moved by cross-replica pulls")
            self._count("serving_router_pull_seconds_total", by=dt,
                        help="wall seconds spent in cross-replica "
                             "pulls")
            self._event("router.pull", req,
                        {"src": src.name, "dst": dst.name,
                         "blocks": len(blocks), "bytes": nbytes,
                         "outcome": "ok",
                         "ack_blocks": ack.get("blocks")})
            return len(blocks)
        except (KVStreamError, OSError, ValueError) as e:
            # Torn stream / nack / refused hello: drop the (possibly
            # desynced) stream, fall back to prefill. The request is
            # unharmed — it has not even been enqueued yet.
            src.drop_stream(dst.name)
            log.warning("router: pull %s -> %s failed (%s), "
                        "prefilling locally", src.name, dst.name, e)
            self._count("serving_router_pull_failed_total",
                        help="cross-replica pulls that fell back to "
                             "local prefill")
            self._event("router.pull", req,
                        {"src": src.name, "dst": dst.name,
                         "outcome": "failed",
                         "error": str(e)[:120]})
            return 0
        finally:
            ex.allocator.release(blocks, owner)

    @staticmethod
    def allocator_release(ex, blocks, owner) -> None:
        """Release-if-held: a zero-block match never registered the
        owner, releasing nothing must not raise."""
        if blocks:
            ex.allocator.release(blocks, owner)

    def close(self) -> None:
        for r in self.replicas:
            r.close()
