"""Cluster-wide prefix cache front end (ISSUE 17).

``PrefixRouter`` + ``RouterReplica`` (router.py) place requests on the
replica that already holds their prefix — scored over the
content-addressed key maps replicas publish through ``GossipBoard`` /
``ReplicaGossip`` (gossip.py) — and move KV over ``KVPageStream``
when placement and residency disagree. Jax-free, like the rest of the
scheduler plane.
"""

from .gossip import GossipBoard, ReplicaGossip, chain_keys
from .router import PrefixRouter, RouterReplica

__all__ = [
    "GossipBoard",
    "PrefixRouter",
    "ReplicaGossip",
    "RouterReplica",
    "chain_keys",
]
