"""Request/response vocabulary of the serving plane.

A GenerateRequest is the unit the continuous-batching scheduler moves:
it enters through the HTTP front-end (server.py), waits in the bounded
AdmissionQueue, occupies one batch SLOT in a ContinuousBatcher for
`max_tokens` decode steps (or until its deadline), and completes back
into the waiting handler thread via its event. Everything here is
dependency-free (no jax) so the queue/scheduler plane imports in any
process — the model only enters through the Executor seam.
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


class ServingError(Exception):
    """Base class for serving-plane rejections."""


class QueueFull(ServingError):
    """Admission refused: queue at max depth. Carries the backpressure
    hint the HTTP layer turns into a 503 + Retry-After."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"admission queue full (depth={depth})")
        self.depth = depth
        self.retry_after_s = retry_after_s


class Draining(ServingError):
    """Admission refused: server is draining (SIGTERM received).
    In-flight requests keep running; new ones must go elsewhere."""


class TenantOverBudget(ServingError):
    """Admission refused: this tenant's token bucket is empty. Carries
    the refill hint the HTTP layer turns into a 429 + Retry-After —
    per-tenant backpressure, distinct from QueueFull's 503: the SERVER
    has capacity, this tenant has spent its share of it."""

    def __init__(self, tenant: str, retry_after_s: float):
        super().__init__(f"tenant {tenant!r} over admission budget")
        self.tenant = tenant
        self.retry_after_s = retry_after_s


#: Priority classes, in strict pop order: every queued interactive
#: request is served before any batch request, and a batch occupant is
#: the only legal preemption victim. Unknown classes are rejected at
#: the HTTP door (400) — a typo must not silently become a new class.
PRIORITIES = ("interactive", "batch")

#: Default cap on distinct tenant label values any one metrics series
#: may carry. Tenant names arrive from the wire, so an adversarial
#: client could otherwise mint unbounded label cardinality.
TENANT_LABEL_CAP = 16


def bounded_tenant_label(tenant: str, seen: set,
                         cap: int = TENANT_LABEL_CAP) -> str:
    """Metrics-safe tenant label: the first `cap` distinct tenants keep
    their own label value, everyone later folds into "other". `seen` is
    the caller-owned admitted-label set (callers mutate it under their
    own lock — the queue and server each bound their series
    independently, so one plane's overflow never renames the other's)."""
    if tenant in seen:
        return tenant
    if len(seen) < cap:
        seen.add(tenant)
        return tenant
    return "other"


# The queue's shed-at-pop error, matched EXACTLY by the HTTP layer to
# pick 503 (back off and retry elsewhere) over 500 (replica failure) —
# a substring match would misclassify executor errors that merely
# mention deadlines (e.g. a collective's DEADLINE_EXCEEDED).
DEADLINE_QUEUED_ERROR = "deadline exceeded while queued"

# The supervisor's give-up error: a request that rode `attempts`
# replica failures has burned its retry budget — 500, not 503, because
# retrying elsewhere is exactly what already failed (matched exactly,
# same reasoning as above).
RETRIES_EXHAUSTED_ERROR = "retries_exhausted"

# KV admission shed: the paged allocator has no pages for this
# request's worst case (prompt + max_tokens). Matched EXACTLY by the
# HTTP layer → 503 + Retry-After: capacity pressure, not a replica
# failure, and pages free as in-flight requests finish.
KV_OOM_ERROR = "kv cache exhausted"


def encode_prompt(text: str, d: int) -> np.ndarray:
    """Deterministic prompt → [d] model-state embedding. The serving
    model (a forward-only view of train_step's stage stack) consumes
    hidden vectors, not token strings; this is the stand-in tokenizer:
    same text always maps to the same state, distinct texts to distinct
    states, so caching/batching behavior is measurable end-to-end."""
    seed = int.from_bytes(hashlib.sha256(text.encode()).digest()[:4], "big")
    return np.random.RandomState(seed).randn(d).astype(np.float32)


def encode_prompt_tokens(text: str, n: int, vocab: int) -> List[int]:
    """Deterministic prompt → n token ids in [0, vocab): the stand-in
    tokenizer for the paged-KV plane (token ids, not hidden vectors —
    the KV executors embed them on device). Same text, same ids, so
    prefix caching across identical prompts is measurable end-to-end."""
    seed = int.from_bytes(hashlib.sha256(text.encode()).digest()[:4], "big")
    return [int(t) for t in
            np.random.RandomState(seed).randint(0, vocab, size=n)]


@dataclass
class GenerateRequest:
    """One in-flight generation. Timestamps are time.monotonic() so
    queue/decode decomposition survives wall-clock jumps."""

    prompt_vec: np.ndarray
    max_tokens: int
    deadline: float                      # absolute monotonic
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    arrival: float = field(default_factory=time.monotonic)
    admitted_at: Optional[float] = None  # scheduler placed it in a slot
    # First decoded token settled (TTFT's right edge): stamped by the
    # retire paths on the first append only, so it covers queue +
    # admission + the whole prefill — exactly what a prefix-cache hit
    # (ISSUE 17) shrinks and what serving_ttft_p99_ms measures.
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    tokens: List[int] = field(default_factory=list)
    truncated: bool = False              # deadline hit mid-decode
    error: Optional[str] = None
    # Replica failures survived so far: the supervisor bumps this on
    # every re-admission after a replica death/wedge; past the pool's
    # attempts budget the request 500s with RETRIES_EXHAUSTED_ERROR.
    attempts: int = 0
    # Multi-tenant QoS (ISSUE 20): who this request bills to and which
    # priority class it rides. Preemption is policy, not failure — a
    # preempted request requeues WITHOUT touching `attempts` (that
    # budget counts replica faults survived, and a batch request parked
    # N times under interactive pressure has survived zero of them);
    # `preemptions` counts the parks separately for tracing/tests.
    tenant: str = "default"
    priority: str = "interactive"
    preemptions: int = 0
    # Span id (int) of the HTTP handler's root "request" span: the
    # explicit parent every cross-thread span for this request hangs
    # off (queue, admit/retire, supervisor requeue). None for requests
    # submitted without a traced front door.
    trace_parent: Optional[int] = None
    # (Re-)enqueue time, stamped by AdmissionQueue.submit/requeue: the
    # queue.wait span's t0. Distinct from arrival so a requeued
    # request's second wait leg doesn't swallow its failed first
    # decode attempt (seize/requeue latency has its own spans).
    enqueued_at: float = field(default_factory=time.monotonic)
    # Paged-KV plane (ISSUE 7): token-id prompt (the KV executors
    # embed ids on device; prompt_vec is the legacy hidden-vector
    # plane and is None for KV requests) and the request's KV-page
    # lease. The lease is OPAQUE here (duck-typed kvcache.KVLease —
    # this module stays dependency-free) and rides the request through
    # the supervisor's seize→requeue path: block-table ownership
    # travels the queue, which is what makes retry re-attach pages
    # instead of re-decoding the prompt.
    prompt_tokens: Optional[List[int]] = None
    kv_lease: Optional[object] = field(default=None, repr=False)
    _done: threading.Event = field(default_factory=threading.Event,
                                   repr=False)

    def finish(self) -> None:
        self.finished_at = time.monotonic()
        # The one settle choke point for KV pages: whichever path
        # settles this request (retire, fail, shed, server stop), the
        # lease releases exactly once (release is idempotent — the
        # happy retire path already released-and-cached before
        # finishing, and this no-ops).
        lease = self.kv_lease
        if lease is not None:
            lease.on_request_settled()
        self._done.set()

    def fail(self, error: str) -> None:
        self.error = error
        self.finish()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def timings_ms(self) -> dict:
        """queue/decode/total decomposition for the response body."""
        end = self.finished_at or time.monotonic()
        admitted = self.admitted_at
        queue_ms = ((admitted - self.arrival) if admitted is not None
                    else (end - self.arrival)) * 1000.0
        decode_ms = ((end - admitted) * 1000.0
                     if admitted is not None else 0.0)
        out = {
            "queue_ms": round(queue_ms, 3),
            "decode_ms": round(decode_ms, 3),
            "total_ms": round((end - self.arrival) * 1000.0, 3),
        }
        if self.first_token_at is not None:
            out["ttft_ms"] = round(
                (self.first_token_at - self.arrival) * 1000.0, 3)
        return out
