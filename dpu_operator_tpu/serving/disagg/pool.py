"""DisaggPool — role-typed disaggregated prefill/decode serving.

The production inference topology ROADMAP item 1 names: dedicated
PREFILL replicas build paged KV and stream the pages over the fabric
to DECODE replicas that only ever run the cheap per-token step. The
two workloads stop sharing a latency regime — a prefill flood cannot
inflate a decode replica's step time, because no decode replica ever
plans a prefill chunk.

Composition, not reinvention — each leg is an existing subsystem:

  * both roles are plain ``ReplicaPool``s over plain
    ``ContinuousBatcher``s (supervisor, watchdog, breaker, crash-only
    batchers, tracing and the flight recorder all ride along
    unchanged); the prefill pool's batchers carry the one new seam, a
    ``handoff`` hook that fires when a request emits its first token;
  * the hand-off is the PR 7 lease machinery doing what it was built
    for: ``kv_detach_slot`` detaches the ``KVLease`` (pages stay
    owned — a failed transfer ``reattach()``es and resumes on the
    prefill side), the pages ship over ``KVPageStream`` (PR 9 framed
    transport + int8 codec + hello checks), the importer builds a
    LOCAL lease in the decode pool, and the request re-enters through
    the queue's existing ``requeue()``; the decode-side ``kv_attach``
    then takes the SAME ``_reattach`` path a kill-mid-decode resume
    takes — a lease migrating prefill→decode is the same move as a
    lease surviving a replica kill, so the exactly-once settle choke
    point and the leak ledger carry over with zero new cases;
  * failure disposition mirrors the supervisor's ``_requeue``
    verbatim: settled → skip; deadline lapsed mid-transfer →
    truncated 200 WITH tokens (never a 503 that discards them);
    attempts budget exhausted → 500 ``retries_exhausted``; otherwise
    requeue to the PREFILL queue front — the retried request
    re-attaches its surviving pages there, re-decodes exactly one
    token and hands off again (streams stay byte-identical, the PR 7
    invariance argument carried across replicas).

Topology note: the front/admission queue IS the prefill queue;
transfers requeue into a separate decode-pool queue (depth-exempt —
these requests were admitted once already). With several decode
replicas the transfer targets the emptiest pool, but the decode
queue is shared: a request popped by a different decode replica
falls back to kv_attach's foreign-lease path (release + re-prefill
locally — correct and byte-identical, just not free; the single-
decode-replica config has no such race).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence

from ... import faults
from ...obs import trace as obs_trace
from ..api import (DEADLINE_QUEUED_ERROR, RETRIES_EXHAUSTED_ERROR,
                   GenerateRequest)
from ..executor import ReplicaPool
from ..queue import AdmissionQueue
from .spec import KVSpecMismatch
from .stream import KVPageStream, KVPageStreamServer

log = logging.getLogger(__name__)

__all__ = ["DisaggPool"]

#: KV-page transfers are small-ms on a fabric: resolve them, not
#: request latencies.
_TRANSFER_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 1.0, 2.5)


class DisaggPool:
    """Two role-typed ReplicaPools plus the transfer plane between
    them, presenting the ReplicaPool surface the ServingServer (and
    its health endpoints) already speak."""

    def __init__(self, prefill_executors: Sequence,
                 decode_executors: Sequence, queue: AdmissionQueue,
                 registry=None, *, codec: Optional[str] = None,
                 seg_bytes: int = 1 << 18,
                 transfer_timeout_s: float = 5.0,
                 max_attempts: int = 3,
                 decode_queue_depth: int = 256,
                 pool_opts: Optional[dict] = None,
                 decode_pool_opts: Optional[dict] = None,
                 tracer=None, flight_recorder=None,
                 host: str = "127.0.0.1"):
        if not prefill_executors or not decode_executors:
            raise ValueError("disagg needs >= 1 prefill and >= 1 "
                             "decode executor")
        for ex in list(prefill_executors) + list(decode_executors):
            if not getattr(ex, "kv", False):
                raise ValueError("disagg executors must be paged-KV "
                                 "(the row plane has no transferable "
                                 "state)")
        # One spec rules them all: the layout is declared once and
        # every replica must agree, or pages shipped between them are
        # bytes, not KV.
        self.spec = prefill_executors[0].kv_spec
        for ex in list(prefill_executors)[1:] + list(decode_executors):
            mine, theirs = self.spec.fingerprint(), \
                ex.kv_spec.fingerprint()
            if mine != theirs:
                raise KVSpecMismatch(
                    f"executors disagree on the KV layout: {mine} vs "
                    f"{theirs}")
        self.codec = self.spec.validate_codec(
            codec if codec is not None else self.spec.default_codec())
        self.queue = queue  # the front door doubles as prefill queue
        self.registry = registry
        self.tracer = (tracer if tracer is not None
                       else obs_trace.get_tracer())
        self.flight_recorder = flight_recorder
        self.max_attempts = int(max_attempts)
        self.seg_bytes = int(seg_bytes)
        self.transfer_timeout_s = float(transfer_timeout_s)
        self.decode_executors = list(decode_executors)

        popts = dict(pool_opts or {})
        pre_bk = dict(popts.pop("batcher_kwargs", {}))
        pre_bk["handoff"] = self._enqueue_handoff
        self.prefill_pool = ReplicaPool(
            prefill_executors, queue, registry=registry,
            role="prefill", name_prefix="prefill",
            batcher_kwargs=pre_bk, tracer=self.tracer,
            flight_recorder=flight_recorder, **popts)
        # Separate queue: transfers requeue() into it (depth/drain
        # exempt), so the depth bound only shapes pathological pileup.
        # No registry: serving_queue_depth is the FRONT door's gauge.
        self.decode_queue = AdmissionQueue(
            max_depth=int(decode_queue_depth), tracer=self.tracer)
        dopts = dict(decode_pool_opts if decode_pool_opts is not None
                     else popts)
        dopts.setdefault("batcher_kwargs", {})
        self.decode_pool = ReplicaPool(
            self.decode_executors, self.decode_queue,
            registry=registry, role="decode", name_prefix="decode",
            tracer=self.tracer, flight_recorder=flight_recorder,
            **dopts)

        # One page-stream import server per decode executor (its own
        # pool, its own port), one lazily-connected client stream per
        # target on the transfer worker.
        self._servers = [
            KVPageStreamServer(self.spec, self._import_fn(i),
                               host=host, codec=self.codec,
                               timeout_s=self.transfer_timeout_s)
            for i in range(len(self.decode_executors))]
        self._streams: Dict[int, KVPageStream] = {}
        self._tlock = threading.Lock()
        self._txq: _queue.Queue = _queue.Queue()
        self._transferring = 0      # handed off, not yet settled out
        self._pending: Dict[str, GenerateRequest] = {}  # xfer -> req
        self._imported: Dict[str, object] = {}  # xfer -> decode lease
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._transfer_loop, daemon=True,
            name="kv-transfer")

    # -- ReplicaPool-compatible surface ---------------------------------------

    @property
    def executors(self) -> List:
        return (list(self.prefill_pool.executors)
                + list(self.decode_pool.executors))

    @property
    def supervised(self) -> bool:
        return (self.prefill_pool.supervised
                and self.decode_pool.supervised)

    @property
    def quorum(self) -> int:
        return self.prefill_pool.quorum + self.decode_pool.quorum

    def live_count(self) -> int:
        return (self.prefill_pool.live_count()
                + self.decode_pool.live_count())

    def states(self) -> Dict[str, str]:
        out = self.prefill_pool.states()
        out.update(self.decode_pool.states())
        return out

    def all_parked(self) -> bool:
        return (self.prefill_pool.all_parked()
                and self.decode_pool.all_parked())

    def active(self) -> int:
        with self._tlock:
            transferring = self._transferring
        return (self.prefill_pool.active() + self.decode_pool.active()
                + transferring)

    def start(self) -> None:
        self.prefill_pool.start()
        self.decode_pool.start()
        self._worker.start()

    def stop(self) -> None:
        # Prefill first: no new hand-offs enter the transfer queue
        # after its batchers stop (their occupants fail through the
        # normal stop path). Then the worker, then everything it
        # could still have been feeding.
        self.prefill_pool.stop()
        self._stop.set()
        if self._worker.is_alive():
            self._worker.join(timeout=2 * self.transfer_timeout_s)
        while True:
            try:
                req, detach = self._txq.get_nowait()
            except _queue.Empty:
                break
            detach["lease"].reattach()
            if not req.done:
                req.fail("server stopped")
            with self._tlock:
                self._transferring -= 1
        self.decode_queue.fail_all("server stopped")
        self.decode_pool.stop()
        for s in self._servers:
            s.close()
        # Snapshot: a worker that outlived the bounded join above may
        # still insert a reconnect stream mid-iteration.
        for st in list(self._streams.values()):
            st.close()
        with self._tlock:
            leftovers = list(self._imported.values())
            self._imported.clear()
        for lease in leftovers:
            lease.release()

    def quiesce(self, timeout: float = 30.0,
                poll_s: float = 0.02) -> bool:
        """Drained when the front queue, BOTH pools (including their
        seize hand-off windows) and the transfer plane are all empty.
        ``ReplicaPool.quiesce(timeout=0)`` is its instantaneous idle
        check — each pool covers its own queue/slots/seizing, this
        adds the detach→requeue window the transfer plane owns."""

        def idle() -> bool:
            with self._tlock:
                transferring = self._transferring
            return (transferring == 0
                    and self.prefill_pool.quiesce(timeout=0)
                    and self.decode_pool.quiesce(timeout=0))

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if idle():
                return True
            time.sleep(poll_s)
        return idle()

    # -- role autoscaling (ISSUE 20) ------------------------------------------

    def transfer_backlog(self) -> int:
        """Hand-offs enqueued or in flight on the transfer plane —
        the decode-side pressure signal the RoleAutoscaler reads
        alongside decode queue depth."""
        with self._tlock:
            return self._txq.qsize() + self._transferring

    def flip_role(self, from_role: str) -> Optional[str]:
        """Move one replica between the role pools, live. The executor
        object — allocator, prefix tree, tier, pages — survives the
        move; only the batcher is rebuilt, with the DESTINATION pool's
        batcher_kwargs (gaining or losing the handoff hook is what
        changes the role). In-flight occupants requeue exactly once
        through the policy path (no `attempts` burn), resume via the
        ordinary attach dispositions, and each pool always keeps at
        least one live replica (returns None instead of violating
        that).

        Transfer-target note: the page-stream import servers are
        index-coupled to the ORIGINAL decode executors, so a replica
        flipped INTO the decode pool serves the decode queue but is
        never a transfer target, and one flipped OUT stops being
        preferred by _pick_target — no server is rebound live."""
        if from_role == "prefill":
            src, dst = self.prefill_pool, self.decode_pool
        elif from_role == "decode":
            src, dst = self.decode_pool, self.prefill_pool
        else:
            raise ValueError(
                f"from_role must be prefill|decode, got {from_role!r}")
        ex = src.detach_replica(min_live=1)
        if ex is None:
            return None
        name = dst.attach_replica(ex)
        direction = f"{from_role}_to_{dst.role}"
        self._count("serving_autoscale_flips_total",
                    {"direction": direction},
                    help="role-autoscaler replica flips between the "
                         "prefill and decode pools")
        self.tracer.event("disagg.flip_role",
                          attrs={"direction": direction,
                                 "replica": name})
        self.tracer.decision("flip_role", direction=direction,
                             replica=name)
        log.info("role flip %s: replica now serving as %s",
                 direction, name)
        return name

    # -- the transfer plane ----------------------------------------------------

    def transfer_addrs(self) -> List:
        """Decode-side import endpoints (tests + ops introspection)."""
        return [s.addr for s in self._servers]

    def _count(self, name: str, labels: dict, help: str = "",
               by: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter_inc(name, labels, by=by, help=help)

    def _enqueue_handoff(self, req: GenerateRequest,
                         detach: dict) -> None:
        """The batcher's handoff hook — called under its settle lock,
        so this only counts and enqueues; export/stream runs on the
        transfer worker. From this instant until requeue/settle the
        request is in no slot and no queue: _transferring keeps the
        quiesce accounting closed over the window (the supervisor's
        _seizing discipline, applied to the third hand-off window
        this plane adds)."""
        with self._tlock:
            self._transferring += 1
        self._txq.put((req, detach))

    def _import_fn(self, i: int):
        ex = self.decode_executors[i]

        def import_pages(meta: dict, planes: list) -> dict:
            t0 = time.monotonic()
            lease = ex.kv_import(meta, planes)
            with self._tlock:
                # Register ONLY while the sender still owns the
                # transfer: if its ack deadline fired while we were
                # importing, it already popped _pending and moved on
                # (retry under a fresh xfer id) — registering now
                # would strand these worst-case pages in _imported
                # until stop(), silently draining the decode pool.
                # Both sender paths pop _pending and _imported under
                # this same lock, so the membership check is exact.
                owned = meta["xfer"] in self._pending
                if owned:
                    self._imported[meta["xfer"]] = lease
                req = self._pending.get(meta["xfer"])
            if not owned:
                lease.release()
                raise RuntimeError(
                    f"sender abandoned transfer {meta['xfer']} "
                    f"(request {meta.get('req')}) before the import "
                    f"finished — pages released")
            self.tracer.record_span(
                "disagg.import", t0, time.monotonic(),
                request_id=str(meta.get("req")),
                parent_id=(req.trace_parent if req is not None
                           else None),
                attrs={"replica": f"decode{i}",
                       "blocks": int(meta["n_blocks"]),
                       "codec": self.codec})
            return {"blocks": len(lease.blocks)}

        return import_pages

    def _pick_target(self) -> int:
        """Emptiest decode pool wins (free blocks = admission
        headroom — the decode-side OOM nack is the pressure valve,
        this just steers away from it). Among targets, prefer
        executors still serving IN the decode pool: one flipped out
        by the autoscaler can still import pages, but the requeued
        request would then pop on another replica and re-prefill via
        the foreign-lease path — correct, just wasted transfer."""
        live = {id(e) for e in list(self.decode_pool.executors)}
        idxs = [i for i, e in enumerate(self.decode_executors)
                if id(e) in live]
        if not idxs:
            idxs = list(range(len(self.decode_executors)))
        return max(idxs,
                   key=lambda i:
                   self.decode_executors[i].allocator.free_count())

    def _stream_for(self, i: int) -> KVPageStream:
        st = self._streams.get(i)
        if st is None:
            st = KVPageStream(self.spec, self._servers[i].addr,
                              codec=self.codec,
                              timeout_s=self.transfer_timeout_s,
                              seg_bytes=self.seg_bytes)
            self._streams[i] = st
        return st

    def _transfer_loop(self) -> None:
        while True:
            try:
                req, detach = self._txq.get(timeout=0.05)
            except _queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                self._transfer_one(req, detach)
            except Exception as e:
                # _transfer_one owns its failure paths; reaching here
                # is a harness bug — settle the request exactly once
                # rather than park its handler forever.
                log.exception("kv transfer: unhandled failure "
                              "(request %s)", req.request_id)
                detach["lease"].reattach()
                if not req.done:
                    req.fail(f"kv transfer failed: {e}")
            finally:
                with self._tlock:
                    self._transferring -= 1

    def _transfer_one(self, req: GenerateRequest, detach: dict) -> None:
        lease = detach["lease"]
        src = detach["executor"]
        if req.done:
            # Settled while queued for transfer (handler abandon /
            # stop): the finish choke point already released the
            # prefill lease; just clear the transit mark.
            lease.reattach()
            self._count("serving_kv_transfers_total",
                        {"outcome": "already_done"},
                        help="KV page transfers by disposition")
            return
        t0 = time.monotonic()
        xfer = uuid.uuid4().hex[:12]
        new_lease = None
        target = self._pick_target()
        try:
            faults.fire("disagg.transfer",
                        attrs={"request_id": req.request_id})
            meta, planes = src.kv_export(req, detach)
            meta["xfer"] = xfer
            with self._tlock:
                self._pending[xfer] = req
            ack = self._stream_for(target).send_pages(meta, planes)
            with self._tlock:
                new_lease = self._imported.pop(xfer, None)
                self._pending.pop(xfer, None)
            if new_lease is None:
                raise RuntimeError(
                    f"ack {ack.get('xfer')} without a registered "
                    f"import (request {req.request_id})")
        except Exception as e:
            with self._tlock:
                self._pending.pop(xfer, None)
                orphan = self._imported.pop(xfer, None)
            if orphan is not None:
                # Import landed but the ack leg died: the decode-side
                # pages must not outlive the failed hand-off.
                orphan.release()
            self._transfer_failed(req, lease, target, e, t0)
            return
        t1 = time.monotonic()
        # Sharded pools ship world per-rank sub-streams; the honest
        # byte count (and the per-rank decomposition) derives from
        # each rank's rank_view geometry — head-sharded sub-streams
        # duplicate the tiny per-block scale vector, which this
        # accounting keeps visible instead of papering over.
        rank_counts = meta.get("rank_blocks")
        if rank_counts is not None:
            rank_bytes = [
                self.spec.rank_wire_block_nbytes(r, self.codec)
                * int(n) for r, n in enumerate(rank_counts)]
            wire_bytes = sum(rank_bytes)
        else:
            rank_bytes = None
            wire_bytes = (self.spec.wire_block_nbytes(self.codec)
                          * int(meta["n_blocks"]))
        # The ack IS the hand-off's success acknowledgment: attach the
        # decode-side lease, then release the prefill pages with the
        # prefix-cache insert riding inside (owner refs still held, so
        # the insert can never fork a freed block — kv_release_slot's
        # own discipline, reused).
        req.kv_lease = new_lease
        lease.release(
            cache_hook=src.prefix_cache_hook(detach["confirmed"]))
        if self.registry is not None:
            self.registry.counter_inc(
                "serving_kv_transfer_bytes_total",
                {"codec": self.codec}, by=float(wire_bytes),
                help="KV page payload bytes shipped prefill->decode, "
                     "by wire codec")
            if rank_bytes is not None:
                for r, nbytes in enumerate(rank_bytes):
                    self.registry.counter_inc(
                        "serving_shard_kv_transfer_bytes_total",
                        {"rank": str(r)}, by=float(nbytes),
                        help="per-rank KV page bytes shipped over the "
                             "sharded point-to-point sub-streams")
            self.registry.observe(
                "serving_kv_transfer_seconds", t1 - t0,
                help="one request's KV transfer wall "
                     "(export -> import ack)",
                buckets=_TRANSFER_BUCKETS)
        self._count("serving_kv_transfers_total", {"outcome": "ok"},
                    help="KV page transfers by disposition")
        self.tracer.record_span(
            "disagg.transfer", t0, t1, request_id=req.request_id,
            parent_id=req.trace_parent,
            attrs={"to": f"decode{target}", "codec": self.codec,
                   "blocks": int(meta["n_blocks"]),
                   "bytes": wire_bytes,
                   "tokens": int(meta["tokens"])})
        if req.done:
            # Settled between ack and requeue (deadline via the
            # handler): finish released the DECODE lease we just
            # attached — nothing further owns pages. (If finish beat
            # the attach, it released the prefill lease and this
            # release of new_lease is the cleanup.)
            new_lease.release()
            return
        self.decode_queue.requeue(req)
        self.tracer.decision("transfer", request_id=req.request_id,
                             to=f"decode{target}")

    def _transfer_failed(self, req: GenerateRequest, lease,
                         target: int, err: Exception,
                         t0: float) -> None:
        """Migration-failure disposition — the supervisor's _requeue
        contract verbatim, applied to the transfer leg: settle at most
        once, keep decoded tokens when the deadline lapsed, burn one
        attempt otherwise and resume on the PREFILL side (the lease
        reattaches: pages survive, the retry re-attaches and re-hands
        off — provably the same stream)."""
        lease.reattach()
        now = time.monotonic()
        self.tracer.record_span(
            "disagg.transfer", t0, now, request_id=req.request_id,
            parent_id=req.trace_parent,
            attrs={"to": f"decode{target}", "codec": self.codec,
                   "error": str(err)[:200]})
        log.warning("kv transfer to decode%d failed (request %s, "
                    "attempt %d): %s", target, req.request_id,
                    req.attempts, err)
        if req.done:
            outcome = "already_done"
        elif req.deadline <= now:
            if req.tokens:
                req.truncated = True
                req.finish()
                outcome = "deadline_truncated"
            else:
                req.fail(DEADLINE_QUEUED_ERROR)
                outcome = "deadline_lapsed"
        else:
            req.attempts += 1
            if req.attempts >= self.max_attempts:
                req.fail(RETRIES_EXHAUSTED_ERROR)
                outcome = "retries_exhausted"
            else:
                # Front of the PREFILL queue: the surviving lease
                # re-attaches there, one token re-decodes, the
                # hand-off retries — possibly to another target.
                self.queue.requeue(req)
                outcome = "requeued_prefill"
        self._count("serving_kv_transfers_total", {"outcome": outcome},
                    help="KV page transfers by disposition")
        self.tracer.decision("transfer_failed",
                             request_id=req.request_id,
                             outcome=outcome)
        rec = self.flight_recorder
        if rec is not None:
            try:
                rec.snapshot("kv_transfer_failed",
                             extra={"request_id": req.request_id,
                                    "target": f"decode{target}",
                                    "outcome": outcome,
                                    "states": self.states()})
            except Exception:
                log.exception("flight recorder snapshot "
                              "(kv_transfer_failed) failed")
