"""KVSpec — the paged-KV pool layout, declared exactly once.

The disaggregated plane moves KV pages between pools that were built
by different executors in different processes. Everything both ends
must agree on to do that safely — block geometry, head layout, the
resident dtype (int8 codes + per-block scales vs fp32 rows), the
model identity that makes the bytes meaningful at all — lives in ONE
frozen ``KVSpec``, and every derived quantity (wire bytes per block,
the segment slicing of a transfer, the payload split a receiver
parses) is computed FROM it. This is the SpecLayout/pjit pattern from
the exemplars: declare the partitioning once, derive all slice math
from the declaration, so the sender's segmentation and the receiver's
parse can never drift apart — they are the same function.

The hello handshake (stream.py) exchanges ``fingerprint()`` dicts
plus the wire codec id before any payload moves, the PR 9 discipline:
a codec disagreement raises the SAME typed ``CodecMismatch`` the
quantized ring uses, a layout disagreement raises ``KVSpecMismatch``
naming the differing fields — never int8 bytes decoded as floats,
never pages appended into the wrong geometry.

Wire codecs (parallel/quantize.py's block-axis twins):

  * ``int8`` — codes + per-block scales. For an int8-resident pool
    this is a VERBATIM passthrough (the pool layout IS the wire
    layout; byte-identical on both ends by construction). For an fp32
    pool it quantizes per block on the way out (KV tolerates int8 far
    better than gradients — the PR 9 lesson applied to residency).
  * ``fp32`` — raw rows, lossless; the exact-reference wire for fp32
    pools. An int8 pool REQUIRES the int8 wire: dequantizing resident
    codes to ship fp32 would quadruple the bytes and re-rounding on
    arrival would break the byte-identical stream contract.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, replace
from typing import Dict, List, Tuple

from ...parallel.fabric_collectives import CodecMismatch

# Per-rank geometry rides the SAME even-contiguous split the fabric
# ring and the row-plane shard ownership use (shard_math.segment_bounds
# is this exact symbol, re-exported) — one partition function, so a
# rank's pool slice, its transfer segmentation and the collective's
# wire segments can never disagree about where a rank's bytes start.
from ...parallel.fabric_collectives import (
    _segment_bounds as segment_bounds)

__all__ = ["KVSpec", "KVSpecMismatch", "CodecMismatch", "WIRE_CODECS",
           "SHARD_AXES"]

#: KV shard axes: "none" (single worker), "head" (Ulysses — every rank
#: holds ALL blocks, a contiguous head slice of each; decode's k+1
#: verify windows attend all-local), "page" (ring — every rank holds
#: ALL heads of a contiguous block-id range; long prefill chunks fold
#: cross-rank partials with the flash online-softmax recurrence).
SHARD_AXES = ("none", "head", "page")

#: Wire codecs the page stream understands (fp32 = raw rows, int8 =
#: parallel/quantize.py block-axis codes + per-block scales).
WIRE_CODECS = ("fp32", "int8")


class KVSpecMismatch(RuntimeError):
    """The two ends of a page stream disagree on the pool layout or
    model identity. Raised at hello time, before any page moves —
    the layout sibling of the codec's ``CodecMismatch``."""


@dataclass(frozen=True)
class KVSpec:
    """One paged-KV pool layout + the model identity its pages encode.

    ``num_blocks`` is deliberately NOT part of the spec: pool capacity
    is a per-replica sizing decision (a decode replica may hold far
    more resident context than a prefill replica) and block ids are
    remapped at import anyway. Everything that determines what a
    block's BYTES mean is here."""

    model: str            # executor family ("paged", "synthetic-kv")
    block_size: int       # tokens per block
    heads: int
    d_head: int
    vocab: int
    max_blocks_per_req: int
    pool_dtype: str       # "int8" (codes+scales) | "fp32"
    planes: int = 2       # K and V (synthetic ships 1 content plane)
    seed: int = 0         # weight identity: pages from a different
    #                       model are bytes, not KV
    #: Context-parallel KV (ISSUE 16): how the pools split across the
    #: shard workers of one replica. "head" gives every rank ALL block
    #: ids and a contiguous head slice of each block (Ulysses); "page"
    #: gives every rank ALL heads of a contiguous block-id range
    #: (ring). Per-rank pool shapes, slice bounds and per-rank wire
    #: framing all derive from these two fields — never recomputed
    #: inline at a use site (the GL018 contract).
    shard_axis: str = "none"
    world: int = 1

    def __post_init__(self):
        if self.pool_dtype not in ("int8", "fp32"):
            raise ValueError(f"pool_dtype must be int8|fp32, got "
                             f"{self.pool_dtype!r}")
        if self.block_size < 1 or self.heads < 1 or self.d_head < 1 \
                or self.planes < 1:
            raise ValueError("block_size/heads/d_head/planes must be "
                             ">= 1")
        if self.shard_axis not in SHARD_AXES:
            raise ValueError(f"shard_axis must be one of {SHARD_AXES},"
                             f" got {self.shard_axis!r}")
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        if self.shard_axis == "none" and self.world != 1:
            raise ValueError(
                f"shard_axis='none' is the single-worker layout; "
                f"world={self.world} needs a shard axis")
        if self.shard_axis == "head" and self.heads % self.world:
            raise ValueError(
                f"head-sharded pools need heads % world == 0 (the "
                f"Ulysses all-to-all constraint): heads={self.heads}, "
                f"world={self.world}")

    # -- derived geometry (every slice below comes from here) ----------------

    @property
    def block_shape(self) -> Tuple[int, int, int]:
        return (self.block_size, self.heads, self.d_head)

    @property
    def elems_per_block(self) -> int:
        return self.block_size * self.heads * self.d_head

    @property
    def max_context(self) -> int:
        return self.max_blocks_per_req * self.block_size

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def default_codec(self) -> str:
        """The natural wire for this pool: its own resident layout."""
        return "int8" if self.pool_dtype == "int8" else "fp32"

    def validate_codec(self, codec: str) -> str:
        if codec not in WIRE_CODECS:
            raise ValueError(f"wire codec must be one of {WIRE_CODECS},"
                             f" got {codec!r}")
        if self.pool_dtype == "int8" and codec != "int8":
            raise ValueError(
                "int8-resident pools require the int8 wire: the codes "
                "+ scales ARE the transfer format (fp32 would 4x the "
                "bytes and re-round on arrival)")
        return codec

    def plane_part_nbytes(self, codec: str,
                          n_blocks: int) -> Tuple[int, int]:
        """(payload_bytes, scale_bytes) for ONE plane of ``n_blocks``
        blocks under ``codec`` — the receiver's parse and the sender's
        frame are both this function."""
        if codec == "int8":
            return n_blocks * self.elems_per_block, n_blocks * 4
        return n_blocks * self.elems_per_block * 4, 0

    def wire_block_nbytes(self, codec: str) -> int:
        """Total wire bytes one block costs across all planes."""
        pay, sc = self.plane_part_nbytes(codec, 1)
        return self.planes * (pay + sc)

    def segments(self, n_blocks: int, codec: str,
                 max_seg_bytes: int = 1 << 18
                 ) -> List[Tuple[int, int]]:
        """Transfer segmentation: ``[(start_block, count), ...]``
        covering ``n_blocks`` with each segment's wire payload at most
        ``max_seg_bytes`` (always >= 1 block/segment). Derived from
        the spec so a layout change re-derives both ends at once."""
        if n_blocks <= 0:
            return []
        per = max(1, max_seg_bytes // self.wire_block_nbytes(codec))
        return [(s, min(per, n_blocks - s))
                for s in range(0, n_blocks, per)]

    # -- per-rank geometry (context-parallel KV, ISSUE 16) --------------------
    #
    # Everything a rank knows about its own slice of the pools comes
    # from the four methods below plus ``rank_view`` — pool shapes,
    # page counts, slice bounds, per-rank wire framing. Computing any
    # of these inline at a use site is the layout-drift class GL018
    # flags: this dataclass is the single blessed derivation site.

    @property
    def sharded(self) -> bool:
        return self.world > 1

    def _check_rank(self, rank: int) -> int:
        rank = int(rank)
        if not 0 <= rank < self.world:
            raise ValueError(f"rank {rank} outside world {self.world}")
        return rank

    def rank_heads(self, rank: int) -> Tuple[int, int]:
        """[lo, hi) of the contiguous head slice rank holds — the
        full head range unless the axis is "head"."""
        rank = self._check_rank(rank)
        if self.shard_axis == "head":
            return segment_bounds(self.heads, self.world)[rank]
        return (0, self.heads)

    def rank_head_count(self, rank: int) -> int:
        lo, hi = self.rank_heads(rank)
        return hi - lo

    def rank_blocks(self, rank: int, num_blocks: int
                    ) -> Tuple[int, int]:
        """[lo, hi) of the GLOBAL block-id range rank's pool holds.
        ``num_blocks`` is the replica's pool capacity (a sizing
        decision, deliberately outside the spec — see the class
        docstring); the partition of it is pure spec."""
        rank = self._check_rank(rank)
        if self.shard_axis == "page":
            if num_blocks < self.world:
                raise ValueError(
                    f"page-sharded pool needs num_blocks >= world: "
                    f"{num_blocks} < {self.world}")
            return segment_bounds(int(num_blocks), self.world)[rank]
        return (0, int(num_blocks))

    def rank_block_shape(self, rank: int) -> Tuple[int, int, int]:
        """One resident block's shape in rank's pool:
        ``(block_size, rank_heads, d_head)``."""
        return (self.block_size, self.rank_head_count(rank),
                self.d_head)

    def rank_view(self, rank: int) -> "KVSpec":
        """Rank's slice of the layout AS a single-worker KVSpec — the
        per-rank wire format. A sharded transfer is ``world``
        point-to-point streams, each framed/segmented/parsed by its
        rank_view exactly like an unsharded stream; deriving the view
        here (instead of re-declaring it rank-side) is what keeps the
        per-rank sender and receiver the same function."""
        rank = self._check_rank(rank)
        return replace(self, heads=self.rank_head_count(rank),
                       shard_axis="none", world=1)

    def rank_plane_part_nbytes(self, rank: int, codec: str,
                               n_blocks: int) -> Tuple[int, int]:
        """(payload_bytes, scale_bytes) for ONE plane of ``n_blocks``
        of rank's blocks — ``plane_part_nbytes`` through rank_view."""
        return self.rank_view(rank).plane_part_nbytes(codec, n_blocks)

    def rank_wire_block_nbytes(self, rank: int, codec: str) -> int:
        return self.rank_view(rank).wire_block_nbytes(codec)

    def rank_resident_nbytes(self, rank: int, num_blocks: int) -> int:
        """Resident pool bytes rank pins for a ``num_blocks`` replica
        pool (all planes, codes + scales for int8) — what the bench's
        resident-context-per-replica arithmetic divides by."""
        lo, hi = self.rank_blocks(rank, num_blocks)
        elem = 1 if self.pool_dtype == "int8" else 4
        per_block = (self.block_size * self.rank_head_count(rank)
                     * self.d_head * elem
                     + (4 if self.pool_dtype == "int8" else 0))
        return self.planes * (hi - lo) * per_block

    # -- the hello contract ---------------------------------------------------

    def fingerprint(self) -> Dict:
        return asdict(self)

    def check_hello(self, remote: Dict, local_codec: str,
                    remote_codec: str) -> None:
        """Validate a peer's hello against this spec + codec. Codec
        disagreement is the PR 9 ``CodecMismatch``; layout/model
        disagreement is ``KVSpecMismatch`` naming every differing
        field — both raised BEFORE any payload byte is parsed."""
        if remote_codec != local_codec:
            raise CodecMismatch(
                f"kv page stream codec mismatch: local {local_codec!r}"
                f" vs peer {remote_codec!r}")
        mine = self.fingerprint()
        diffs = [f"{k}: {mine[k]!r} != {remote.get(k)!r}"
                 for k in mine if remote.get(k) != mine[k]]
        if diffs:
            raise KVSpecMismatch(
                "kv pool layout mismatch: " + "; ".join(sorted(diffs)))
