"""KVSpec — the paged-KV pool layout, declared exactly once.

The disaggregated plane moves KV pages between pools that were built
by different executors in different processes. Everything both ends
must agree on to do that safely — block geometry, head layout, the
resident dtype (int8 codes + per-block scales vs fp32 rows), the
model identity that makes the bytes meaningful at all — lives in ONE
frozen ``KVSpec``, and every derived quantity (wire bytes per block,
the segment slicing of a transfer, the payload split a receiver
parses) is computed FROM it. This is the SpecLayout/pjit pattern from
the exemplars: declare the partitioning once, derive all slice math
from the declaration, so the sender's segmentation and the receiver's
parse can never drift apart — they are the same function.

The hello handshake (stream.py) exchanges ``fingerprint()`` dicts
plus the wire codec id before any payload moves, the PR 9 discipline:
a codec disagreement raises the SAME typed ``CodecMismatch`` the
quantized ring uses, a layout disagreement raises ``KVSpecMismatch``
naming the differing fields — never int8 bytes decoded as floats,
never pages appended into the wrong geometry.

Wire codecs (parallel/quantize.py's block-axis twins):

  * ``int8`` — codes + per-block scales. For an int8-resident pool
    this is a VERBATIM passthrough (the pool layout IS the wire
    layout; byte-identical on both ends by construction). For an fp32
    pool it quantizes per block on the way out (KV tolerates int8 far
    better than gradients — the PR 9 lesson applied to residency).
  * ``fp32`` — raw rows, lossless; the exact-reference wire for fp32
    pools. An int8 pool REQUIRES the int8 wire: dequantizing resident
    codes to ship fp32 would quadruple the bytes and re-rounding on
    arrival would break the byte-identical stream contract.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Dict, List, Tuple

from ...parallel.fabric_collectives import CodecMismatch

__all__ = ["KVSpec", "KVSpecMismatch", "CodecMismatch", "WIRE_CODECS"]

#: Wire codecs the page stream understands (fp32 = raw rows, int8 =
#: parallel/quantize.py block-axis codes + per-block scales).
WIRE_CODECS = ("fp32", "int8")


class KVSpecMismatch(RuntimeError):
    """The two ends of a page stream disagree on the pool layout or
    model identity. Raised at hello time, before any page moves —
    the layout sibling of the codec's ``CodecMismatch``."""


@dataclass(frozen=True)
class KVSpec:
    """One paged-KV pool layout + the model identity its pages encode.

    ``num_blocks`` is deliberately NOT part of the spec: pool capacity
    is a per-replica sizing decision (a decode replica may hold far
    more resident context than a prefill replica) and block ids are
    remapped at import anyway. Everything that determines what a
    block's BYTES mean is here."""

    model: str            # executor family ("paged", "synthetic-kv")
    block_size: int       # tokens per block
    heads: int
    d_head: int
    vocab: int
    max_blocks_per_req: int
    pool_dtype: str       # "int8" (codes+scales) | "fp32"
    planes: int = 2       # K and V (synthetic ships 1 content plane)
    seed: int = 0         # weight identity: pages from a different
    #                       model are bytes, not KV

    def __post_init__(self):
        if self.pool_dtype not in ("int8", "fp32"):
            raise ValueError(f"pool_dtype must be int8|fp32, got "
                             f"{self.pool_dtype!r}")
        if self.block_size < 1 or self.heads < 1 or self.d_head < 1 \
                or self.planes < 1:
            raise ValueError("block_size/heads/d_head/planes must be "
                             ">= 1")

    # -- derived geometry (every slice below comes from here) ----------------

    @property
    def block_shape(self) -> Tuple[int, int, int]:
        return (self.block_size, self.heads, self.d_head)

    @property
    def elems_per_block(self) -> int:
        return self.block_size * self.heads * self.d_head

    @property
    def max_context(self) -> int:
        return self.max_blocks_per_req * self.block_size

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def default_codec(self) -> str:
        """The natural wire for this pool: its own resident layout."""
        return "int8" if self.pool_dtype == "int8" else "fp32"

    def validate_codec(self, codec: str) -> str:
        if codec not in WIRE_CODECS:
            raise ValueError(f"wire codec must be one of {WIRE_CODECS},"
                             f" got {codec!r}")
        if self.pool_dtype == "int8" and codec != "int8":
            raise ValueError(
                "int8-resident pools require the int8 wire: the codes "
                "+ scales ARE the transfer format (fp32 would 4x the "
                "bytes and re-round on arrival)")
        return codec

    def plane_part_nbytes(self, codec: str,
                          n_blocks: int) -> Tuple[int, int]:
        """(payload_bytes, scale_bytes) for ONE plane of ``n_blocks``
        blocks under ``codec`` — the receiver's parse and the sender's
        frame are both this function."""
        if codec == "int8":
            return n_blocks * self.elems_per_block, n_blocks * 4
        return n_blocks * self.elems_per_block * 4, 0

    def wire_block_nbytes(self, codec: str) -> int:
        """Total wire bytes one block costs across all planes."""
        pay, sc = self.plane_part_nbytes(codec, 1)
        return self.planes * (pay + sc)

    def segments(self, n_blocks: int, codec: str,
                 max_seg_bytes: int = 1 << 18
                 ) -> List[Tuple[int, int]]:
        """Transfer segmentation: ``[(start_block, count), ...]``
        covering ``n_blocks`` with each segment's wire payload at most
        ``max_seg_bytes`` (always >= 1 block/segment). Derived from
        the spec so a layout change re-derives both ends at once."""
        if n_blocks <= 0:
            return []
        per = max(1, max_seg_bytes // self.wire_block_nbytes(codec))
        return [(s, min(per, n_blocks - s))
                for s in range(0, n_blocks, per)]

    # -- the hello contract ---------------------------------------------------

    def fingerprint(self) -> Dict:
        return asdict(self)

    def check_hello(self, remote: Dict, local_codec: str,
                    remote_codec: str) -> None:
        """Validate a peer's hello against this spec + codec. Codec
        disagreement is the PR 9 ``CodecMismatch``; layout/model
        disagreement is ``KVSpecMismatch`` naming every differing
        field — both raised BEFORE any payload byte is parsed."""
        if remote_codec != local_codec:
            raise CodecMismatch(
                f"kv page stream codec mismatch: local {local_codec!r}"
                f" vs peer {remote_codec!r}")
        mine = self.fingerprint()
        diffs = [f"{k}: {mine[k]!r} != {remote.get(k)!r}"
                 for k in mine if remote.get(k) != mine[k]]
        if diffs:
            raise KVSpecMismatch(
                "kv pool layout mismatch: " + "; ".join(sorted(diffs)))
