"""KVPageStream — paged KV blocks point-to-point over the fabric.

One prefill replica's finished pages ship to one decode replica's
pool over the SAME framed transport the sharded plane speaks
(serving/sharded/protocol.py: ``!II`` header + JSON + raw payload
parts, whole-frame receive deadlines — the GL010 discipline), with
the PR 9 wire rules on top:

  * **hello before payload** — the first frame each way carries the
    ``KVSpec.fingerprint()`` and the wire codec id; a codec
    disagreement raises the quantized ring's typed ``CodecMismatch``
    and a layout disagreement ``KVSpecMismatch``, both before a
    single page byte is parsed (never int8 codes decoded as floats,
    never rows scattered into the wrong block geometry);
  * **self-describing segments** — a transfer is ``pages`` metadata
    followed by N ``seg`` frames whose slicing is DERIVED from the
    spec (``KVSpec.segments``): sender slice and receiver parse are
    the same function, so they cannot drift;
  * **int8 by default where it is free** — an int8-resident pool's
    codes + per-block scales ship VERBATIM (4x fewer bytes than fp32
    rows, byte-identical on both ends by construction); an fp32 pool
    can opt into the int8 wire via the ``parallel/quantize.py``
    block-axis codec twins (KV tolerates int8 far better than
    gradients), or stay lossless on the fp32 wire.

Failure surface: every receive carries a deadline, sockets are armed
with timeouts at connect, and ``faults.fire("kvstream.send")`` sits
between segments — the chaos matrix cuts a transfer MID-STREAM there
and the importer must discard the partial accumulation with zero
leaked blocks (the transfer plane in pool.py owns the retry/requeue
disposition).
"""

from __future__ import annotations

import logging
import select
import socket
import threading
import uuid
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ... import faults
from ...parallel.quantize import (int8_block_decode_xp,
                                  int8_block_encode_xp)
from ..sharded.protocol import ProtocolError, recv_msg, send_msg
from .spec import KVSpec

log = logging.getLogger(__name__)

__all__ = ["KVPageStream", "KVPageStreamServer", "KVStreamError",
           "KVStreamNack"]


class KVStreamError(RuntimeError):
    """Transport-level page-stream failure (peer gone, torn frame,
    deadline): the transfer is poisoned, the pool layer decides
    between retry and requeue-to-prefill."""


class KVStreamNack(KVStreamError):
    """The importer refused the pages (decode-side OOM, a failed
    integrity check). ``oom`` distinguishes capacity pressure (pages
    free as decode work finishes — retry is sane) from poison."""

    def __init__(self, error: str, oom: bool = False):
        super().__init__(error)
        self.oom = oom


def _wire_planes(spec: KVSpec, codec: str,
                 planes: List[Tuple[np.ndarray, np.ndarray]]
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Pool layout -> wire layout per plane. int8 pools pass through
    (codes + scales ARE the wire); fp32 pools either ship raw rows
    (fp32 wire) or quantize per block (int8 wire)."""
    out = []
    for payload, scales in planes:
        if codec == "int8":
            if spec.pool_dtype == "int8":
                out.append((np.ascontiguousarray(payload, np.int8),
                            np.ascontiguousarray(scales, np.float32)))
            else:
                q, sc = int8_block_encode_xp(
                    np.asarray(payload, np.float32))
                out.append((q, sc))
        else:
            out.append((np.ascontiguousarray(payload, np.float32),
                        np.zeros((0,), np.float32)))
    return out


def _split_segment(spec: KVSpec, codec: str, count: int, blob: bytes
                   ) -> List[Tuple[bytes, bytes]]:
    """One segment's payload blob -> per-plane (payload, scales) byte
    slices — the exact inverse of the sender's part order, both
    derived from plane_part_nbytes so they cannot drift."""
    pay_n, sc_n = spec.plane_part_nbytes(codec, count)
    need = spec.planes * (pay_n + sc_n)
    if len(blob) != need:
        raise ProtocolError(
            f"segment payload is {len(blob)} bytes, spec derives "
            f"{need} for {count} block(s) under {codec!r}")
    out = []
    off = 0
    for _ in range(spec.planes):
        out.append((blob[off:off + pay_n],
                    blob[off + pay_n:off + pay_n + sc_n]))
        off += pay_n + sc_n
    return out


def _pool_planes(spec: KVSpec, codec: str, n_blocks: int,
                 plane_bytes: List[Tuple[bytes, bytes]]
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Reassembled per-plane wire bytes -> pool-layout arrays: int8
    pools get (codes, scales) verbatim; fp32 pools get fp32 rows (+
    all-ones scales), decoded through the quantize.py block twin when
    the wire was int8."""
    shape = (n_blocks,) + spec.block_shape
    out = []
    for raw, sc_raw in plane_bytes:
        if codec == "int8":
            codes = np.frombuffer(raw, np.int8).reshape(shape)
            scales = np.frombuffer(sc_raw, np.float32).copy()
            if spec.pool_dtype == "int8":
                out.append((codes.copy(), scales))
            else:
                out.append((int8_block_decode_xp(codes, scales),
                            np.ones((n_blocks,), np.float32)))
        else:
            out.append((np.frombuffer(raw, np.float32).reshape(
                shape).copy(), np.ones((n_blocks,), np.float32)))
    return out


class KVPageStream:
    """Client half: one prefill-side connection to one decode-side
    ``KVPageStreamServer``. ``connect()`` runs the hello/spec check;
    ``send_pages()`` ships one request's pages as spec-derived
    segments and blocks for the import ack. Not thread-safe — the
    transfer plane owns one stream per (worker, target) pair."""

    def __init__(self, spec: KVSpec, addr: Tuple[str, int],
                 codec: Optional[str] = None, timeout_s: float = 5.0,
                 seg_bytes: int = 1 << 18):
        self.spec = spec
        self.addr = addr
        self.codec = spec.validate_codec(
            codec if codec is not None else spec.default_codec())
        self.timeout_s = float(timeout_s)
        self.seg_bytes = int(seg_bytes)
        self._sock: Optional[socket.socket] = None

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def connect(self) -> None:
        if self._sock is not None:
            return
        sock = socket.create_connection(self.addr,
                                        timeout=self.timeout_s)
        try:
            # Small control frames interleave with bulk segments on
            # one long-lived socket: never sit out a Nagle exchange.
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            faults.fire("kvstream.connect")
            send_msg(sock, {"kind": "hello",
                            "spec": self.spec.fingerprint(),
                            "codec": self.codec})
            ack, _ = recv_msg(sock, timeout=self.timeout_s)
            if not ack.get("ok"):
                raise KVStreamNack(ack.get("error", "hello refused"))
            # Symmetric check: the server validated us; we validate
            # the server (a one-sided hello would let a stale peer
            # stream into a re-specced pool).
            self.spec.check_hello(ack["spec"], self.codec,
                                  ack.get("codec"))
        except BaseException:
            sock.close()
            raise
        self._sock = sock

    def send_pages(self, meta: Dict,
                   planes: List[Tuple[np.ndarray, np.ndarray]]
                   ) -> Dict:
        """Ship one transfer (``meta`` + pool-layout plane arrays) and
        return the importer's ack. Any failure closes the stream (the
        positional protocol is desynced past repair) and raises
        KVStreamError/KVStreamNack — the caller owns disposition."""
        if self.spec.sharded:
            # Context-parallel pools (ISSUE 16): ``planes`` is the
            # rank-major plane-set list kv_export produced, and the
            # transfer is ``world`` per-rank sub-streams multiplexed
            # on this socket — each framed/segmented by that rank's
            # ``rank_view`` (a single-worker KVSpec), so the per-rank
            # sender and the receiver's parse stay the same function.
            if len(planes) != self.spec.world:
                raise ValueError(
                    f"sharded spec (world {self.spec.world}) wants "
                    f"rank-major plane sets, caller passed "
                    f"{len(planes)}")
        elif len(planes) != self.spec.planes:
            raise ValueError(
                f"spec declares {self.spec.planes} plane(s), caller "
                f"passed {len(planes)}")
        self.connect()
        sock = self._sock
        n_blocks = int(meta["n_blocks"])
        xfer = meta.get("xfer") or uuid.uuid4().hex[:12]
        if self.spec.sharded:
            counts = [int(c) for c in meta["rank_blocks"]]
            rank_segs = [
                self.spec.rank_view(r).segments(counts[r], self.codec,
                                                self.seg_bytes)
                for r in range(self.spec.world)]
            n_segs = sum(len(s) for s in rank_segs)
        else:
            counts, rank_segs = [n_blocks], [self.spec.segments(
                n_blocks, self.codec, self.seg_bytes)]
            n_segs = len(rank_segs[0])
        try:
            send_msg(sock, dict(meta, kind="pages", xfer=xfer,
                                codec=self.codec, segments=n_segs))
            si = 0
            for r, segs in enumerate(rank_segs):
                rv = (self.spec.rank_view(r) if self.spec.sharded
                      else self.spec)
                wire = _wire_planes(rv, self.codec,
                                    planes[r] if self.spec.sharded
                                    else planes)
                for start, count in segs:
                    # The chaos seam: a mid-transfer kill lands
                    # BETWEEN segments, after real bytes moved.
                    faults.fire("kvstream.send",
                                attrs={"xfer": xfer, "seg": si,
                                       "rank": r})
                    parts = []
                    for payload, scales in wire:
                        parts.append(payload[start:start + count])
                        if self.codec == "int8":
                            parts.append(np.ascontiguousarray(
                                scales[start:start + count],
                                np.float32))
                    send_msg(sock, {"kind": "seg", "xfer": xfer,
                                    "seq": si, "rank": r,
                                    "start": start, "count": count,
                                    "last": si == n_segs - 1}, *parts)
                    si += 1
            ack, _ = recv_msg(sock, timeout=self.timeout_s)
        except (OSError, ProtocolError) as e:
            self.close()
            raise KVStreamError(
                f"page stream to {self.addr} failed mid-transfer "
                f"(xfer {xfer}): {e}") from e
        except BaseException:
            # Any other failure mid-segment (an injected fault, a
            # codec bug) leaves the positional stream desynced past
            # repair: the socket must not be reused.
            self.close()
            raise
        if not ack.get("ok"):
            raise KVStreamNack(ack.get("error", "import refused"),
                               oom=bool(ack.get("oom")))
        return ack

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class KVPageStreamServer:
    """Decode-side half: accepts page streams, validates the hello,
    reassembles spec-derived segments and hands complete transfers to
    ``import_fn(meta, planes) -> ack_extras`` (the executor's
    ``kv_import`` wrapper in pool.py). An import raising nacks the
    transfer — ``oom=True`` for KVCacheOOM-shaped errors — and the
    connection survives; a torn stream drops the partial accumulation
    on the floor (no blocks were allocated until import runs)."""

    def __init__(self, spec: KVSpec, import_fn: Callable,
                 host: str = "127.0.0.1", port: int = 0,
                 codec: Optional[str] = None, timeout_s: float = 5.0):
        self.spec = spec
        self.import_fn = import_fn
        self.codec = spec.validate_codec(
            codec if codec is not None else spec.default_codec())
        self.timeout_s = float(timeout_s)
        self._stop = threading.Event()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                               1)
        self._lsock.bind((host, port))
        self._lsock.listen(8)
        # The accept loop selects first, but the socket is armed too
        # (the GL010 connect-time discipline): no receive leg in this
        # module can ever block unbounded, select bug or not.
        self._lsock.settimeout(1.0)
        self.addr = self._lsock.getsockname()
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"kvstream-accept-{self.addr[1]}")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                r, _, _ = select.select([self._lsock], [], [], 0.1)
            except (OSError, ValueError):
                return  # close() tore the listener down mid-select
            if not r:
                continue
            try:
                conn, peer = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, peer), daemon=True,
                                 name=f"kvstream-conn-{peer[1]}")
            t.start()
            # Prune the dead before tracking the new: every failed
            # transfer reconnects, and a long-lived server must not
            # hoard one Thread object per retry forever.
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _recv(self, conn: socket.socket):
        """Idle-tolerant receive: re-arm on quiet (a healthy prefill
        peer submits nothing between transfers), whole-frame deadline
        once bytes flow (the shard_worker select-then-recv shape)."""
        while not self._stop.is_set():
            r, _, _ = select.select([conn], [], [], 0.1)
            if r:
                return recv_msg(conn, timeout=self.timeout_s)
        raise ProtocolError("server stopping")

    def _serve_conn(self, conn: socket.socket, peer) -> None:
        try:
            with conn:
                try:
                    hello, _ = recv_msg(conn, timeout=self.timeout_s)
                    self.spec.check_hello(hello.get("spec", {}),
                                          self.codec,
                                          hello.get("codec"))
                except Exception as e:
                    # Typed refusal BEFORE any payload: the client
                    # raises its own CodecMismatch/KVSpecMismatch off
                    # this ack.
                    send_msg(conn, {"ok": False, "error": str(e)})
                    return
                send_msg(conn, {"ok": True,
                                "spec": self.spec.fingerprint(),
                                "codec": self.codec})
                while not self._stop.is_set():
                    try:
                        msg, _ = self._recv(conn)
                    except (OSError, ProtocolError):
                        return  # peer gone / torn stream: partial
                        # accumulations die with the connection
                    if msg.get("kind") != "pages":
                        send_msg(conn, {"ok": False,
                                        "error": f"unexpected frame "
                                                 f"{msg.get('kind')!r}"})
                        return
                    self._one_transfer(conn, msg)
        except (OSError, ProtocolError) as e:
            # A peer dying mid-transfer is an EXPECTED failure mode
            # (the chaos matrix's bread and butter): the partial
            # accumulation dies with the connection, no blocks were
            # allocated, the sender owns the retry.
            log.warning("kv page stream: connection from %s torn "
                        "mid-transfer: %s", peer, e)
        except Exception:
            log.exception("kv page stream: connection from %s died",
                          peer)

    def _one_transfer(self, conn: socket.socket, meta: Dict) -> None:
        n_blocks = int(meta["n_blocks"])
        n_segs = int(meta["segments"])
        codec = meta.get("codec", self.codec)
        if codec != self.codec:
            # The codec was NEGOTIATED at hello; a frame stamped with
            # another one is a skewed/poisoned peer, and parsing its
            # payload under either codec would scatter misinterpreted
            # bytes into the pool — the exact failure the hello check
            # exists to make impossible.
            raise ProtocolError(
                f"pages frame stamped codec {codec!r} on a "
                f"{self.codec!r}-negotiated stream")
        # Sharded pools: ``world`` per-rank sub-streams multiplexed on
        # this socket, each parsed by its rank_view (the same derived
        # geometry the sender framed with); the flat path is the
        # world-1 degenerate case of the same loop.
        if self.spec.sharded:
            counts = [int(c) for c in meta["rank_blocks"]]
            views = [self.spec.rank_view(r)
                     for r in range(self.spec.world)]
        else:
            counts, views = [n_blocks], [self.spec]
        acc: List[List[List[bytes]]] = [
            [[] for _ in range(2 * self.spec.planes)] for _ in views]
        covered = [0] * len(views)
        for si in range(n_segs):
            msg, payload = recv_msg(conn, timeout=self.timeout_s)
            r = int(msg.get("rank", 0))
            if (msg.get("kind") != "seg"
                    or msg.get("xfer") != meta.get("xfer")
                    or int(msg.get("seq", -1)) != si
                    or not 0 <= r < len(views)
                    or int(msg.get("start", -1)) != covered[r]):
                raise ProtocolError(
                    f"segment stream desync at seq {si}: {msg}")
            count = int(msg["count"])
            for p, (raw, sc) in enumerate(_split_segment(
                    views[r], codec, count, payload)):
                acc[r][2 * p].append(raw)
                acc[r][2 * p + 1].append(sc)
            covered[r] += count
        if covered != counts:
            raise ProtocolError(
                f"segments cover {covered} block(s), header declared "
                f"{counts}")
        rank_planes = [
            _pool_planes(
                views[r], codec, counts[r],
                [(b"".join(acc[r][2 * p]),
                  b"".join(acc[r][2 * p + 1]))
                 for p in range(self.spec.planes)])
            for r in range(len(views))]
        planes = (rank_planes if self.spec.sharded
                  else rank_planes[0])
        try:
            faults.fire("kvstream.import",
                        attrs={"xfer": meta.get("xfer")})
            extras = self.import_fn(meta, planes) or {}
        except Exception as e:
            oom = "exhausted" in str(e) or "KVCacheOOM" in type(e).__name__
            log.warning("kv page stream: import refused (request %s): "
                        "%s", meta.get("req"), e)
            send_msg(conn, {"ok": False, "error": str(e), "oom": oom})
            return
        send_msg(conn, {"ok": True, "xfer": meta.get("xfer"),
                        **extras})

    def close(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        for t in self._threads:
            t.join(timeout=2.0)
