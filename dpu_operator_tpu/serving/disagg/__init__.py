"""Disaggregated prefill/decode serving (ROADMAP item 1).

Dedicated prefill replicas build paged KV and stream the pages over
the fabric to decode replicas that only run the per-token step:

  * spec.py   — ``KVSpec``: the pool layout declared ONCE; wire
    bytes, segmentation and the receiver's parse all derive from it
    (hello-checked with typed CodecMismatch/KVSpecMismatch).
  * stream.py — ``KVPageStream``/``KVPageStreamServer``: pages
    point-to-point over the sharded plane's framed transport with
    the PR 9 int8 block codec (verbatim for int8-resident pools).
  * pool.py   — ``DisaggPool``: two role-typed ReplicaPools plus the
    transfer plane; lease migration rides the PR 7 detach →
    stream → import → ``_reattach`` path, failure disposition
    mirrors the supervisor's requeue contract.

See docs/serving.md ("Disaggregated prefill/decode").
"""

from .pool import DisaggPool
from .spec import CodecMismatch, KVSpec, KVSpecMismatch
from .stream import (KVPageStream, KVPageStreamServer, KVStreamError,
                     KVStreamNack)

__all__ = [
    "CodecMismatch",
    "DisaggPool",
    "KVPageStream",
    "KVPageStreamServer",
    "KVSpec",
    "KVSpecMismatch",
    "KVStreamError",
    "KVStreamNack",
]
