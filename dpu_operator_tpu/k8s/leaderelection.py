"""Lease-based leader election.

Counterpart of the controller-runtime leader election the reference
enables in its manager (cmd/main.go:80-102, `LeaderElection: true` with a
coordination.k8s.io/v1 Lease). Semantics follow client-go's
leaderelection package:

  * a single Lease object is the lock; `spec.holderIdentity` names the
    current leader, `spec.renewTime` + `spec.leaseDurationSeconds` bound
    its validity;
  * candidates poll every `retry_period`; a lease held by another
    identity is only stolen after it expires;
  * the leader renews every `retry_period` and abdicates if it cannot
    renew within `renew_deadline` (apiserver partition) — callers must
    treat `on_stopped_leading` as fatal, exactly as client-go does
    (the operator process exits and lets k8s restart it);
  * on clean `stop()` the lease is released (holder cleared, duration
    shortened) so the next candidate takes over in ~1 retry period
    rather than a full lease duration.

Optimistic concurrency does the real work: two candidates that race an
expired lease both try `update()` from the same resourceVersion and the
store/apiserver rejects one with Conflict.
"""

from __future__ import annotations

import datetime
import logging
import threading
import time
import uuid
from typing import Callable, Optional

from .client import Client
from .objects import K8sObject
from .store import AlreadyExists, Conflict, NotFound

log = logging.getLogger(__name__)

LEASE_API_VERSION = "coordination.k8s.io/v1"
LEASE_KIND = "Lease"

# RFC3339 with microseconds, the MicroTime format Lease uses.
_MICRO_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def _now_micro() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(_MICRO_FMT)


class LeaderElector:
    def __init__(
        self,
        client: Client,
        lease_name: str,
        namespace: str,
        identity: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        retry_period: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        if renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        if retry_period >= renew_deadline:
            raise ValueError("retry_period must be < renew_deadline")
        self._client = client
        self._lease_name = lease_name
        self._namespace = namespace
        self.identity = identity or f"{lease_name}-{uuid.uuid4().hex[:8]}"
        self._lease_duration = lease_duration
        self._renew_deadline = renew_deadline
        self._retry_period = retry_period
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._stop = threading.Event()
        self._voluntary_stop = False
        self._thread: Optional[threading.Thread] = None
        self._is_leader = False
        # Lease validity is judged from *locally observed* renew times,
        # not the remote wall-clock timestamps — client-go does the same
        # so that clock skew between nodes cannot break mutual exclusion:
        # a lease only expires after we watched it go un-renewed for a
        # full lease_duration on our own monotonic clock.
        self._observed_record: Optional[tuple] = None
        self._observed_at: float = 0.0

    # -- observability --------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def leader_identity(self) -> Optional[str]:
        """Current holder as recorded in the Lease (None if unheld)."""
        lease = self._client.get_or_none(
            LEASE_API_VERSION, LEASE_KIND, self._namespace, self._lease_name
        )
        if lease is None:
            return None
        return (lease.get("spec") or {}).get("holderIdentity") or None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"leader-elector-{self.identity}"
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        # Voluntary shutdown must not fire on_stopped_leading — callers
        # wire that to "fatal, exit non-zero" (main.py), which is only
        # correct for *losing* the lease, not releasing it.
        self._voluntary_stop = True
        self._stop.set()
        if self._thread:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # A renew RPC is hung past the join timeout: releasing now
                # would race it — the late renew could rewrite
                # holderIdentity after our release and resurrect a lease
                # nobody holds, forcing the next candidate to wait out a
                # full lease_duration. Leave the lease to expire naturally
                # instead (same worst case, no corrupted handover).
                log.warning(
                    "leader election: %s renew thread still alive after "
                    "%.1fs; skipping lease release to avoid a late-renew "
                    "race (lease will expire naturally)",
                    self.identity, timeout,
                )
                return
        if self._is_leader:
            self._release()
            self._is_leader = False
            log.info("leader election: %s released leadership", self.identity)

    # -- internals ------------------------------------------------------------

    def _set_leader(self, leading: bool) -> None:
        was = self._is_leader
        self._is_leader = leading
        if leading and not was:
            log.info("leader election: %s became leader", self.identity)
            if self._on_started:
                try:
                    self._on_started()
                except Exception:
                    # A dead on_started (e.g. manager failed to start)
                    # while we hold the lease would leave the process
                    # "leading" but doing nothing. Abdicate and take the
                    # fatal on_stopped path so the pod restarts.
                    log.exception(
                        "leader election: on_started_leading failed; abdicating"
                    )
                    self._release()
                    self._set_leader(False)
                    self._stop.set()
        elif was and not leading:
            log.warning("leader election: %s lost leadership", self.identity)
            if self._on_stopped and not self._voluntary_stop:
                self._on_stopped()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                self._set_leader(True)
                self._renew_loop()
                if self._stop.is_set():
                    return
                # lost leadership (renewal starvation) — fall back to
                # candidate mode only via on_stopped; client-go exits here.
                return
            self._stop.wait(self._retry_period)

    def _renew_loop(self) -> None:
        while not self._stop.is_set():
            deadline = time.monotonic() + self._renew_deadline
            renewed = False
            while time.monotonic() < deadline and not self._stop.is_set():
                if self._try_acquire_or_renew():
                    renewed = True
                    break
                self._stop.wait(min(self._retry_period, 0.5))
            if not renewed:
                self._set_leader(False)
                return
            self._stop.wait(self._retry_period)

    def _new_lease(self) -> K8sObject:
        now = _now_micro()
        return {
            "apiVersion": LEASE_API_VERSION,
            "kind": LEASE_KIND,
            "metadata": {"name": self._lease_name, "namespace": self._namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self._lease_duration),
                "acquireTime": now,
                "renewTime": now,
                "leaseTransitions": 0,
            },
        }

    def _try_acquire_or_renew(self) -> bool:
        try:
            lease = self._client.get_or_none(
                LEASE_API_VERSION, LEASE_KIND, self._namespace, self._lease_name
            )
            if lease is None:
                try:
                    self._client.create(self._new_lease())
                    return True
                except (AlreadyExists, Conflict):
                    return False
            spec = lease.setdefault("spec", {})
            holder = spec.get("holderIdentity") or ""
            duration = float(spec.get("leaseDurationSeconds") or self._lease_duration)
            if holder and holder != self.identity:
                record = (holder, spec.get("renewTime") or "")
                if record != self._observed_record:
                    # Renewal observed — restart the local expiry clock.
                    # A fresh candidate therefore waits out one full
                    # lease_duration before stealing, never trusting the
                    # remote timestamp (which may be skewed).
                    self._observed_record = record
                    self._observed_at = time.monotonic()
                if time.monotonic() - self._observed_at < duration:
                    return False  # valid lease held by someone else
            # Acquire (expired/unheld) or renew (ours).
            now = _now_micro()
            if holder != self.identity:
                spec["leaseTransitions"] = int(spec.get("leaseTransitions") or 0) + 1
                spec["acquireTime"] = now
            spec["holderIdentity"] = self.identity
            spec["leaseDurationSeconds"] = int(self._lease_duration)
            spec["renewTime"] = now
            try:
                self._client.update(lease)
                return True
            except (Conflict, NotFound):
                return False
        except Exception:
            log.exception("leader election: acquire/renew attempt failed")
            return False

    def _release(self) -> None:
        """Clean handover: clear the holder so candidates don't wait out
        the full lease duration (client-go's ReleaseOnCancel)."""
        try:
            lease = self._client.get_or_none(
                LEASE_API_VERSION, LEASE_KIND, self._namespace, self._lease_name
            )
            if lease is None:
                return
            spec = lease.setdefault("spec", {})
            if spec.get("holderIdentity") != self.identity:
                return
            spec["holderIdentity"] = ""
            spec["leaseDurationSeconds"] = 1
            spec["renewTime"] = _now_micro()
            self._client.update(lease)
        except Exception:
            log.debug("leader election: release failed", exc_info=True)
