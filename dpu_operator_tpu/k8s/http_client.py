"""HttpClient — the Client interface against a real kube-apiserver.

Production binding of dpu_operator_tpu.k8s.client.Client (the in-memory
binding serves tests/standalone). Pure stdlib: bearer-token or cert auth,
JSON REST, and watch via the chunked ?watch=1 stream. In-cluster config
comes from the service-account mount, out-of-cluster from $KUBECONFIG.

The kind→resource mapping covers the kinds this operator touches; new
kinds just add a row (we deliberately avoid a discovery client)."""

from __future__ import annotations

import json
import os
import queue
import ssl
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional

import yaml

from .client import Client
from .objects import K8sObject, name_of, namespace_of
from .store import AlreadyExists, Conflict, NotFound, WatchEvent

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind → (plural, api prefix). Core v1 uses /api/v1, everything else /apis/<gv>.
_RESOURCES: Dict[str, str] = {
    "Pod": "pods",
    "Node": "nodes",
    "ConfigMap": "configmaps",
    "Secret": "secrets",
    "Service": "services",
    "ServiceAccount": "serviceaccounts",
    "Namespace": "namespaces",
    "Deployment": "deployments",
    "DaemonSet": "daemonsets",
    "Role": "roles",
    "RoleBinding": "rolebindings",
    "ClusterRole": "clusterroles",
    "ClusterRoleBinding": "clusterrolebindings",
    "CustomResourceDefinition": "customresourcedefinitions",
    "MutatingWebhookConfiguration": "mutatingwebhookconfigurations",
    "ValidatingWebhookConfiguration": "validatingwebhookconfigurations",
    "NetworkAttachmentDefinition": "network-attachment-definitions",
    "DpuOperatorConfig": "dpuoperatorconfigs",
    "DataProcessingUnit": "dataprocessingunits",
    "ServiceFunctionChain": "servicefunctionchains",
    "DataProcessingUnitConfig": "dataprocessingunitconfigs",
}

_CLUSTER_SCOPED = {
    "Node",
    "Namespace",
    "ClusterRole",
    "ClusterRoleBinding",
    "CustomResourceDefinition",
    "MutatingWebhookConfiguration",
    "ValidatingWebhookConfiguration",
}


class _HttpWatcher:
    def __init__(self):
        self.events: "queue.Queue[WatchEvent]" = queue.Queue()
        self.stopped = threading.Event()


class HttpClient(Client):
    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ):
        self._base = base_url.rstrip("/")
        self._token = token
        if insecure:
            self._ctx = ssl._create_unverified_context()
        elif ca_file:
            self._ctx = ssl.create_default_context(cafile=ca_file)
        else:
            self._ctx = ssl.create_default_context()
        self._watchers: List[_HttpWatcher] = []

    # -- url plumbing --------------------------------------------------------

    def _resource_url(
        self, api_version: str, kind: str, namespace: Optional[str], name: Optional[str]
    ) -> str:
        plural = _RESOURCES.get(kind)
        if plural is None:
            plural = kind.lower() + "s"
        prefix = "/api/v1" if api_version == "v1" else f"/apis/{api_version}"
        url = self._base + prefix
        if namespace and kind not in _CLUSTER_SCOPED:
            url += f"/namespaces/{namespace}"
        url += f"/{plural}"
        if name:
            url += f"/{name}"
        return url

    def _request(self, method: str, url: str, body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if body is not None:
            req.add_header("Content-Type", "application/json")
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            with urllib.request.urlopen(req, context=self._ctx, timeout=30) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            if e.code == 404:
                raise NotFound(f"{method} {url}: {detail}")
            if e.code == 409:
                if "AlreadyExists" in detail or method == "POST":
                    raise AlreadyExists(f"{method} {url}: {detail}")
                raise Conflict(f"{method} {url}: {detail}")
            raise RuntimeError(f"{method} {url}: HTTP {e.code}: {detail}")

    # -- Client interface ----------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        url = self._resource_url(obj["apiVersion"], obj["kind"], namespace_of(obj), None)
        return self._request("POST", url, obj)

    def get(self, api_version, kind, namespace, name) -> K8sObject:
        return self._request(
            "GET", self._resource_url(api_version, kind, namespace, name)
        )

    def list(self, api_version, kind, namespace=None, label_selector=None):
        url = self._resource_url(api_version, kind, namespace, None)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            url += f"?labelSelector={urllib.request.quote(sel)}"
        return self._request("GET", url).get("items", [])

    def update(self, obj: K8sObject) -> K8sObject:
        url = self._resource_url(
            obj["apiVersion"], obj["kind"], namespace_of(obj), name_of(obj)
        )
        return self._request("PUT", url, obj)

    def update_status(self, obj: K8sObject) -> K8sObject:
        url = (
            self._resource_url(
                obj["apiVersion"], obj["kind"], namespace_of(obj), name_of(obj)
            )
            + "/status"
        )
        return self._request("PUT", url, obj)

    def delete(self, api_version, kind, namespace, name) -> None:
        self._request(
            "DELETE", self._resource_url(api_version, kind, namespace, name)
        )

    def watch(self, api_version, kind, namespace=None):
        w = _HttpWatcher()
        self._watchers.append(w)
        t = threading.Thread(
            target=self._watch_loop,
            args=(w, api_version, kind, namespace),
            daemon=True,
            name=f"http-watch-{kind}",
        )
        t.start()
        return w

    def stop_watch(self, watcher) -> None:
        watcher.stopped.set()
        if watcher in self._watchers:
            self._watchers.remove(watcher)

    # -- watch internals -----------------------------------------------------

    def _watch_loop(self, w: _HttpWatcher, api_version, kind, namespace) -> None:
        import time

        rv: Optional[str] = None  # None = must (re)list before watching
        while not w.stopped.is_set():
            try:
                if rv is None:
                    listing = self._request(
                        "GET",
                        self._resource_url(api_version, kind, namespace, None),
                    )
                    rv = listing.get("metadata", {}).get("resourceVersion", "")
                    for item in listing.get("items", []):
                        item.setdefault("apiVersion", api_version)
                        item.setdefault("kind", kind)
                        w.events.put(WatchEvent("ADDED", item))
                url = (
                    self._resource_url(api_version, kind, namespace, None)
                    + f"?watch=1&resourceVersion={rv}&allowWatchBookmarks=true"
                )
                req = urllib.request.Request(url)
                req.add_header("Accept", "application/json")
                if self._token:
                    req.add_header("Authorization", f"Bearer {self._token}")
                with urllib.request.urlopen(req, context=self._ctx) as resp:
                    for line in resp:
                        if w.stopped.is_set():
                            return
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        ev_type = ev.get("type", "MODIFIED")
                        obj = ev.get("object", {})
                        if ev_type == "BOOKMARK":
                            # Progress marker carrying only a metadata
                            # skeleton — never delivered as a resource
                            # event (it would hand the controllers a
                            # spec-less ghost object), but its
                            # resourceVersion lets the next watch RESUME
                            # instead of relisting the world. This is
                            # what bookmarks exist for.
                            rv = obj.get("metadata", {}).get(
                                "resourceVersion") or rv
                            continue
                        if ev_type == "ERROR":
                            # e.g. 410 Gone (expired resourceVersion),
                            # body is a Status, not a resource: fall
                            # back to relist + rewatch — rate-limited
                            # like the exception path, or a server that
                            # ERRORs every stream would be list-hammered.
                            rv = None
                            if not w.stopped.is_set():
                                time.sleep(1.0)
                            break
                        obj.setdefault("apiVersion", api_version)
                        obj.setdefault("kind", kind)
                        rv = obj.get("metadata", {}).get(
                            "resourceVersion") or rv
                        w.events.put(WatchEvent(ev_type, obj))
                # Clean stream end: re-watch from the last seen RV (rv
                # stays set) — no duplicate-ADDED storm through the
                # controllers on every idle-timeout reconnect. Small
                # pause so a proxy that closes every stream immediately
                # cannot drive an unthrottled hot request loop.
                if not w.stopped.is_set():
                    time.sleep(0.2)
            except Exception:
                if w.stopped.is_set():
                    return
                rv = None
                time.sleep(2.0)  # relist + rewatch


def in_cluster_client() -> HttpClient:
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    with open(os.path.join(SA_DIR, "token")) as f:
        token = f.read().strip()
    return HttpClient(
        f"https://{host}:{port}", token=token, ca_file=os.path.join(SA_DIR, "ca.crt")
    )


def client_from_kubeconfig(path: Optional[str] = None) -> HttpClient:
    """In-cluster when the SA mount exists, else $KUBECONFIG/~/.kube/config
    (current-context, token or insecure)."""
    if os.path.exists(os.path.join(SA_DIR, "token")):
        return in_cluster_client()
    path = path or os.environ.get("KUBECONFIG", os.path.expanduser("~/.kube/config"))
    with open(path) as f:
        cfg = yaml.safe_load(f)
    ctx_name = cfg.get("current-context")
    ctx = next(c["context"] for c in cfg["contexts"] if c["name"] == ctx_name)
    cluster = next(
        c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"]
    )
    user = next(u["user"] for u in cfg["users"] if u["name"] == ctx["user"])
    token = user.get("token")
    insecure = bool(cluster.get("insecure-skip-tls-verify"))
    ca = cluster.get("certificate-authority")
    return HttpClient(cluster["server"], token=token, ca_file=ca, insecure=insecure)
