"""Helpers over plain-dict Kubernetes objects.

We deliberately represent every API object as a plain dict (apiVersion /
kind / metadata / spec / status), matching the wire format — the Python
counterpart of the reference's typed Go structs + unstructured rendering.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

K8sObject = Dict[str, Any]


def now_rfc3339() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def name_of(obj: K8sObject) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace_of(obj: K8sObject) -> Optional[str]:
    return obj.get("metadata", {}).get("namespace")


def uid_of(obj: K8sObject) -> str:
    return obj.get("metadata", {}).get("uid", "")


def gvk_of(obj: K8sObject) -> tuple:
    return (obj.get("apiVersion", ""), obj.get("kind", ""))


# -- conditions (status.conditions, metav1.Condition semantics) --------------


def set_condition(
    obj: K8sObject,
    type_: str,
    status: str,
    reason: str = "",
    message: str = "",
) -> bool:
    """Set/refresh a status condition. Returns True if it changed.

    Mirrors the Ready-condition plumbing the reference daemon does on
    DataProcessingUnit CRs (internal/daemon/daemon.go:173-204)."""
    status_block = obj.setdefault("status", {})
    conds: List[dict] = status_block.setdefault("conditions", [])
    for c in conds:
        if c.get("type") == type_:
            changed = (
                c.get("status") != status
                or c.get("reason") != reason
                or c.get("message") != message
            )
            if changed:
                c.update(
                    status=status,
                    reason=reason,
                    message=message,
                    lastTransitionTime=now_rfc3339(),
                )
            return changed
    conds.append(
        {
            "type": type_,
            "status": status,
            "reason": reason,
            "message": message,
            "lastTransitionTime": now_rfc3339(),
        }
    )
    return True


def get_condition(obj: K8sObject, type_: str) -> Optional[dict]:
    for c in obj.get("status", {}).get("conditions", []):
        if c.get("type") == type_:
            return c
    return None


# -- owner references --------------------------------------------------------


def owner_reference(owner: K8sObject, controller: bool = True) -> dict:
    return {
        "apiVersion": owner["apiVersion"],
        "kind": owner["kind"],
        "name": name_of(owner),
        "uid": uid_of(owner),
        "controller": controller,
        "blockOwnerDeletion": True,
    }


def set_owner(obj: K8sObject, owner: K8sObject) -> None:
    meta = obj.setdefault("metadata", {})
    refs = meta.setdefault("ownerReferences", [])
    for r in refs:
        if r.get("uid") == uid_of(owner):
            return
    refs.append(owner_reference(owner))


# -- finalizers --------------------------------------------------------------


def has_finalizer(obj: K8sObject, finalizer: str) -> bool:
    return finalizer in obj.get("metadata", {}).get("finalizers", [])


def add_finalizer(obj: K8sObject, finalizer: str) -> bool:
    meta = obj.setdefault("metadata", {})
    fins = meta.setdefault("finalizers", [])
    if finalizer in fins:
        return False
    fins.append(finalizer)
    return True


def remove_finalizer(obj: K8sObject, finalizer: str) -> bool:
    fins = obj.get("metadata", {}).get("finalizers", [])
    if finalizer not in fins:
        return False
    fins.remove(finalizer)
    return True


# -- label selectors ---------------------------------------------------------


def matches_selector(obj: K8sObject, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    return all(labels.get(k) == v for k, v in selector.items())
