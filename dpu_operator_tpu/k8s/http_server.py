"""ApiServer — InMemoryCluster behind real kube-apiserver REST semantics.

The HTTP tier of the test harness: the counterpart of the reference's
envtest/Kind clusters (internal/testutils/kindcluster.go:47-64,162-214),
which exist precisely so the *production* client bindings get exercised.
Serving the in-memory store over genuine HTTP lets the whole e2e stack
run through HttpClient (http_client.py) — chunked `?watch=1` streaming,
409 conflicts, the /status subresource, finalizer-gated deletion — so a
mistake in the production wire path fails a test instead of a cluster.

Speaks exactly the subset HttpClient emits:
  GET/POST           /api/v1|/apis/<gv> [/namespaces/<ns>] /<plural>
  GET/PUT/DELETE     .../<plural>/<name> [/status]
  GET                .../<plural>?watch=1&resourceVersion=N  (chunked)
plus `?labelSelector=k=v,...` on lists and k8s Status error bodies.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from .http_client import _CLUSTER_SCOPED, _RESOURCES
from .store import AlreadyExists, Conflict, Expired, InMemoryCluster, NotFound

_PLURAL_TO_KIND: Dict[str, str] = {v: k for k, v in _RESOURCES.items()}


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps(
        {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "reason": reason,
            "message": message,
            "code": code,
        }
    ).encode()


class _Route:
    __slots__ = ("api_version", "kind", "namespace", "name", "subresource")

    def __init__(self, api_version, kind, namespace, name, subresource):
        self.api_version = api_version
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.subresource = subresource


def _parse_path(path: str) -> Optional[_Route]:
    """/api/v1/... or /apis/<group>/<version>/... →  route or None."""
    parts = [p for p in path.split("/") if p]
    if not parts:
        return None
    if parts[0] == "api" and len(parts) >= 2 and parts[1] == "v1":
        api_version = "v1"
        rest = parts[2:]
    elif parts[0] == "apis" and len(parts) >= 3:
        api_version = f"{parts[1]}/{parts[2]}"
        rest = parts[3:]
    else:
        return None
    namespace = None
    # "namespaces/<ns>" is a scope prefix only when a resource follows;
    # "/api/v1/namespaces/<name>" with nothing after is the Namespace
    # object itself (GET/PUT/DELETE by name must not 404).
    if len(rest) >= 3 and rest[0] == "namespaces":
        namespace = rest[1]
        rest = rest[2:]
    if not rest:
        return None
    plural = rest[0]
    kind = _PLURAL_TO_KIND.get(plural)
    if kind is None:
        # Mirror the client's fallback: plural = kind.lower() + "s".
        kind = plural[:-1].capitalize() if plural.endswith("s") else plural.capitalize()
    name = rest[1] if len(rest) >= 2 else None
    subresource = rest[2] if len(rest) >= 3 else None
    return _Route(api_version, kind, namespace, name, subresource)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "ApiServer"

    # -- plumbing -------------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet; the tests assert, not read logs
        pass

    def _deny_unless_authorized(self) -> bool:
        token = self.server.token
        if not token:
            return False
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {token}":
            return False
        self._send(401, _status_body(401, "Unauthorized", "bad or missing token"))
        return True

    def _send(self, code: int, body: bytes, content_type="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_obj(self, code: int, obj: dict):
        self._send(code, json.dumps(obj).encode())

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length)) if length else {}

    def _route(self) -> Tuple[Optional[_Route], dict]:
        parsed = urllib.parse.urlsplit(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        rec = getattr(self.server, "request_log", None)
        if rec is not None and len(rec) < 10_000:
            rec.append(
                {
                    "method": self.command,
                    "path": parsed.path,
                    "query": query,
                    "content_type": self.headers.get("Content-Type"),
                }
            )
        return _parse_path(parsed.path), query

    # -- verbs ----------------------------------------------------------------

    def do_GET(self):
        if self._deny_unless_authorized():
            return
        route, query = self._route()
        if route is None:
            return self._send(404, _status_body(404, "NotFound", self.path))
        cluster = self.server.cluster
        try:
            if route.name:
                obj = cluster.get(
                    route.api_version, route.kind, route.namespace, route.name
                )
                return self._send_obj(200, obj)
            if query.get("watch") in ("1", "true"):
                return self._serve_watch(route, query)
            selector = None
            if "labelSelector" in query:
                selector = dict(
                    kv.split("=", 1) for kv in query["labelSelector"].split(",") if "=" in kv
                )
            # Items and rv under one lock hold: an rv taken separately could
            # postdate the snapshot and make watch resume skip the gap.
            items, rv = cluster.list_with_rv(
                route.api_version, route.kind, route.namespace, selector
            )
            return self._send_obj(
                200,
                {
                    "kind": f"{route.kind}List",
                    "apiVersion": route.api_version,
                    "metadata": {"resourceVersion": rv},
                    "items": items,
                },
            )
        except NotFound as e:
            return self._send(404, _status_body(404, "NotFound", str(e)))

    def do_POST(self):
        if self._deny_unless_authorized():
            return
        route, _ = self._route()
        if route is None:
            return self._send(404, _status_body(404, "NotFound", self.path))
        obj = self._read_body()
        obj.setdefault("apiVersion", route.api_version)
        obj.setdefault("kind", route.kind)
        if route.namespace and route.kind not in _CLUSTER_SCOPED:
            obj.setdefault("metadata", {}).setdefault("namespace", route.namespace)
        try:
            created = self.server.cluster.create(obj)
            return self._send_obj(201, created)
        except AlreadyExists as e:
            return self._send(409, _status_body(409, "AlreadyExists", str(e)))

    def do_PUT(self):
        if self._deny_unless_authorized():
            return
        route, _ = self._route()
        if route is None or route.name is None:
            return self._send(404, _status_body(404, "NotFound", self.path))
        obj = self._read_body()
        obj.setdefault("apiVersion", route.api_version)
        obj.setdefault("kind", route.kind)
        try:
            if route.subresource == "status":
                updated = self.server.cluster.update_status(obj)
            elif route.subresource is None:
                updated = self.server.cluster.update(obj)
            else:
                return self._send(
                    404, _status_body(404, "NotFound", f"subresource {route.subresource}")
                )
            return self._send_obj(200, updated)
        except NotFound as e:
            return self._send(404, _status_body(404, "NotFound", str(e)))
        except Conflict as e:
            return self._send(409, _status_body(409, "Conflict", str(e)))

    def do_DELETE(self):
        if self._deny_unless_authorized():
            return
        route, _ = self._route()
        if route is None or route.name is None:
            return self._send(404, _status_body(404, "NotFound", self.path))
        try:
            self.server.cluster.delete(
                route.api_version, route.kind, route.namespace, route.name
            )
            return self._send_obj(200, {"kind": "Status", "status": "Success"})
        except NotFound as e:
            return self._send(404, _status_body(404, "NotFound", str(e)))

    # -- watch ----------------------------------------------------------------

    def _serve_watch(self, route: _Route, query: dict):
        """Chunked newline-delimited watch events, real apiserver shape.
        Runs until the client hangs up (write fails) or the server stops."""
        cluster = self.server.cluster
        try:
            watcher = cluster.watch(
                route.api_version,
                route.kind,
                route.namespace,
                since_rv=query.get("resourceVersion") or None,
            )
        except Expired as e:
            # 410 Gone: the resume point fell off the history window; the
            # client relists, exactly as against a real apiserver.
            return self._send(410, _status_body(410, "Expired", str(e)))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while not self.server.stopping.is_set():
                try:
                    ev = watcher.events.get(timeout=0.25)
                except Exception:
                    continue
                line = json.dumps({"type": ev.type, "object": ev.object}).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(line) + line + b"\r\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            cluster.stop_watch(watcher)
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
            self.close_connection = True


class ApiServer:
    """Serve `cluster` on 127.0.0.1:<port> (0 = ephemeral). With `token`,
    every request must carry the matching Bearer token (the reference
    protects its endpoints the same way, cmd/main.go:82-86)."""

    def __init__(
        self,
        cluster: InMemoryCluster,
        port: int = 0,
        token: Optional[str] = None,
        record_requests: bool = False,
    ):
        self.cluster = cluster
        self.token = token
        self.stopping = threading.Event()
        # With record_requests, every request's (method, path, query,
        # content-type) is appended here — the protocol-fidelity tests
        # assert these wire shapes match kube-apiserver's documented
        # forms, so a drift in HttpClient's URL/verb construction fails a
        # test instead of a real cluster.
        self.request_log: Optional[list] = [] if record_requests else None
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        # Hand the handler its back-references via the server object.
        self._httpd.cluster = cluster  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self._httpd.stopping = self.stopping  # type: ignore[attr-defined]
        self._httpd.request_log = self.request_log  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="apiserver"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def write_kubeconfig(self, path: str) -> str:
        """A kubeconfig pointing at this server — lets tests exercise
        client_from_kubeconfig end-to-end."""
        cfg = {
            "apiVersion": "v1",
            "kind": "Config",
            "current-context": "inmem",
            "contexts": [{"name": "inmem", "context": {"cluster": "inmem", "user": "u"}}],
            "clusters": [{"name": "inmem", "cluster": {"server": self.url}}],
            "users": [{"name": "u", "user": ({"token": self.token} if self.token else {})}],
        }
        import yaml

        with open(path, "w") as f:
            yaml.safe_dump(cfg, f)
        return path
