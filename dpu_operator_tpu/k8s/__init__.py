from .objects import (
    name_of,
    namespace_of,
    set_condition,
    get_condition,
    owner_reference,
    has_finalizer,
    add_finalizer,
    remove_finalizer,
)
from .store import InMemoryCluster, Conflict, NotFound, AlreadyExists, WatchEvent
from .client import Client, InMemoryClient
from .controller import Manager, Reconciler, Result, Request

__all__ = [
    "name_of",
    "namespace_of",
    "set_condition",
    "get_condition",
    "owner_reference",
    "has_finalizer",
    "add_finalizer",
    "remove_finalizer",
    "InMemoryCluster",
    "Conflict",
    "NotFound",
    "AlreadyExists",
    "WatchEvent",
    "Client",
    "InMemoryClient",
    "Manager",
    "Reconciler",
    "Result",
    "Request",
]
