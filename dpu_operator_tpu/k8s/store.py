"""InMemoryCluster — a minimal but semantically-faithful API server.

This is the test/standalone seam of the framework: the counterpart of the
reference's envtest/Kind clusters (internal/testutils/kindcluster.go). It
implements the API-machinery semantics the controllers depend on:

  * resourceVersion bump on every write + optimistic-concurrency Conflict
  * watch streams (ADDED/MODIFIED/DELETED) with per-watcher queues
  * deletionTimestamp + finalizer gating of actual removal
  * ownerReference cascade garbage collection
  * namespaced and cluster-scoped resources, label-selector list

Production deployments talk to a real kube-apiserver through the same
Client interface (client.py); controllers cannot tell the difference.
"""

from __future__ import annotations

import copy
import queue
import threading
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .objects import K8sObject, name_of, namespace_of, now_rfc3339, uid_of


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


class Conflict(Exception):
    pass


class Expired(Exception):
    """Watch resume point fell off the event history (HTTP 410 Gone);
    the client must relist, exactly as against a real apiserver."""


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: K8sObject


Key = Tuple[str, str, Optional[str], str]  # (apiVersion, kind, namespace, name)


class _Watcher:
    def __init__(self, api_version: str, kind: str, namespace: Optional[str]):
        self.api_version = api_version
        self.kind = kind
        self.namespace = namespace
        self.events: "queue.Queue[WatchEvent]" = queue.Queue()

    def matches(self, obj: K8sObject) -> bool:
        if obj.get("apiVersion") != self.api_version or obj.get("kind") != self.kind:
            return False
        if self.namespace is not None and namespace_of(obj) != self.namespace:
            return False
        return True


class InMemoryCluster:
    HISTORY = 4096  # retained watch events; older resume points get Expired

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[Key, K8sObject] = {}
        self._rv = 0
        self._watchers: List[_Watcher] = []
        # (rv, WatchEvent) ring so watches can resume from a resourceVersion
        # with DELETED/MODIFIED fidelity, like a real apiserver's etcd window.
        self._history: "deque[Tuple[int, WatchEvent]]" = deque(maxlen=self.HISTORY)

    # -- helpers -------------------------------------------------------------

    def _key(self, obj: K8sObject) -> Key:
        return (obj["apiVersion"], obj["kind"], namespace_of(obj), name_of(obj))

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _emit(self, etype: str, obj: K8sObject) -> None:
        ev = WatchEvent(etype, copy.deepcopy(obj))
        rv = int(obj.get("metadata", {}).get("resourceVersion", self._rv) or self._rv)
        self._history.append((rv, ev))
        for w in self._watchers:
            if w.matches(obj):
                w.events.put(WatchEvent(etype, copy.deepcopy(obj)))

    # -- CRUD ----------------------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        with self._lock:
            obj = copy.deepcopy(obj)
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExists(f"{key} already exists")
            meta = obj.setdefault("metadata", {})
            meta["uid"] = meta.get("uid") or str(uuid.uuid4())
            meta["resourceVersion"] = self._next_rv()
            meta["creationTimestamp"] = meta.get("creationTimestamp") or now_rfc3339()
            self._objects[key] = obj
            self._emit("ADDED", obj)
            return copy.deepcopy(obj)

    def get(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> K8sObject:
        with self._lock:
            key = (api_version, kind, namespace, name)
            obj = self._objects.get(key)
            if obj is None:
                raise NotFound(f"{key} not found")
            return copy.deepcopy(obj)

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[K8sObject]:
        from .objects import matches_selector

        with self._lock:
            out = []
            for (av, k, ns, _), obj in self._objects.items():
                if av != api_version or k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if not matches_selector(obj, label_selector):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def update(self, obj: K8sObject) -> K8sObject:
        with self._lock:
            obj = copy.deepcopy(obj)
            key = self._key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{key} not found")
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{key}: resourceVersion {sent_rv} != {cur['metadata']['resourceVersion']}"
                )
            # Immutable fields survive the write.
            obj["metadata"]["uid"] = cur["metadata"]["uid"]
            obj["metadata"]["creationTimestamp"] = cur["metadata"]["creationTimestamp"]
            if "deletionTimestamp" in cur["metadata"]:
                obj["metadata"]["deletionTimestamp"] = cur["metadata"]["deletionTimestamp"]
            obj["metadata"]["resourceVersion"] = self._next_rv()
            self._objects[key] = obj
            self._emit("MODIFIED", obj)
            # A finalizer removal on a deleting object may allow reaping.
            if "deletionTimestamp" in obj["metadata"] and not obj["metadata"].get(
                "finalizers"
            ):
                self._reap(key)
            return copy.deepcopy(obj)

    def update_status(self, obj: K8sObject) -> K8sObject:
        """Status-subresource write: only .status is applied."""
        with self._lock:
            key = self._key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{key} not found")
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{key}: resourceVersion {sent_rv} != {cur['metadata']['resourceVersion']}"
                )
            cur = copy.deepcopy(cur)
            cur["status"] = copy.deepcopy(obj.get("status", {}))
            cur["metadata"]["resourceVersion"] = self._next_rv()
            self._objects[key] = cur
            self._emit("MODIFIED", cur)
            return copy.deepcopy(cur)

    def delete(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> None:
        with self._lock:
            key = (api_version, kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{key} not found")
            if cur["metadata"].get("finalizers"):
                if "deletionTimestamp" not in cur["metadata"]:
                    cur["metadata"]["deletionTimestamp"] = now_rfc3339()
                    cur["metadata"]["resourceVersion"] = self._next_rv()
                    self._emit("MODIFIED", cur)
                return
            self._reap(key)

    def _reap(self, key: Key) -> None:
        cur = self._objects.pop(key, None)
        if cur is None:
            return
        # A real apiserver stamps the deletion event with a fresh rv; the
        # watch-resume filter (rv > floor) depends on that.
        cur["metadata"]["resourceVersion"] = self._next_rv()
        self._emit("DELETED", cur)
        self._gc_orphans(uid_of(cur))

    def _gc_orphans(self, owner_uid: str) -> None:
        """Cascade-delete objects whose sole controller owner vanished."""
        to_delete = []
        for key, obj in list(self._objects.items()):
            refs = obj.get("metadata", {}).get("ownerReferences", [])
            if any(r.get("uid") == owner_uid for r in refs):
                remaining = [r for r in refs if r.get("uid") != owner_uid]
                if remaining:
                    obj["metadata"]["ownerReferences"] = remaining
                    obj["metadata"]["resourceVersion"] = self._next_rv()
                    self._emit("MODIFIED", obj)
                else:
                    to_delete.append(key)
        for key in to_delete:
            av, k, ns, n = key
            try:
                self.delete(av, k, ns, n)
            except NotFound:
                pass

    # -- watches -------------------------------------------------------------

    @property
    def resource_version(self) -> str:
        """The cluster's current (latest) resourceVersion."""
        with self._lock:
            return str(self._rv)

    def list_with_rv(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[K8sObject], str]:
        """Items + the list resourceVersion under ONE lock hold — the rv a
        client may resume a watch from without losing events created
        between a separate list() and resource_version read."""
        with self._lock:
            return self.list(api_version, kind, namespace, label_selector), str(self._rv)

    def watch(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        since_rv: Optional[str] = None,
    ) -> _Watcher:
        """Returns a watcher primed with synthetic ADDED events for existing
        objects (list+watch semantics collapsed, as informers present it).

        With `since_rv` (real apiserver `?watch=1&resourceVersion=` shape,
        used by the HTTP tier where the client already listed), priming
        replays the recorded event history after that resourceVersion —
        including DELETED/MODIFIED, so a deletion between the client's
        list and the watch registration is not lost. A resume point older
        than the retained history raises Expired (HTTP 410 Gone) to force
        a relist, matching apiserver behavior."""
        with self._lock:
            w = _Watcher(api_version, kind, namespace)
            if since_rv is not None:
                floor = int(since_rv)
                if floor < self._rv:
                    oldest = self._history[0][0] if self._history else self._rv + 1
                    if floor + 1 < oldest:
                        raise Expired(
                            f"resourceVersion {since_rv} is too old "
                            f"(history starts at {oldest})"
                        )
                    for rv, ev in self._history:
                        if rv > floor and w.matches(ev.object):
                            w.events.put(
                                WatchEvent(ev.type, copy.deepcopy(ev.object))
                            )
            else:
                for obj in self.list(api_version, kind, namespace):
                    w.events.put(WatchEvent("ADDED", obj))
            self._watchers.append(w)
            return w

    def stop_watch(self, watcher: _Watcher) -> None:
        with self._lock:
            if watcher in self._watchers:
                self._watchers.remove(watcher)
