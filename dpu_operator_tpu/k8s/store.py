"""InMemoryCluster — a minimal but semantically-faithful API server.

This is the test/standalone seam of the framework: the counterpart of the
reference's envtest/Kind clusters (internal/testutils/kindcluster.go). It
implements the API-machinery semantics the controllers depend on:

  * resourceVersion bump on every write + optimistic-concurrency Conflict
  * watch streams (ADDED/MODIFIED/DELETED) with per-watcher queues
  * deletionTimestamp + finalizer gating of actual removal
  * ownerReference cascade garbage collection
  * namespaced and cluster-scoped resources, label-selector list

Production deployments talk to a real kube-apiserver through the same
Client interface (client.py); controllers cannot tell the difference.
"""

from __future__ import annotations

import copy
import queue
import threading
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .objects import K8sObject, name_of, namespace_of, now_rfc3339, uid_of


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


class Conflict(Exception):
    pass


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: K8sObject


Key = Tuple[str, str, Optional[str], str]  # (apiVersion, kind, namespace, name)


class _Watcher:
    def __init__(self, api_version: str, kind: str, namespace: Optional[str]):
        self.api_version = api_version
        self.kind = kind
        self.namespace = namespace
        self.events: "queue.Queue[WatchEvent]" = queue.Queue()

    def matches(self, obj: K8sObject) -> bool:
        if obj.get("apiVersion") != self.api_version or obj.get("kind") != self.kind:
            return False
        if self.namespace is not None and namespace_of(obj) != self.namespace:
            return False
        return True


class InMemoryCluster:
    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[Key, K8sObject] = {}
        self._rv = 0
        self._watchers: List[_Watcher] = []

    # -- helpers -------------------------------------------------------------

    def _key(self, obj: K8sObject) -> Key:
        return (obj["apiVersion"], obj["kind"], namespace_of(obj), name_of(obj))

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _emit(self, etype: str, obj: K8sObject) -> None:
        for w in self._watchers:
            if w.matches(obj):
                w.events.put(WatchEvent(etype, copy.deepcopy(obj)))

    # -- CRUD ----------------------------------------------------------------

    def create(self, obj: K8sObject) -> K8sObject:
        with self._lock:
            obj = copy.deepcopy(obj)
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExists(f"{key} already exists")
            meta = obj.setdefault("metadata", {})
            meta["uid"] = meta.get("uid") or str(uuid.uuid4())
            meta["resourceVersion"] = self._next_rv()
            meta["creationTimestamp"] = meta.get("creationTimestamp") or now_rfc3339()
            self._objects[key] = obj
            self._emit("ADDED", obj)
            return copy.deepcopy(obj)

    def get(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> K8sObject:
        with self._lock:
            key = (api_version, kind, namespace, name)
            obj = self._objects.get(key)
            if obj is None:
                raise NotFound(f"{key} not found")
            return copy.deepcopy(obj)

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[K8sObject]:
        from .objects import matches_selector

        with self._lock:
            out = []
            for (av, k, ns, _), obj in self._objects.items():
                if av != api_version or k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if not matches_selector(obj, label_selector):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def update(self, obj: K8sObject) -> K8sObject:
        with self._lock:
            obj = copy.deepcopy(obj)
            key = self._key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{key} not found")
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{key}: resourceVersion {sent_rv} != {cur['metadata']['resourceVersion']}"
                )
            # Immutable fields survive the write.
            obj["metadata"]["uid"] = cur["metadata"]["uid"]
            obj["metadata"]["creationTimestamp"] = cur["metadata"]["creationTimestamp"]
            if "deletionTimestamp" in cur["metadata"]:
                obj["metadata"]["deletionTimestamp"] = cur["metadata"]["deletionTimestamp"]
            obj["metadata"]["resourceVersion"] = self._next_rv()
            self._objects[key] = obj
            self._emit("MODIFIED", obj)
            # A finalizer removal on a deleting object may allow reaping.
            if "deletionTimestamp" in obj["metadata"] and not obj["metadata"].get(
                "finalizers"
            ):
                self._reap(key)
            return copy.deepcopy(obj)

    def update_status(self, obj: K8sObject) -> K8sObject:
        """Status-subresource write: only .status is applied."""
        with self._lock:
            key = self._key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{key} not found")
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{key}: resourceVersion {sent_rv} != {cur['metadata']['resourceVersion']}"
                )
            cur = copy.deepcopy(cur)
            cur["status"] = copy.deepcopy(obj.get("status", {}))
            cur["metadata"]["resourceVersion"] = self._next_rv()
            self._objects[key] = cur
            self._emit("MODIFIED", cur)
            return copy.deepcopy(cur)

    def delete(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> None:
        with self._lock:
            key = (api_version, kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{key} not found")
            if cur["metadata"].get("finalizers"):
                if "deletionTimestamp" not in cur["metadata"]:
                    cur["metadata"]["deletionTimestamp"] = now_rfc3339()
                    cur["metadata"]["resourceVersion"] = self._next_rv()
                    self._emit("MODIFIED", cur)
                return
            self._reap(key)

    def _reap(self, key: Key) -> None:
        cur = self._objects.pop(key, None)
        if cur is None:
            return
        self._emit("DELETED", cur)
        self._gc_orphans(uid_of(cur))

    def _gc_orphans(self, owner_uid: str) -> None:
        """Cascade-delete objects whose sole controller owner vanished."""
        to_delete = []
        for key, obj in list(self._objects.items()):
            refs = obj.get("metadata", {}).get("ownerReferences", [])
            if any(r.get("uid") == owner_uid for r in refs):
                remaining = [r for r in refs if r.get("uid") != owner_uid]
                if remaining:
                    obj["metadata"]["ownerReferences"] = remaining
                    obj["metadata"]["resourceVersion"] = self._next_rv()
                    self._emit("MODIFIED", obj)
                else:
                    to_delete.append(key)
        for key in to_delete:
            av, k, ns, n = key
            try:
                self.delete(av, k, ns, n)
            except NotFound:
                pass

    # -- watches -------------------------------------------------------------

    def watch(
        self, api_version: str, kind: str, namespace: Optional[str] = None
    ) -> _Watcher:
        """Returns a watcher primed with synthetic ADDED events for existing
        objects (list+watch semantics collapsed, as informers present it)."""
        with self._lock:
            w = _Watcher(api_version, kind, namespace)
            for obj in self.list(api_version, kind, namespace):
                w.events.put(WatchEvent("ADDED", obj))
            self._watchers.append(w)
            return w

    def stop_watch(self, watcher: _Watcher) -> None:
        with self._lock:
            if watcher in self._watchers:
                self._watchers.remove(watcher)
