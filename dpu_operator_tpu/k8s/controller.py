"""Controller framework — workqueue reconcilers driven by watches.

Python counterpart of controller-runtime's manager/controller machinery
that the reference builds on (cmd/main.go:80-148). Each controller owns a
watch on its primary kind (plus optional secondary kinds mapped to
requests), a deduplicating workqueue, and a worker thread that calls
Reconcile with retry-on-error exponential backoff.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from .client import Client
from .objects import K8sObject, name_of, namespace_of

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Request:
    namespace: Optional[str]
    name: str


@dataclass
class Result:
    requeue_after: Optional[float] = None


class Reconciler:
    def reconcile(self, req: Request) -> Result:
        raise NotImplementedError


@dataclass
class _WatchSpec:
    api_version: str
    kind: str
    namespace: Optional[str]
    # Maps an event object to reconcile Requests (identity for the primary
    # kind; owner-lookup or constant mapping for secondary kinds).
    mapper: Callable[[K8sObject], List[Request]]


class Controller:
    _MAX_BACKOFF = 16.0

    def __init__(self, name: str, reconciler: Reconciler, client: Client):
        self.name = name
        self.reconciler = reconciler
        self.client = client
        self._watch_specs: List[_WatchSpec] = []
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._pending: set = set()
        self._pending_lock = threading.Lock()
        self._failures: dict = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._watchers: List[Tuple[object, _WatchSpec]] = []

    # -- wiring --------------------------------------------------------------

    def watches(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        mapper: Optional[Callable[[K8sObject], List[Request]]] = None,
    ) -> "Controller":
        if mapper is None:
            mapper = lambda obj: [Request(namespace_of(obj), name_of(obj))]
        self._watch_specs.append(_WatchSpec(api_version, kind, namespace, mapper))
        return self

    def enqueue(self, req: Request) -> None:
        with self._pending_lock:
            if req in self._pending:
                return
            self._pending.add(req)
        self._queue.put(req)

    # -- run loop ------------------------------------------------------------

    def start(self) -> None:
        for spec in self._watch_specs:
            w = self.client.watch(spec.api_version, spec.kind, spec.namespace)
            self._watchers.append((w, spec))
            t = threading.Thread(
                target=self._watch_loop, args=(w, spec), daemon=True,
                name=f"ctrl-{self.name}-watch-{spec.kind}",
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(
            target=self._worker, daemon=True, name=f"ctrl-{self.name}-worker"
        )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for w, _ in self._watchers:
            try:
                self.client.stop_watch(w)
            except Exception:
                pass

    def _watch_loop(self, watcher, spec: _WatchSpec) -> None:
        while not self._stop.is_set():
            try:
                ev = watcher.events.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                for req in spec.mapper(ev.object):
                    self.enqueue(req)
            except Exception:
                log.exception("%s: watch mapper failed", self.name)

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                req = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._pending_lock:
                self._pending.discard(req)
            from ..utils.metrics import default_registry as metrics

            try:
                result = self.reconciler.reconcile(req)
                self._failures.pop(req, None)
                if result and result.requeue_after:
                    self._requeue_later(req, result.requeue_after)
                metrics.counter_inc(
                    "dpu_reconcile_total",
                    {"controller": self.name, "result": "ok"},
                    help="Reconcile attempts per controller",
                )
            except Exception:
                log.exception("%s: reconcile %s failed", self.name, req)
                metrics.counter_inc(
                    "dpu_reconcile_total",
                    {"controller": self.name, "result": "error"},
                    help="Reconcile attempts per controller",
                )
                n = self._failures.get(req, 0) + 1
                self._failures[req] = n
                self._requeue_later(req, min(0.05 * (2 ** n), self._MAX_BACKOFF))

    def _requeue_later(self, req: Request, delay: float) -> None:
        def fire():
            if not self._stop.is_set():
                self.enqueue(req)

        t = threading.Timer(delay, fire)
        t.daemon = True
        t.start()


class Manager:
    """Holds controllers and runs them; the process-level lifecycle object
    (reference: ctrl.NewManager + mgr.Start, cmd/main.go:80-161)."""

    def __init__(self, client: Client):
        self.client = client
        self._controllers: List[Controller] = []
        self._runnables: List[Callable[[], None]] = []
        self._stop_fns: List[Callable[[], None]] = []
        self._threads: List[threading.Thread] = []
        self._started = False

    def new_controller(self, name: str, reconciler: Reconciler) -> Controller:
        c = Controller(name, reconciler, self.client)
        self._controllers.append(c)
        return c

    def add_runnable(
        self, run: Callable[[], None], stop: Optional[Callable[[], None]] = None
    ) -> None:
        self._runnables.append(run)
        if stop:
            self._stop_fns.append(stop)

    def start(self) -> None:
        self._started = True
        for c in self._controllers:
            c.start()
        for run in self._runnables:
            t = threading.Thread(target=run, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        for c in self._controllers:
            c.stop()
        for fn in self._stop_fns:
            try:
                fn()
            except Exception:
                log.exception("runnable stop failed")

    def wait_until(self, predicate: Callable[[], bool], timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.02)
        return predicate()
