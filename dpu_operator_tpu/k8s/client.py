"""Client — the one API-access interface every component uses.

Controllers, daemon, and webhooks all speak this interface; InMemoryClient
binds it to the in-process store (test/standalone), and HttpClient (see
http_client.py) binds it to a real kube-apiserver. This is the seam the
reference gets from controller-runtime's client.Client.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .objects import K8sObject
from .store import InMemoryCluster, NotFound


class Client:
    def create(self, obj: K8sObject) -> K8sObject:
        raise NotImplementedError

    def get(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> K8sObject:
        raise NotImplementedError

    def get_or_none(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> Optional[K8sObject]:
        try:
            return self.get(api_version, kind, namespace, name)
        except NotFound:
            return None

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[K8sObject]:
        raise NotImplementedError

    def update(self, obj: K8sObject) -> K8sObject:
        raise NotImplementedError

    def update_status(self, obj: K8sObject) -> K8sObject:
        raise NotImplementedError

    def delete(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> None:
        raise NotImplementedError

    def delete_if_exists(
        self, api_version: str, kind: str, namespace: Optional[str], name: str
    ) -> None:
        try:
            self.delete(api_version, kind, namespace, name)
        except NotFound:
            pass

    def apply(self, obj: K8sObject) -> K8sObject:
        """Create-or-update merge apply (the reference uses
        sriov-network-operator's pkg/apply; render.go:26-80)."""
        from .objects import name_of, namespace_of

        cur = self.get_or_none(
            obj["apiVersion"], obj["kind"], namespace_of(obj), name_of(obj)
        )
        if cur is None:
            return self.create(obj)
        merged = dict(cur)
        for k, v in obj.items():
            if k == "metadata":
                m = dict(cur.get("metadata", {}))
                for mk, mv in obj["metadata"].items():
                    if mk in ("labels", "annotations") and mk in m and isinstance(mv, dict):
                        merged_map = dict(m[mk] or {})
                        merged_map.update(mv)
                        m[mk] = merged_map
                    elif mk not in ("resourceVersion", "uid", "creationTimestamp"):
                        m[mk] = mv
                merged["metadata"] = m
            elif k != "status":
                merged[k] = v
        merged["metadata"]["resourceVersion"] = cur["metadata"]["resourceVersion"]
        return self.update(merged)

    def watch(self, api_version: str, kind: str, namespace: Optional[str] = None):
        raise NotImplementedError

    def stop_watch(self, watcher) -> None:
        raise NotImplementedError


class InMemoryClient(Client):
    def __init__(self, cluster: InMemoryCluster):
        self.cluster = cluster

    def create(self, obj):
        return self.cluster.create(obj)

    def get(self, api_version, kind, namespace, name):
        return self.cluster.get(api_version, kind, namespace, name)

    def list(self, api_version, kind, namespace=None, label_selector=None):
        return self.cluster.list(api_version, kind, namespace, label_selector)

    def update(self, obj):
        return self.cluster.update(obj)

    def update_status(self, obj):
        return self.cluster.update_status(obj)

    def delete(self, api_version, kind, namespace, name):
        return self.cluster.delete(api_version, kind, namespace, name)

    def watch(self, api_version, kind, namespace=None):
        return self.cluster.watch(api_version, kind, namespace)

    def stop_watch(self, watcher):
        return self.cluster.stop_watch(watcher)
