"""Manifest rendering + tracked apply.

Counterpart of reference pkgs/render/render.go: templates over embedded
YAML with missing-variable errors (render.go:26-42 uses missingkey=error;
jinja2 StrictUndefined is the same contract), sorted file order
(render.go:43-60), owner references on everything applied, and a
ResourceRenderer that records applied objects for reverse-order cleanup
(the deletion path of the DpuOperatorConfig finalizer)."""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

import jinja2
import yaml

from ..k8s.client import Client
from ..k8s.objects import K8sObject, name_of, namespace_of, set_owner

log = logging.getLogger(__name__)

_ENV = jinja2.Environment(undefined=jinja2.StrictUndefined, autoescape=False)


def render_template(text: str, variables: Dict[str, str]) -> List[K8sObject]:
    """Render one template into its (possibly multi-doc) objects."""
    rendered = _ENV.from_string(text).render(**variables)
    objs = []
    for doc in yaml.safe_load_all(rendered):
        if doc:
            objs.append(doc)
    return objs


def render_dir(directory: str, variables: Dict[str, str]) -> List[K8sObject]:
    """Render every .yaml in sorted order (reference render.go:43-60)."""
    objs: List[K8sObject] = []
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(directory, fname)) as f:
            objs.extend(render_template(f.read(), variables))
    return objs


class ResourceRenderer:
    """Tracked apply + reverse-order cleanup (reference ResourceRenderer)."""

    def __init__(self, client: Client):
        self._client = client
        self._applied: List[K8sObject] = []

    def apply(self, obj: K8sObject, owner: Optional[K8sObject] = None) -> K8sObject:
        if owner is not None and namespace_of(obj) == namespace_of(owner):
            set_owner(obj, owner)
        applied = self._client.apply(obj)
        self._applied.append(
            {
                "apiVersion": obj["apiVersion"],
                "kind": obj["kind"],
                "metadata": {
                    "name": name_of(obj),
                    "namespace": namespace_of(obj),
                },
            }
        )
        return applied

    def apply_all(
        self,
        objs: List[K8sObject],
        owner: Optional[K8sObject] = None,
    ) -> None:
        for obj in objs:
            self.apply(obj, owner)

    def apply_dir(
        self,
        directory: str,
        variables: Dict[str, str],
        owner: Optional[K8sObject] = None,
    ) -> None:
        self.apply_all(render_dir(directory, variables), owner)

    def cleanup_reverse_order(self) -> None:
        for ref in reversed(self._applied):
            self._client.delete_if_exists(
                ref["apiVersion"],
                ref["kind"],
                ref["metadata"]["namespace"],
                ref["metadata"]["name"],
            )
        self._applied.clear()

    @property
    def applied_refs(self) -> List[K8sObject]:
        return list(self._applied)
