from .render import ResourceRenderer, render_dir, render_template

__all__ = ["ResourceRenderer", "render_dir", "render_template"]
