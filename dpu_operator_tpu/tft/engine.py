"""Traffic engines — iperf3/netperf stand-ins runnable inside a pod netns.

The reference delegates to iperf3/netperf via the
kubernetes-traffic-flow-tests submodule (hack/traffic_flow_tests.sh,
ocp-tft-config.yaml: iperf-tcp / iperf-udp / netperf-tcp-stream /
netperf-tcp-rr). Neither tool ships in this image, so the same four test
shapes are implemented twice behind one CLI:

  * the native pump (native/tft-pump, C++) — no interpreter in the byte
    loop, so its numbers measure the dataplane; preferred whenever the
    binary is built (main() execs it);
  * this Python fallback — honest about being an engine ceiling: every
    result line is tagged "engine": "python" vs "c" so recorded numbers
    say what produced them (VERDICT r1 Weak #2).

Each engine prints a single JSON result line so the harness can collect
from `ip netns exec` subprocesses.

Invocation (from tft.py, one process per endpoint):
    python -m dpu_operator_tpu.tft.engine server <type> <bind_ip> <port> <duration>
    python -m dpu_operator_tpu.tft.engine client <type> <server_ip> <port> <duration>

Env: TFT_PUMP=/path/to/tft-pump overrides binary discovery;
TFT_PUMP=python forces the fallback (used by tests)."""

from __future__ import annotations

import json
import os
import socket
import sys
import time

BUF = 256 * 1024  # stream write size
UDP_PAYLOAD = 8192
RR_PAYLOAD = 1


def _family(ip: str) -> int:
    """Dual-stack: the v6 matrix cases (13/14) hand engines ULA
    addresses."""
    return socket.AF_INET6 if ":" in ip else socket.AF_INET


def _emit(**kw) -> None:
    kw.setdefault("engine", "python")
    print(json.dumps(kw), flush=True)


def find_pump() -> str | None:
    """Locate the native engine: $TFT_PUMP, or the repo-local cmake
    output (native/build/tft-pump). Returns None to use the fallback."""
    override = os.environ.get("TFT_PUMP")
    if override == "python":
        return None
    if override:
        # An explicit override that can't run must fail loudly, not
        # silently degrade to the slower fallback engine.
        if not os.access(override, os.X_OK):
            raise RuntimeError(f"TFT_PUMP={override} is not an executable file")
        return override
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    candidate = os.path.join(repo, "native", "build", "tft-pump")
    return candidate if os.access(candidate, os.X_OK) else None


# -- TCP stream (iperf-tcp / netperf-tcp-stream) ------------------------------


def tcp_stream_server(bind_ip: str, port: int, duration: float) -> None:
    s = socket.socket(_family(bind_ip))
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((bind_ip, port))
    s.listen(1)
    s.settimeout(duration + 30)
    conn, _ = s.accept()
    conn.settimeout(10)
    total = 0
    start = None
    buf = bytearray(BUF)  # preallocated: recv_into avoids per-read allocation
    try:
        while True:
            n = conn.recv_into(buf)
            if not n:
                break
            if start is None:
                start = time.perf_counter()
            total += n
    except socket.timeout:
        pass
    elapsed = (time.perf_counter() - start) if start else 0.0
    gbps = (total * 8 / elapsed / 1e9) if elapsed else 0.0
    _emit(type="tcp-stream", bytes=total, seconds=round(elapsed, 3), gbps=round(gbps, 3))


def tcp_stream_client(server_ip: str, port: int, duration: float) -> None:
    conn = _dial(server_ip, port)
    payload = b"\x5a" * BUF
    end = time.perf_counter() + duration
    total = 0
    while time.perf_counter() < end:
        conn.sendall(payload)
        total += len(payload)
    conn.close()
    _emit(type="tcp-stream-client", bytes=total)


# -- UDP stream (iperf-udp) ---------------------------------------------------


def udp_server(bind_ip: str, port: int, duration: float) -> None:
    s = socket.socket(_family(bind_ip), socket.SOCK_DGRAM)
    s.bind((bind_ip, port))
    s.settimeout(duration + 30)
    total = 0
    pkts = 0
    start = None
    try:
        while True:
            data, _ = s.recvfrom(UDP_PAYLOAD)
            if data == b"FIN":
                break
            if start is None:
                start = time.perf_counter()
                s.settimeout(duration + 5)
            total += len(data)
            pkts += 1
    except socket.timeout:
        pass
    elapsed = (time.perf_counter() - start) if start else 0.0
    gbps = (total * 8 / elapsed / 1e9) if elapsed else 0.0
    _emit(
        type="udp", bytes=total, packets=pkts, seconds=round(elapsed, 3),
        gbps=round(gbps, 3),
    )


def udp_client(server_ip: str, port: int, duration: float) -> None:
    s = socket.socket(_family(server_ip), socket.SOCK_DGRAM)
    payload = b"\x5a" * UDP_PAYLOAD
    end = time.perf_counter() + duration
    total = 0
    while time.perf_counter() < end:
        s.sendto(payload, (server_ip, port))
        total += len(payload)
    for _ in range(5):
        s.sendto(b"FIN", (server_ip, port))
    _emit(type="udp-client", bytes=total)


# -- TCP request/response (netperf-tcp-rr) ------------------------------------


def tcp_rr_server(bind_ip: str, port: int, duration: float) -> None:
    s = socket.socket(_family(bind_ip))
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((bind_ip, port))
    s.listen(1)
    s.settimeout(duration + 30)
    conn, _ = s.accept()
    conn.settimeout(10)
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    n = 0
    try:
        while True:
            data = conn.recv(RR_PAYLOAD)
            if not data:
                break
            conn.sendall(data)
            n += 1
    except socket.timeout:
        pass
    _emit(type="tcp-rr-server", transactions=n)


def tcp_rr_client(server_ip: str, port: int, duration: float) -> None:
    conn = _dial(server_ip, port)
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    end = time.perf_counter() + duration
    start = time.perf_counter()
    n = 0
    while time.perf_counter() < end:
        conn.sendall(b"\x5a")
        if not conn.recv(RR_PAYLOAD):
            break
        n += 1
    elapsed = time.perf_counter() - start
    conn.close()
    tps = n / elapsed if elapsed else 0.0
    _emit(
        type="tcp-rr", transactions=n, seconds=round(elapsed, 3),
        tps=round(tps, 1), mean_rtt_us=round(elapsed / n * 1e6, 1) if n else None,
    )


def _dial(ip: str, port: int, timeout: float = 15.0):
    deadline = time.monotonic() + timeout
    while True:
        try:
            return socket.create_connection((ip, port), timeout=5)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


ENGINES = {
    ("server", "iperf-tcp"): tcp_stream_server,
    ("client", "iperf-tcp"): tcp_stream_client,
    ("server", "netperf-tcp-stream"): tcp_stream_server,
    ("client", "netperf-tcp-stream"): tcp_stream_client,
    ("server", "iperf-udp"): udp_server,
    ("client", "iperf-udp"): udp_client,
    ("server", "netperf-tcp-rr"): tcp_rr_server,
    ("client", "netperf-tcp-rr"): tcp_rr_client,
}


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    pump = find_pump()
    if pump is not None:
        os.execv(pump, [pump] + list(argv))  # no interpreter in the loop
    role, typ, ip, port, duration = (
        argv[0], argv[1], argv[2], int(argv[3]), float(argv[4]),
    )
    ENGINES[(role, typ)](ip, port, duration)
    return 0


if __name__ == "__main__":
    sys.exit(main())
