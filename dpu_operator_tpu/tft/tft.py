"""Traffic-flow test runner.

Config shape follows the reference's ocp-tft-config.yaml: a `tft` list of
tests, each with connections of the four supported types, a duration,
and the secondary-network NAD to ride. Execution here targets two pod
network namespaces (local mode — what the zero-hardware tier and the
single-TPU-VM deployment use); each endpoint runs an engine subprocess
via `ip netns exec`, mirroring how the reference execs iperf in pods."""

from __future__ import annotations

import json
import logging
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

SUPPORTED_TYPES = (
    "iperf-tcp",
    "iperf-udp",
    "netperf-tcp-stream",
    "netperf-tcp-rr",
)
BASE_PORT = 20100


@dataclass
class ConnectionSpec:
    name: str
    type: str
    instances: int = 1

    def __post_init__(self):
        if self.type not in SUPPORTED_TYPES:
            raise ValueError(
                f"connection {self.name}: unsupported type {self.type}; "
                f"supported: {', '.join(SUPPORTED_TYPES)}"
            )


@dataclass
class TestSpec:
    name: str
    namespace: str = "default"
    duration: float = 30.0
    connections: List[ConnectionSpec] = field(default_factory=list)
    secondary_network_nad: str = "default-ici-net"
    # Case selection, reference grammar ("1", "1-9,15-19") — consumed by
    # run_case_matrix; plain run_suite measures whatever endpoints the
    # caller built (the self-contained CNI-backed pair).
    test_cases: str = "1"


def load_config(path: str) -> List[TestSpec]:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    tests = []
    for t in doc.get("tft", []):
        conns = []
        nad = None
        for c in t.get("connections", []):
            conns.append(
                ConnectionSpec(
                    name=c.get("name", "conn"),
                    type=c.get("type", "iperf-tcp"),
                    instances=int(c.get("instances", 1)),
                )
            )
            nad = nad or c.get("secondary_network_nad")
        tests.append(
            TestSpec(
                name=t.get("name", "test"),
                namespace=t.get("namespace", "default"),
                duration=float(t.get("duration", 30)),
                connections=conns,
                secondary_network_nad=nad or "default-ici-net",
                test_cases=str(t.get("test_cases", "1")),
            )
        )
    return tests


def _netns_cmd(netns: Optional[str], args: List[str]) -> List[str]:
    return (["ip", "netns", "exec", netns] if netns else []) + args


def run_connection(
    conn: ConnectionSpec,
    server_netns: Optional[str],
    client_netns: Optional[str],
    server_ip: str,
    duration: float,
    port: int = BASE_PORT,
    connect_ip: Optional[str] = None,
    connect_port: Optional[int] = None,
) -> dict:
    """One connection: server engine in the server netns, client engine in
    the client netns, collect the server-side result line. When a service
    fronts the server (clusterIP/nodePort cases), the client dials
    connect_ip/connect_port instead of the server's bind address."""
    eng = [sys.executable, "-m", "dpu_operator_tpu.tft.engine"]
    server = subprocess.Popen(
        _netns_cmd(server_netns, eng + ["server", conn.type, server_ip, str(port), str(duration)]),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    time.sleep(0.3)
    client = subprocess.Popen(
        _netns_cmd(client_netns, eng + [
            "client", conn.type, connect_ip or server_ip,
            str(connect_port if connect_port is not None else port),
            str(duration)]),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    budget = duration + 60
    try:
        c_out, c_err = client.communicate(timeout=budget)
        s_out, s_err = server.communicate(timeout=budget)
    finally:
        for p in (client, server):
            if p.poll() is None:
                p.kill()
    if server.returncode != 0:
        raise RuntimeError(f"server engine failed: {s_err}")
    if client.returncode != 0:
        raise RuntimeError(f"client engine failed: {c_err}")
    server_result = json.loads(s_out.strip().splitlines()[-1])
    client_result = json.loads(c_out.strip().splitlines()[-1])
    # RR results are measured client-side (transactions/sec), stream/udp
    # server-side (goodput) — same split the reference tools use.
    result = client_result if conn.type == "netperf-tcp-rr" else server_result
    return {"connection": conn.name, "type": conn.type, **result}


def _run_test_connections(
    t: TestSpec,
    server_netns: Optional[str],
    client_netns: Optional[str],
    server_ip: str,
    duration_override: Optional[float],
    port: int,
    tags: Optional[Dict] = None,
    connect_ip: Optional[str] = None,
    port_offset: int = 0,
) -> Tuple[List[dict], int]:
    """One test's connections × instances against one endpoint pair —
    the execution loop run_suite and run_case_matrix share. Returns
    (results, next free port)."""
    results = []
    label = (" ".join(f"{k}={v}" for k, v in tags.items()) + " ") if tags else ""
    for conn in t.connections:
        for i in range(conn.instances):
            port += 1
            d = duration_override if duration_override is not None else t.duration
            log.info("tft: %s%s / %s instance %d (%.1fs)",
                     label, t.name, conn.name, i, d)
            r = run_connection(conn, server_netns, client_netns, server_ip, d,
                               port, connect_ip=connect_ip,
                               connect_port=port + port_offset)
            r["test"] = t.name
            if tags:
                r.update(tags)
            results.append(r)
    return results, port


def run_suite(
    tests: List[TestSpec],
    server_netns: Optional[str],
    client_netns: Optional[str],
    server_ip: str,
    duration_override: Optional[float] = None,
) -> List[dict]:
    results = []
    port = BASE_PORT
    for t in tests:
        rs, port = _run_test_connections(
            t, server_netns, client_netns, server_ip, duration_override, port)
        results.extend(rs)
    return results


def run_case_matrix(
    tests: List[TestSpec],
    duration_override: Optional[float] = None,
    cases_override: Optional[str] = None,
) -> List[dict]:
    """Run each test's connection list over every selected numbered case
    topology (tft/cases.py). Locally-unsupported cases are reported as
    skipped entries with the reason — selection is never silently
    narrowed."""
    from .cases import CASES, build_case_topology, case_reason, parse_cases

    results = []
    port = BASE_PORT + 500  # clear of run_suite's range
    for t in tests:
        for cid in parse_cases(cases_override or t.test_cases):
            case_name = CASES[cid][0]
            reason = case_reason(cid)
            if reason is not None:
                log.info("tft: case %d (%s) skipped: %s", cid, case_name, reason)
                results.append({
                    "test": t.name, "case": cid, "case_name": case_name,
                    "skipped": reason,
                })
                continue
            # NodePort cases program exact per-port DNAT pairs, so the
            # topology gets the engine port range up front.
            span = sum(c.instances for c in t.connections)
            topo = build_case_topology(cid, port_base=port + 1,
                                       port_span=span)
            try:
                rs, port = _run_test_connections(
                    t, topo.server_netns, topo.client_netns, topo.server_ip,
                    duration_override, port,
                    tags={"case": cid, "case_name": case_name, **topo.tags},
                    connect_ip=topo.connect_ip,
                    port_offset=topo.port_offset)
                results.extend(rs)
            finally:
                topo.cleanup()
    return results


def print_results(results: List[dict], file=None) -> None:
    file = file or sys.stdout
    for r in results:
        case = f' case {r["case"]:>2} {r["case_name"]:<26}' if "case" in r else ""
        if "gbps" in r:
            line = (f'{r["test"]:<10}{case} {r["connection"]:<14} '
                    f'{r["type"]:<20} {r["gbps"]:>9.3f} Gbps')
        elif "tps" in r:
            line = (f'{r["test"]:<10}{case} {r["connection"]:<14} '
                    f'{r["type"]:<20} {r["tps"]:>9.1f} tps')
        elif "skipped" in r:
            line = f'{r["test"]:<10}{case} SKIPPED: {r["skipped"]}'
        else:
            line = json.dumps(r)
        print(line, file=file)
    print(json.dumps({"tft_results": results}), file=file)
