"""CLI: python -m dpu_operator_tpu.tft <config.yaml> [--duration D]
       [--self-contained | --server-netns NS --client-netns NS --server-ip IP]

Counterpart of hack/traffic_flow_tests.sh + tft.py in the reference's
kubernetes-traffic-flow-tests submodule. --self-contained stands up the
whole local slice (tpuvsp + fabric bridge + two CNI-attached netns) and
measures through it — the mode `hack/traffic_flow_tests.sh` uses on a
single TPU-VM node."""

from __future__ import annotations

import argparse
import logging
import subprocess
import sys
import uuid

from .tft import load_config, print_results, run_suite


def _self_contained_run(tests, duration):
    import socket as socketlib
    import tempfile

    from ..cni import CniRequest, do_cni
    from ..daemon import GrpcPlugin
    from ..daemon.converged_side import ConvergedSideManager
    from ..parallel import SliceTopology
    from ..utils import PathManager
    from ..vsp import VspServer
    from ..vsp.tpu_dataplane import TpuFabricDataplane
    from ..vsp.tpu_vsp import TpuVsp

    with socketlib.socket() as s:
        s.bind(("127.0.0.1", 0))
        opi_port = s.getsockname()[1]

    root = tempfile.mkdtemp(prefix="dpu-tft-")
    pm = PathManager(root=root)
    bridge = "brTFT" + uuid.uuid4().hex[:6]
    vsp = TpuVsp(
        topology=SliceTopology.single_chip(),
        dataplane=TpuFabricDataplane(bridge=bridge),
        opi_port=opi_port,
    )
    vsp_server = VspServer(vsp, pm)
    vsp_server.start()
    manager = ConvergedSideManager(
        GrpcPlugin(pm.vendor_plugin_socket()),
        "tft-local",
        path_manager=pm,
        register_device_plugin=False,
    )
    namespaces, reqs, ips = [], [], []
    nad = tests[0].secondary_network_nad if tests else "default-ici-net"
    conf = {"cniVersion": "1.0.0", "name": nad, "type": "dpu-cni"}
    try:
        manager.start_vsp()
        manager.setup_devices()
        manager.listen()
        manager.serve()
        sock = manager.cni_server.socket_path
        for i in range(2):
            ns = f"tft{i}-" + uuid.uuid4().hex[:6]
            subprocess.run(["ip", "netns", "add", ns], check=True)
            namespaces.append(ns)
            req = CniRequest(
                command="ADD",
                container_id=f"tftc{i}" + uuid.uuid4().hex[:10],
                netns=ns,
                ifname="net1",
                config=conf,
            )
            reqs.append(req)
            result = do_cni(sock, req)
            ips.append(result["ips"][0]["address"].split("/")[0])
        return run_suite(
            tests,
            server_netns=namespaces[1],
            client_netns=namespaces[0],
            server_ip=ips[1],
            duration_override=duration,
        )
    finally:
        try:
            sock = manager.cni_server.socket_path
            for req in reqs:
                do_cni(sock, CniRequest(
                    command="DEL", container_id=req.container_id,
                    netns=req.netns, ifname="net1", config=conf,
                ))
        except Exception:
            pass
        for ns in namespaces:
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)
        for stop in (manager.stop, vsp_server.stop):
            try:
                stop()
            except Exception:
                logging.getLogger(__name__).exception("tft teardown step failed")
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)
        import shutil

        shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(prog="tft")
    ap.add_argument("config")
    ap.add_argument("--duration", type=float, default=None)
    ap.add_argument("--self-contained", action="store_true")
    ap.add_argument("--case-matrix", action="store_true",
                    help="run the numbered endpoint-topology case matrix "
                         "(config test_cases / --cases selection)")
    ap.add_argument("--cases", default=None,
                    help='case selection override, e.g. "1-26" (all '
                         'cases run locally where the kernel offers '
                         'nf_tables NAT; service cases skip with the '
                         'probe reason otherwise) or the reference\'s '
                         '"1-9,15-19"')
    ap.add_argument("--server-netns")
    ap.add_argument("--client-netns")
    ap.add_argument("--server-ip")
    args = ap.parse_args(argv)
    if args.cases and not args.case_matrix:
        ap.error("--cases only selects topologies in --case-matrix mode")

    tests = load_config(args.config)
    if args.case_matrix:
        from .tft import run_case_matrix

        results = run_case_matrix(
            tests, duration_override=args.duration,
            cases_override=args.cases)
    elif args.self_contained:
        results = _self_contained_run(tests, args.duration)
    else:
        if not args.server_ip:
            ap.error("--server-ip required unless --self-contained")
        results = run_suite(
            tests, args.server_netns, args.client_netns, args.server_ip,
            duration_override=args.duration,
        )
    print_results(results)
    return 0


if __name__ == "__main__":
    sys.exit(main())
