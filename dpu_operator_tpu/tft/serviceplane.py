"""Kube-proxy-style service plane over nft NAT — the cluster half of the
traffic-flow matrix.

The reference's clusterIP/nodePort cases (5-14, 19-24 of the upstream
kubernetes-traffic-flow-tests numbering; its supported selection
"1-9,15-19" includes 5-9 — /root/reference/hack/cluster-configs/
ocp-tft-config.yaml:3-6) ride a real cluster's kube-proxy. This module
realises the same dataplane locally with the repo's own raw-netlink
nf_tables codec (cni/nftnl.py): DNAT rules on the node's prerouting
(pod-originated) and output (host-originated) hooks, plus masquerade on
postrouting so hairpinned flows stay symmetric through the node's
conntrack — exactly the rule shapes kube-proxy's iptables/nftables mode
programs, built here with zero userspace tooling.

Flow anatomy (clusterIP, pod client):
    pod 10.94.0.11 → VIP 10.96.0.10        (off-subnet → default gw)
    node prerouting: dnat → backend .12    (addr-only: port==targetPort)
    node postrouting: masq → src 10.94.0.1 (reply must re-enter conntrack,
                                            not short-circuit over L2)
    backend reply → node → de-NAT both ways → pod sees VIP as the peer

NodePort adds the port-rewrite shape: nodeIP:30NNN → backend:20NNN, one
rule per port pair (the harness's per-connection ports are known at
topology-build time, so the rules are exact, not wildcards).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..cni import nftnl as nf

_PROTO_NUM = {"tcp": 6, "udp": 17}


class ServicePlane:
    """One node's service NAT rules, one nft table per instance (per-case
    tag keeps concurrent topologies disjoint); close() drops the table
    and every rule with it — cleanup is one transaction."""

    def __init__(self, tag: str, v6: bool = False):
        self.v6 = v6
        self.table = ("dpusvc6" if v6 else "dpusvc") + tag
        self._masqueraded: set = set()
        self._nft = nf.Nft(
            family=nf.NFPROTO_IPV6 if v6 else nf.NFPROTO_IPV4)
        ok = False
        try:
            self._nft.ensure_table(self.table)
            self._nft.ensure_nat_chain(
                self.table, "prerouting", nf.NF_INET_PRE_ROUTING, -100)
            self._nft.ensure_nat_chain(
                self.table, "output", nf.NF_INET_LOCAL_OUT, -100)
            self._nft.ensure_nat_chain(
                self.table, "postrouting", nf.NF_INET_POST_ROUTING, 100)
            ok = True
        finally:
            if not ok:
                # Half-initialised planes must not strand a table (and
                # best-effort teardown must not mask the real error).
                try:
                    self.close()
                except Exception:
                    pass

    # -- match helpers --------------------------------------------------------

    def _daddr_match(self, ip: str) -> List[bytes]:
        import socket as _s

        if self.v6:
            return [nf.payload_load(nf.NFT_PAYLOAD_NETWORK_HEADER, 24, 16),
                    nf.cmp_eq(_s.inet_pton(_s.AF_INET6, ip))]
        return [nf.payload_load(nf.NFT_PAYLOAD_NETWORK_HEADER, 16, 4),
                nf.cmp_eq(_s.inet_aton(ip))]

    @staticmethod
    def _l4_match(proto: str, dport: Optional[int]) -> List[bytes]:
        import struct as _st

        exprs = [nf.meta_l4proto(), nf.cmp_eq(bytes([_PROTO_NUM[proto]]))]
        if dport is not None:
            exprs += [nf.payload_load(nf.NFT_PAYLOAD_TRANSPORT_HEADER, 2, 2),
                      nf.cmp_eq(_st.pack(">H", dport))]
        return exprs

    def _dnat_rule(self, frontend_ip: str, frontend_port: Optional[int],
                   backend_ip: str, backend_port: Optional[int],
                   proto: str) -> None:
        exprs = (self._l4_match(proto, frontend_port)
                 + self._daddr_match(frontend_ip)
                 + nf.dnat_to(backend_ip, backend_port))
        # Both origination paths: prerouting catches pod/fabric clients,
        # output catches the node's own (host) clients.
        for chain in ("prerouting", "output"):
            self._nft.add_rule(self.table, chain, exprs)

    # -- service shapes -------------------------------------------------------

    def add_clusterip(self, vip: str, backend_ip: str,
                      protos: Iterable[str] = ("tcp", "udp")) -> None:
        """VIP → backend, any port (the k8s port==targetPort shape, one
        rule per protocol like kube-proxy's per-protocol service ports)."""
        for proto in protos:
            self._dnat_rule(vip, None, backend_ip, None, proto)
        self.add_masquerade_to(backend_ip)

    def add_nodeport(self, node_ip: str, node_port: int, backend_ip: str,
                     backend_port: int,
                     protos: Iterable[str] = ("tcp", "udp")) -> None:
        """nodeIP:nodePort → backend:targetPort — the port-rewrite shape."""
        for proto in protos:
            self._dnat_rule(node_ip, node_port, backend_ip, backend_port,
                            proto)

    def add_masquerade_to(self, dest_ip: str) -> None:
        """Masquerade flows headed to `dest_ip` (post-DNAT daddr): forces
        replies back through this node's conntrack instead of letting a
        same-subnet backend answer the client directly with its own
        (un-de-NATted) address. Idempotent per destination."""
        if dest_ip in self._masqueraded:
            return
        self._masqueraded.add(dest_ip)
        self._nft.add_rule(self.table, "postrouting",
                           self._daddr_match(dest_ip) + [nf.masq()])

    def close(self) -> None:
        try:
            self._nft.delete_table(self.table)
        finally:
            self._nft.close()
