"""Numbered traffic-flow test cases — the endpoint-topology matrix.

The reference's config selects cases by number with a range grammar
(`test_cases: "1"`, "1-9,15-19" — /root/reference/hack/cluster-configs/
ocp-tft-config.yaml:4-5) against the kubernetes-traffic-flow-tests
matrix of {pod, host} × {pod, host, clusterIP, nodePort} × {same node,
different node} endpoints. This module carries that numbering and maps
each case onto a locally-realisable topology:

  * pod endpoints    — a network namespace attached to the fabric bridge
  * host endpoints   — the node's root namespace, addressed on the
                       bridge device itself (how a host reaches the
                       fabric without a pod sandbox)
  * same node        — one bridge
  * different node   — two bridges joined by a veth uplink pair, the
                       two-"node" fabric emulation (same L2 domain, the
                       flat-ICI shape; traffic really crosses
                       bridge A -> uplink -> bridge B)
  * clusterIP/nodePort/external cases — need a cluster service plane (or
    an off-fabric external host); reported as SKIPPED with the reason,
    never silently dropped.

The case grammar parser accepts exactly the reference's forms:
"1", "1,3,17", "1-9,15-19".
"""

from __future__ import annotations

import subprocess
import uuid
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

# (case id) -> (name, client_kind, server_kind, same_node) or an
# unsupported-locally reason. Numbering follows the upstream
# kubernetes-traffic-flow-tests TestCaseType convention the reference
# selects from ("1-9,15-19" supported there).
_CLUSTER = "needs a cluster service plane (clusterIP/nodePort) — run on a real cluster via make kind-test"
_EXTERNAL = "needs an off-fabric external host — covered by tests/test_e2e.py external scenarios"

CASES = {
    1: ("pod-to-pod-same-node", "pod", "pod", True),
    2: ("pod-to-pod-diff-node", "pod", "pod", False),
    3: ("pod-to-host-same-node", "pod", "host", True),
    4: ("pod-to-host-diff-node", "pod", "host", False),
    5: ("pod-to-clusterip-to-pod-same-node", _CLUSTER),
    6: ("pod-to-clusterip-to-pod-diff-node", _CLUSTER),
    7: ("pod-to-clusterip-to-host-same-node", _CLUSTER),
    8: ("pod-to-clusterip-to-host-diff-node", _CLUSTER),
    9: ("pod-to-nodeport-to-pod-same-node", _CLUSTER),
    10: ("pod-to-nodeport-to-pod-diff-node", _CLUSTER),
    11: ("pod-to-nodeport-to-host-same-node", _CLUSTER),
    12: ("pod-to-nodeport-to-host-diff-node", _CLUSTER),
    13: ("pod-to-nodeport-to-host-same-node-v6", _CLUSTER),
    14: ("pod-to-nodeport-to-host-diff-node-v6", _CLUSTER),
    15: ("host-to-host-same-node", "host", "host", True),
    16: ("host-to-host-diff-node", "host", "host", False),
    17: ("host-to-pod-same-node", "host", "pod", True),
    18: ("host-to-pod-diff-node", "host", "pod", False),
    19: ("host-to-clusterip-to-pod-same-node", _CLUSTER),
    20: ("host-to-clusterip-to-pod-diff-node", _CLUSTER),
    21: ("host-to-clusterip-to-host-same-node", _CLUSTER),
    22: ("host-to-clusterip-to-host-diff-node", _CLUSTER),
    23: ("host-to-nodeport-to-pod-same-node", _CLUSTER),
    24: ("host-to-nodeport-to-pod-diff-node", _CLUSTER),
    25: ("pod-to-external", _EXTERNAL),
    26: ("host-to-external", _EXTERNAL),
}


def parse_cases(spec: str) -> List[int]:
    """The reference's selection grammar: '1', '1,3,17', '1-9,15-19'."""
    out: List[int] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, _, hi_s = part.partition("-")
            lo, hi = int(lo_s), int(hi_s)
            if lo > hi:
                raise ValueError(f"test_cases range {part!r}: {lo} > {hi}")
            out.extend(range(lo, hi + 1))
        else:
            out.append(int(part))
    unknown = [c for c in out if c not in CASES]
    if unknown:
        raise ValueError(f"unknown test case id(s) {unknown}; known: 1-26")
    if not out:
        # A perf matrix silently measuring nothing is the worst outcome.
        raise ValueError(f"test_cases {spec!r} selects no cases")
    # De-dup preserving order.
    seen: set = set()
    return [c for c in out if not (c in seen or seen.add(c))]


def case_reason(case_id: int) -> Optional[str]:
    """The skip reason for locally-unsupported cases, else None."""
    entry = CASES[case_id]
    return entry[1] if len(entry) == 2 else None


@dataclass
class CaseTopology:
    """Built endpoints for one case: netns of None means the root
    namespace (host endpoint)."""
    case_id: int
    name: str
    client_netns: Optional[str]
    server_netns: Optional[str]
    server_ip: str
    _cleanups: List[Callable[[], None]] = field(default_factory=list)

    def cleanup(self) -> None:
        for fn in reversed(self._cleanups):
            try:
                fn()
            except Exception:
                pass


def _run(args: List[str]) -> None:
    subprocess.run(args, check=True, capture_output=True)


def _fabric_mtu() -> int:
    """Case topologies carry the same frame-size policy the shipped
    dataplane applies (utils/mtu.py) — a 1500-byte test topology would
    measure a fabric the CNI never builds."""
    from ..utils.mtu import resolve_fabric_mtu

    return resolve_fabric_mtu()


def _pod(ns: str, host_if: str, pod_if: str, bridge: str, ip: str,
         cleanups: List, mtu: int) -> None:
    _run(["ip", "netns", "add", ns])
    cleanups.append(lambda: subprocess.run(
        ["ip", "netns", "del", ns], capture_output=True))
    _run(["ip", "link", "add", host_if, "mtu", str(mtu),
          "type", "veth", "peer", "name", pod_if, "mtu", str(mtu)])
    _run(["ip", "link", "set", pod_if, "netns", ns])
    _run(["ip", "link", "set", host_if, "master", bridge])
    _run(["ip", "link", "set", host_if, "up"])
    _run(["ip", "-n", ns, "link", "set", pod_if, "up"])
    _run(["ip", "-n", ns, "addr", "add", f"{ip}/24", "dev", pod_if])


def build_case_topology(case_id: int) -> CaseTopology:
    """Stand up the case's endpoint topology with a unique name tag;
    raises ValueError for locally-unsupported cases (use case_reason
    first to report a skip instead)."""
    reason = case_reason(case_id)
    if reason is not None:
        raise ValueError(f"case {case_id} unsupported locally: {reason}")
    name, client_kind, server_kind, same_node = CASES[case_id]
    tag = uuid.uuid4().hex[:5]
    cleanups: List = []
    try:
        return _build(case_id, name, client_kind, server_kind, same_node,
                      tag, cleanups)
    except Exception:
        # A half-built topology must not leak bridges/netns on the host.
        for fn in reversed(cleanups):
            try:
                fn()
            except Exception:
                pass
        raise


def _build(case_id: int, name: str, client_kind: str, server_kind: str,
           same_node: bool, tag: str, cleanups: List) -> CaseTopology:
    mtu = _fabric_mtu()

    bridge_a = "bta" + tag
    _run(["ip", "link", "add", bridge_a, "mtu", str(mtu), "type", "bridge"])
    cleanups.append(lambda: subprocess.run(
        ["ip", "link", "del", bridge_a], capture_output=True))
    _run(["ip", "link", "set", bridge_a, "up"])

    if same_node:
        bridge_b = bridge_a
    else:
        # "Node B" = a second bridge, fabric-linked to node A by a veth
        # uplink pair — cross-node traffic really transits both bridges.
        bridge_b = "btb" + tag
        _run(["ip", "link", "add", bridge_b, "mtu", str(mtu),
              "type", "bridge"])
        cleanups.append(lambda: subprocess.run(
            ["ip", "link", "del", bridge_b], capture_output=True))
        _run(["ip", "link", "set", bridge_b, "up"])
        up_a, up_b = "bua" + tag, "bub" + tag
        _run(["ip", "link", "add", up_a, "mtu", str(mtu),
              "type", "veth", "peer", "name", up_b, "mtu", str(mtu)])
        cleanups.append(lambda: subprocess.run(
            ["ip", "link", "del", up_a], capture_output=True))
        _run(["ip", "link", "set", up_a, "master", bridge_a])
        _run(["ip", "link", "set", up_b, "master", bridge_b])
        _run(["ip", "link", "set", up_a, "up"])
        _run(["ip", "link", "set", up_b, "up"])

    # Address plan: hosts .1/.2, pods .11/.12 — one flat /24, the
    # flat-ICI L2 shape.
    endpoints = {}  # role -> (netns or None, ip)
    for role, kind, bridge, host_ip, pod_ip, idx in (
        ("client", client_kind, bridge_a, "10.94.0.1", "10.94.0.11", 0),
        ("server", server_kind, bridge_b, "10.94.0.2", "10.94.0.12", 1),
    ):
        if kind == "host" and role == "server" and not same_node:
            # "Node B's root namespace": a host endpoint in the SAME
            # (test-runner) netns as the client would satisfy the local
            # route table and short-circuit over loopback, never touching
            # the fabric. A remote node's root ns is a different ns, so
            # model it as one — its fabric interface rides bridge B.
            ns = f"tn{idx}{tag}"
            _pod(ns, f"th{idx}{tag}", f"tp{idx}{tag}", bridge, host_ip,
                 cleanups, mtu)
            endpoints[role] = (ns, host_ip)
        elif kind == "host":
            _run(["ip", "addr", "add", f"{host_ip}/24", "dev", bridge])
            cleanups.append(lambda b=bridge, ip=host_ip: subprocess.run(
                ["ip", "addr", "del", f"{ip}/24", "dev", b],
                capture_output=True))
            endpoints[role] = (None, host_ip)
        else:
            ns = f"tc{idx}{tag}"
            _pod(ns, f"th{idx}{tag}", f"tp{idx}{tag}", bridge, pod_ip,
                 cleanups, mtu)
            endpoints[role] = (ns, pod_ip)

    return CaseTopology(
        case_id=case_id,
        name=name,
        client_netns=endpoints["client"][0],
        server_netns=endpoints["server"][0],
        server_ip=endpoints["server"][1],
        _cleanups=cleanups,
    )
