"""Numbered traffic-flow test cases — the endpoint-topology matrix.

The reference's config selects cases by number with a range grammar
(`test_cases: "1"`, "1-9,15-19" — /root/reference/hack/cluster-configs/
ocp-tft-config.yaml:4-5) against the kubernetes-traffic-flow-tests
matrix of {pod, host} × {pod, host, clusterIP, nodePort, external} ×
{same node, different node} endpoints. This module carries that
numbering and maps EVERY case onto a locally-realisable topology:

  * pod endpoints    — a network namespace attached to the fabric bridge
  * host endpoints   — the node's root namespace, addressed on the
                       bridge device itself (how a host reaches the
                       fabric without a pod sandbox)
  * same node        — one bridge
  * different node   — two bridges joined by a veth uplink pair, the
                       two-"node" fabric emulation (same L2 domain, the
                       flat-ICI shape; traffic really crosses
                       bridge A -> uplink -> bridge B)
  * clusterIP/nodePort — a kube-proxy-style NAT service plane programmed
    through the repo's own raw-netlink nf_tables codec
    (tft/serviceplane.py over cni/nftnl.py): DNAT on the node's
    prerouting/output hooks, masquerade on postrouting. The client
    targets the VIP (or nodeIP:nodePort) and the flow really transits
    the node's conntrack both ways. v6 flavours ride an ip6-family
    table over the fabric's ULA prefix.
  * external         — an off-fabric namespace behind a routed (not
    bridged) veth on its own subnet; pod egress masquerades through the
    node, the classic SNAT egress path.

On kernels without nf_tables NAT the service cases degrade to explicit
SKIPPED rows with the reason (probed once, never silently dropped).

Case 15 (host-to-host-same-node) note: both endpoints are root-netns
addresses, so the kernel local-routes the flow over loopback — exactly
what two host-network endpoints on one real node do. The result row is
tagged `path: local-route` so the number is never mistaken for a bridge
measurement (the diff-node variant, case 16, crosses the fabric).

The case grammar parser accepts exactly the reference's forms:
"1", "1,3,17", "1-9,15-19".
"""

from __future__ import annotations

import subprocess
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# Service-plane address plan: fabric pods/hosts live in 10.94.0.0/24
# (fd00:5e::/64), services in the 10.96.0.0/16 clusterIP convention,
# external hosts on their own routed subnet.
VIP = "10.96.0.10"
GW_IP = "10.94.0.1"
GW_IP6 = "fd00:5e::1"
HOST6 = {"10.94.0.1": "fd00:5e::1", "10.94.0.2": "fd00:5e::2"}
POD6 = {"10.94.0.11": "fd00:5e::11", "10.94.0.12": "fd00:5e::12"}
NODEPORT_OFFSET = 10000  # engine port 20xxx <-> nodePort 30xxx
EXT_NET = "192.168.77"

# (case id) -> (name, client_kind, server_kind, same_node, service).
# service: None | clusterip | nodeport | nodeport6 | external.
# Numbering follows the upstream kubernetes-traffic-flow-tests
# TestCaseType convention the reference selects from.
CASES = {
    1: ("pod-to-pod-same-node", "pod", "pod", True, None),
    2: ("pod-to-pod-diff-node", "pod", "pod", False, None),
    3: ("pod-to-host-same-node", "pod", "host", True, None),
    4: ("pod-to-host-diff-node", "pod", "host", False, None),
    5: ("pod-to-clusterip-to-pod-same-node", "pod", "pod", True, "clusterip"),
    6: ("pod-to-clusterip-to-pod-diff-node", "pod", "pod", False, "clusterip"),
    7: ("pod-to-clusterip-to-host-same-node", "pod", "host", True, "clusterip"),
    8: ("pod-to-clusterip-to-host-diff-node", "pod", "host", False, "clusterip"),
    9: ("pod-to-nodeport-to-pod-same-node", "pod", "pod", True, "nodeport"),
    10: ("pod-to-nodeport-to-pod-diff-node", "pod", "pod", False, "nodeport"),
    11: ("pod-to-nodeport-to-host-same-node", "pod", "host", True, "nodeport"),
    12: ("pod-to-nodeport-to-host-diff-node", "pod", "host", False, "nodeport"),
    13: ("pod-to-nodeport-to-host-same-node-v6", "pod", "host", True, "nodeport6"),
    14: ("pod-to-nodeport-to-host-diff-node-v6", "pod", "host", False, "nodeport6"),
    15: ("host-to-host-same-node", "host", "host", True, None),
    16: ("host-to-host-diff-node", "host", "host", False, None),
    17: ("host-to-pod-same-node", "host", "pod", True, None),
    18: ("host-to-pod-diff-node", "host", "pod", False, None),
    19: ("host-to-clusterip-to-pod-same-node", "host", "pod", True, "clusterip"),
    20: ("host-to-clusterip-to-pod-diff-node", "host", "pod", False, "clusterip"),
    21: ("host-to-clusterip-to-host-same-node", "host", "host", True, "clusterip"),
    22: ("host-to-clusterip-to-host-diff-node", "host", "host", False, "clusterip"),
    23: ("host-to-nodeport-to-pod-same-node", "host", "pod", True, "nodeport"),
    24: ("host-to-nodeport-to-pod-diff-node", "host", "pod", False, "nodeport"),
    25: ("pod-to-external", "pod", "external", False, "external"),
    26: ("host-to-external", "host", "external", False, "external"),
}


def parse_cases(spec: str) -> List[int]:
    """The reference's selection grammar: '1', '1,3,17', '1-9,15-19'."""
    out: List[int] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, _, hi_s = part.partition("-")
            lo, hi = int(lo_s), int(hi_s)
            if lo > hi:
                raise ValueError(f"test_cases range {part!r}: {lo} > {hi}")
            out.extend(range(lo, hi + 1))
        else:
            out.append(int(part))
    unknown = [c for c in out if c not in CASES]
    if unknown:
        raise ValueError(f"unknown test case id(s) {unknown}; known: 1-26")
    if not out:
        # A perf matrix silently measuring nothing is the worst outcome.
        raise ValueError(f"test_cases {spec!r} selects no cases")
    # De-dup preserving order.
    seen: set = set()
    return [c for c in out if not (c in seen or seen.add(c))]


_nat_probe: Dict[bool, Optional[str]] = {}


def _nat_unsupported(v6: bool) -> Optional[str]:
    """One cached kernel probe per family: can we create an ip/ip6 nat
    chain? Returns the skip reason when we can't (old kernel, missing
    nf_nat/conntrack, insufficient privilege), else None."""
    if v6 not in _nat_probe:
        from ..cni import nftnl as nf

        if v6:
            import os

            # ip6 nat chains can register even when the host has IPv6
            # runtime-disabled; the address plan would then fail at
            # `ip -6 addr add`. Skip honestly instead.
            if not os.path.exists("/proc/net/if_inet6"):
                _nat_probe[v6] = ("host has IPv6 runtime-disabled — "
                                  "v6 cases need an IPv6-capable node")
                return _nat_probe[v6]
        probe = "dpusvcprobe6" if v6 else "dpusvcprobe"
        try:
            with nf.Nft(family=nf.NFPROTO_IPV6 if v6
                        else nf.NFPROTO_IPV4) as nft:
                nft.ensure_table(probe)
                try:
                    nft.ensure_nat_chain(
                        probe, "pr", nf.NF_INET_PRE_ROUTING, -100)
                finally:
                    nft.delete_table(probe)
            _nat_probe[v6] = None
        except Exception as e:
            _nat_probe[v6] = (
                f"kernel/privilege lacks nf_tables {'ip6' if v6 else 'ip'} "
                f"NAT ({e}) — run on a real cluster via make kind-test")
    return _nat_probe[v6]


def case_reason(case_id: int) -> Optional[str]:
    """The skip reason for cases this environment can't realise, else
    None. All 26 cases run where nf_tables NAT is available (probed)."""
    service = CASES[case_id][4]
    if service in ("clusterip", "nodeport", "external"):
        return _nat_unsupported(v6=False)
    if service == "nodeport6":
        return _nat_unsupported(v6=True)
    return None


@dataclass
class CaseTopology:
    """Built endpoints for one case: netns of None means the root
    namespace (host endpoint). Clients dial connect_ip (the service VIP
    or nodeIP when a service fronts the server) at engine port +
    port_offset; servers bind server_ip at the engine port."""
    case_id: int
    name: str
    client_netns: Optional[str]
    server_netns: Optional[str]
    server_ip: str
    connect_ip: Optional[str] = None
    port_offset: int = 0
    tags: Dict[str, str] = field(default_factory=dict)
    _cleanups: List[Callable[[], None]] = field(default_factory=list)

    def cleanup(self) -> None:
        for fn in reversed(self._cleanups):
            try:
                fn()
            except Exception:
                pass


def _run(args: List[str]) -> None:
    subprocess.run(args, check=True, capture_output=True)


def _fabric_mtu() -> int:
    """Case topologies carry the same frame-size policy the shipped
    dataplane applies (utils/mtu.py) — a 1500-byte test topology would
    measure a fabric the CNI never builds."""
    from ..utils.mtu import resolve_fabric_mtu

    return resolve_fabric_mtu()


def _sysctl(path: str, value: str, cleanups: List,
            netns: Optional[str] = None) -> None:
    """Set a sysctl, restoring the prior value at cleanup (root-netns
    sysctls are global state the suite must hand back)."""
    cmd = ["ip", "netns", "exec", netns] if netns else []
    pre = subprocess.run(cmd + ["cat", path], capture_output=True, text=True)
    old = pre.stdout.strip()
    if netns is None and (pre.returncode != 0 or not old):
        # Without the prior value we cannot register a restore, and a
        # root-netns knob (ip_forward, bridge-nf-call-*) left flipped
        # outlives the suite. Refuse rather than silently leak state.
        raise RuntimeError(
            f"cannot read {path} before changing it "
            f"(rc={pre.returncode}, stderr={pre.stderr.strip()!r}); "
            f"refusing to set a root-netns sysctl with no restore value")
    _run(cmd + ["sh", "-c", f"echo {value} > {path}"])
    if old and old != value and netns is None:
        cleanups.append(lambda: subprocess.run(
            ["sh", "-c", f"echo {old} > {path}"], capture_output=True))


def _pod(ns: str, host_if: str, pod_if: str, bridge: str, ip: str,
         cleanups: List, mtu: int, ip6: Optional[str] = None,
         gw: Optional[str] = None, gw6: Optional[str] = None) -> None:
    _run(["ip", "netns", "add", ns])
    cleanups.append(lambda: subprocess.run(
        ["ip", "netns", "del", ns], capture_output=True))
    _run(["ip", "link", "add", host_if, "mtu", str(mtu),
          "type", "veth", "peer", "name", pod_if, "mtu", str(mtu)])
    _run(["ip", "link", "set", pod_if, "netns", ns])
    _run(["ip", "link", "set", host_if, "master", bridge])
    _run(["ip", "link", "set", host_if, "up"])
    _run(["ip", "-n", ns, "link", "set", pod_if, "up"])
    _run(["ip", "-n", ns, "addr", "add", f"{ip}/24", "dev", pod_if])
    if ip6:
        _run(["ip", "-n", ns, "-6", "addr", "add", f"{ip6}/64",
              "dev", pod_if, "nodad"])
    if gw:
        _run(["ip", "-n", ns, "route", "add", "default", "via", gw])
        # A router hairpinning a flow back out its ingress interface
        # emits ICMP redirects; a client that honours one would bypass
        # the NAT mid-flow. Pods ignore them (netns dies with cleanup).
        _sysctl("/proc/sys/net/ipv4/conf/all/accept_redirects", "0",
                cleanups, netns=ns)
    if gw6:
        _run(["ip", "-n", ns, "-6", "route", "add", "default", "via", gw6])
        _sysctl("/proc/sys/net/ipv6/conf/all/accept_redirects", "0",
                cleanups, netns=ns)


def build_case_topology(case_id: int, port_base: int = 0,
                        port_span: int = 0) -> CaseTopology:
    """Stand up the case's endpoint topology with a unique name tag.
    NodePort cases program exact per-port DNAT pairs, so callers must
    pass the engine port range ([port_base, port_base+port_span)) they
    will run against. Raises ValueError for cases this kernel can't
    realise (use case_reason first to report a skip instead)."""
    name, client_kind, server_kind, same_node, service = CASES[case_id]
    if service in ("nodeport", "nodeport6") and port_base <= 0:
        # Precondition check before the kernel probe: a caller bug, not
        # an environment limitation.
        raise ValueError(
            f"case {case_id} ({name}) programs exact nodePort DNAT pairs: "
            f"pass port_base/port_span for the engine ports you will use")
    reason = case_reason(case_id)
    if reason is not None:
        raise ValueError(f"case {case_id} unsupported locally: {reason}")
    tag = uuid.uuid4().hex[:5]
    cleanups: List = []
    try:
        return _build(case_id, name, client_kind, server_kind, same_node,
                      service, tag, cleanups, port_base, port_span or 1)
    except Exception:
        # A half-built topology must not leak bridges/netns on the host.
        for fn in reversed(cleanups):
            try:
                fn()
            except Exception:
                pass
        raise


def _build(case_id: int, name: str, client_kind: str, server_kind: str,
           same_node: bool, service: Optional[str], tag: str,
           cleanups: List, port_base: int, port_span: int) -> CaseTopology:
    mtu = _fabric_mtu()
    v6 = service == "nodeport6"

    bridge_a = "bta" + tag
    _run(["ip", "link", "add", bridge_a, "mtu", str(mtu), "type", "bridge"])
    cleanups.append(lambda: subprocess.run(
        ["ip", "link", "del", bridge_a], capture_output=True))
    _run(["ip", "link", "set", bridge_a, "up"])

    if same_node or server_kind == "external":
        bridge_b = bridge_a
    else:
        # "Node B" = a second bridge, fabric-linked to node A by a veth
        # uplink pair — cross-node traffic really transits both bridges.
        bridge_b = "btb" + tag
        _run(["ip", "link", "add", bridge_b, "mtu", str(mtu),
              "type", "bridge"])
        cleanups.append(lambda: subprocess.run(
            ["ip", "link", "del", bridge_b], capture_output=True))
        _run(["ip", "link", "set", bridge_b, "up"])
        up_a, up_b = "bua" + tag, "bub" + tag
        _run(["ip", "link", "add", up_a, "mtu", str(mtu),
              "type", "veth", "peer", "name", up_b, "mtu", str(mtu)])
        cleanups.append(lambda: subprocess.run(
            ["ip", "link", "del", up_a], capture_output=True))
        _run(["ip", "link", "set", up_a, "master", bridge_a])
        _run(["ip", "link", "set", up_b, "master", bridge_b])
        _run(["ip", "link", "set", up_a, "up"])
        _run(["ip", "link", "set", up_b, "up"])

    service_gw = service is not None and server_kind != "external"
    pod_gw = GW_IP if service_gw else None
    pod_gw6 = GW_IP6 if v6 else None

    # Address plan: hosts .1/.2, pods .11/.12 — one flat /24, the
    # flat-ICI L2 shape. v6 cases add the matching ULA /64.
    endpoints = {}  # role -> (netns or None, ip)
    host_ips_added = set()
    for role, kind, bridge, host_ip, pod_ip, idx in (
        ("client", client_kind, bridge_a, "10.94.0.1", "10.94.0.11", 0),
        ("server", server_kind, bridge_b, "10.94.0.2", "10.94.0.12", 1),
    ):
        if kind == "external":
            # Off-fabric: a routed (not bridged) veth on its own subnet;
            # the node forwards + masquerades pod egress toward it.
            ns = f"tx{idx}{tag}"
            ext_host, ext_peer = f"xh{idx}{tag}", f"xp{idx}{tag}"
            _run(["ip", "netns", "add", ns])
            cleanups.append(lambda n=ns: subprocess.run(
                ["ip", "netns", "del", n], capture_output=True))
            _run(["ip", "link", "add", ext_host, "type", "veth",
                  "peer", "name", ext_peer])
            cleanups.append(lambda l=ext_host: subprocess.run(
                ["ip", "link", "del", l], capture_output=True))
            _run(["ip", "link", "set", ext_peer, "netns", ns])
            _run(["ip", "addr", "add", f"{EXT_NET}.1/24", "dev", ext_host])
            _run(["ip", "link", "set", ext_host, "up"])
            _run(["ip", "-n", ns, "link", "set", ext_peer, "up"])
            _run(["ip", "-n", ns, "addr", "add", f"{EXT_NET}.2/24",
                  "dev", ext_peer])
            _run(["ip", "-n", ns, "route", "add", "default",
                  "via", f"{EXT_NET}.1"])
            endpoints[role] = (ns, f"{EXT_NET}.2")
        elif kind == "host" and role == "server" and not same_node:
            # "Node B's root namespace": a host endpoint in the SAME
            # (test-runner) netns as the client would satisfy the local
            # route table and short-circuit over loopback, never touching
            # the fabric. A remote node's root ns is a different ns, so
            # model it as one — its fabric interface rides bridge B.
            ns = f"tn{idx}{tag}"
            _pod(ns, f"th{idx}{tag}", f"tp{idx}{tag}", bridge, host_ip,
                 cleanups, mtu, ip6=HOST6[host_ip] if v6 else None)
            endpoints[role] = (ns, HOST6[host_ip] if v6 else host_ip)
        elif kind == "host":
            _run(["ip", "addr", "add", f"{host_ip}/24", "dev", bridge])
            cleanups.append(lambda b=bridge, ip=host_ip: subprocess.run(
                ["ip", "addr", "del", f"{ip}/24", "dev", b],
                capture_output=True))
            host_ips_added.add(host_ip)
            if v6:
                _run(["ip", "-6", "addr", "add", f"{HOST6[host_ip]}/64",
                      "dev", bridge, "nodad"])
                cleanups.append(lambda b=bridge, ip=HOST6[host_ip]:
                                subprocess.run(
                    ["ip", "-6", "addr", "del", f"{ip}/64", "dev", b],
                    capture_output=True))
            endpoints[role] = (None, HOST6[host_ip] if v6 else host_ip)
        else:
            ns = f"tc{idx}{tag}"
            _pod(ns, f"th{idx}{tag}", f"tp{idx}{tag}", bridge, pod_ip,
                 cleanups, mtu, ip6=POD6[pod_ip] if v6 else None,
                 gw=pod_gw, gw6=pod_gw6)
            endpoints[role] = (ns, POD6[pod_ip] if v6 else pod_ip)

    topo = CaseTopology(
        case_id=case_id,
        name=name,
        client_netns=endpoints["client"][0],
        server_netns=endpoints["server"][0],
        server_ip=endpoints["server"][1],
        _cleanups=cleanups,
    )
    if case_id == 15:
        topo.tags["path"] = "local-route"  # see module docstring
    if service is not None:
        _wire_service(topo, service, client_kind, bridge_a, endpoints,
                      host_ips_added, cleanups, port_base, port_span)
    return topo


def _wire_service(topo: CaseTopology, service: str, client_kind: str,
                  bridge_a: str, endpoints: Dict, host_ips_added: set,
                  cleanups: List, port_base: int, port_span: int) -> None:
    """The node-side scaffolding every service case shares: gateway
    address, forwarding, redirect suppression, and the NAT rule set."""
    from .serviceplane import ServicePlane

    v6 = service == "nodeport6"
    backend_ip = topo.server_ip
    tag = bridge_a[3:]

    if service != "external":
        # The node is the pods' default gateway — give bridge A the
        # gateway address unless a host endpoint already claimed it.
        if GW_IP not in host_ips_added:
            _run(["ip", "addr", "add", f"{GW_IP}/24", "dev", bridge_a])
            cleanups.append(lambda: subprocess.run(
                ["ip", "addr", "del", f"{GW_IP}/24", "dev", bridge_a],
                capture_output=True))
        if v6:
            # The node's v6 identity (nodePort target): host endpoints
            # only ever claim ::2 in the plan, so ::1 is always ours.
            _run(["ip", "-6", "addr", "add", f"{GW_IP6}/64",
                  "dev", bridge_a, "nodad"])
            cleanups.append(lambda: subprocess.run(
                ["ip", "-6", "addr", "del", f"{GW_IP6}/64", "dev", bridge_a],
                capture_output=True))

    _sysctl("/proc/sys/net/ipv4/ip_forward", "1", cleanups)
    _sysctl(f"/proc/sys/net/ipv4/conf/{bridge_a}/send_redirects", "0",
            cleanups)
    if v6:
        _sysctl("/proc/sys/net/ipv6/conf/all/forwarding", "1", cleanups)
    # Two-"node" emulation artifact: both bridges share ONE root netns,
    # so with br_netfilter active the routed-then-bridged packet
    # re-enters the ip prerouting path on bridge B carrying the node's
    # own source address (post-masquerade) and dies on the martian-
    # source check. Real clusters never bridge two nodes through one
    # conntrack domain; the service plane here rides the routed path
    # only, so bridge-nf-call is not needed — park it for the case.
    import os

    for knob in ("bridge-nf-call-iptables", "bridge-nf-call-ip6tables"):
        path = f"/proc/sys/net/bridge/{knob}"
        if os.path.exists(path):
            _sysctl(path, "0", cleanups)

    sp = ServicePlane(tag, v6=v6)
    cleanups.append(sp.close)

    if service == "clusterip":
        sp.add_clusterip(VIP, backend_ip)
        topo.connect_ip = VIP
        if client_kind == "host":
            # Host clients need an initial route for the VIP (the route
            # lookup precedes the output-hook DNAT; the kernel reroutes
            # after the rewrite).
            _run(["ip", "route", "add", f"{VIP}/32", "dev", bridge_a])
            cleanups.append(lambda: subprocess.run(
                ["ip", "route", "del", f"{VIP}/32", "dev", bridge_a],
                capture_output=True))
    elif service in ("nodeport", "nodeport6"):
        node_ip = GW_IP6 if v6 else GW_IP
        for port in range(port_base, port_base + port_span):
            sp.add_nodeport(node_ip, port + NODEPORT_OFFSET,
                            backend_ip, port)
        if endpoints["server"][0] is not None:
            sp.add_masquerade_to(backend_ip)
        topo.connect_ip = node_ip
        topo.port_offset = NODEPORT_OFFSET
    elif service == "external":
        # Egress SNAT: pod traffic leaves the fabric masqueraded as the
        # node; host traffic is already node-sourced.
        sp.add_masquerade_to(backend_ip)
        if client_kind == "pod":
            # Pods need a way off the fabric subnet.
            client_ns = endpoints["client"][0]
            _run(["ip", "addr", "add", f"{GW_IP}/24", "dev", bridge_a])
            cleanups.append(lambda: subprocess.run(
                ["ip", "addr", "del", f"{GW_IP}/24", "dev", bridge_a],
                capture_output=True))
            _run(["ip", "-n", client_ns, "route", "add", "default",
                  "via", GW_IP])
    topo.tags["service"] = service
