"""tft — traffic-flow tests for fabric-backed pod interfaces.

TPU-native replacement for the reference's kubernetes-traffic-flow-tests
submodule + hack/traffic_flow_tests.sh: same YAML config shape
(hack/cluster-configs/ocp-tft-config.yaml — connection list with
iperf-tcp / iperf-udp / netperf-tcp-stream / netperf-tcp-rr types,
per-test duration, secondary-network NAD), run either against two
existing netns (cluster mode would exec into pods; local mode execs into
the netns the CNI attached) with the engines in engine.py."""

from .tft import ConnectionSpec, TestSpec, load_config, run_connection, run_suite

__all__ = [
    "ConnectionSpec",
    "TestSpec",
    "load_config",
    "run_connection",
    "run_suite",
]
